"""AWS Kinesis provider (reference: pkg/providers/kinesis/ — replication
source).

Dependency-free client over the Kinesis JSON API (Kinesis_20131202.*)
with AWS SigV4 request signing (hashlib/hmac); composes the shared
QueueSource machinery — shards map to partitions, sequence numbers are the
offsets, and checkpoints flow through the coordinator like every queue
source.
"""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import json
import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.parsers import Message
from transferia_tpu.providers.queue_common import FetchedBatch, QueueSource
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)


class KinesisError(CategorizedError):
    def __init__(self, message: str, code: str = ""):
        super().__init__(CategorizedError.SOURCE, message)
        self.code = code


def sigv4_headers(method: str, host: str, path: str, body: bytes,
                  region: str, service: str, access_key: str,
                  secret_key: str, target: str,
                  now: Optional[datetime.datetime] = None) -> dict:
    """AWS Signature Version 4 for a JSON POST."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {
        "content-type": "application/x-amz-json-1.1",
        "host": host,
        "x-amz-date": amz_date,
        "x-amz-target": target,
    }
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        method, path, "",
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash,
    ])
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])

    def hm(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = hm(hm(hm(hm(b"AWS4" + secret_key.encode(), date_stamp),
                region), service), "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={signature}"
    )
    return headers


class KinesisClient:
    def __init__(self, region: str = "us-east-1",
                 access_key: str = "", secret_key: str = "",
                 endpoint: str = "", timeout: float = 60.0):
        import urllib.parse

        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        if endpoint:
            parsed = urllib.parse.urlparse(endpoint)
            self.host = parsed.hostname
            self.port = parsed.port or (
                443 if parsed.scheme == "https" else 80
            )
            self.secure = parsed.scheme == "https"
        else:
            self.host = f"kinesis.{region}.amazonaws.com"
            self.port = 443
            self.secure = True
        self.timeout = timeout

    def call(self, action: str, payload: dict) -> dict:
        import http.client

        body = json.dumps(payload).encode()
        target = f"Kinesis_20131202.{action}"
        # sign the Host header exactly as http.client transmits it: with
        # ":port" when non-default (custom endpoints, e.g. localstack)
        default_port = 443 if self.secure else 80
        signed_host = self.host if self.port == default_port \
            else f"{self.host}:{self.port}"
        headers = sigv4_headers(
            "POST", signed_host, "/", body, self.region, "kinesis",
            self.access_key, self.secret_key, target,
        )
        cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        conn = cls(self.host, self.port, timeout=self.timeout)
        try:
            conn.request("POST", "/", body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                obj = json.loads(data) if data else {}
            except ValueError:
                # proxies/LBs answer errors with HTML bodies
                obj = {"message": data[:200].decode("utf-8", "replace")}
            if resp.status != 200:
                raise KinesisError(
                    obj.get("message", f"HTTP {resp.status}"),
                    code=obj.get("__type", ""),
                )
            return obj
        except (ConnectionError, OSError) as e:
            raise KinesisError(f"kinesis unreachable: {e}") from e
        finally:
            conn.close()

    def list_shards(self, stream: str) -> list[str]:
        # page on NextToken: streams wider than one page (100 shards)
        # would otherwise silently lose shards (never replicated)
        shards: list[str] = []
        req: dict = {"StreamName": stream}
        while True:
            out = self.call("ListShards", req)
            shards.extend(s["ShardId"] for s in out.get("Shards", []))
            token = out.get("NextToken")
            if not token:
                return sorted(shards)
            # per API: NextToken must be the only parameter besides limit
            req = {"NextToken": token}

    def shard_iterator(self, stream: str, shard: str,
                      after_sequence: Optional[str] = None,
                      latest: bool = False) -> str:
        req = {"StreamName": stream, "ShardId": shard}
        if after_sequence:
            req["ShardIteratorType"] = "AFTER_SEQUENCE_NUMBER"
            req["StartingSequenceNumber"] = after_sequence
        else:
            req["ShardIteratorType"] = "LATEST" if latest \
                else "TRIM_HORIZON"
        return self.call("GetShardIterator", req)["ShardIterator"]

    def get_records(self, iterator: str, limit: int = 1000) -> dict:
        return self.call("GetRecords",
                         {"ShardIterator": iterator, "Limit": limit})


@register_endpoint
@dataclass
class KinesisSourceParams(EndpointParams):
    PROVIDER = "kinesis"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    stream: str = ""
    region: str = "us-east-1"
    access_key: str = ""
    secret_key: str = ""
    endpoint: str = ""            # custom endpoint (localstack etc.)
    parser: Optional[dict] = None
    parallelism: int = 4
    start_from: str = "earliest"  # earliest | latest

    def __post_init__(self):
        if self.start_from not in ("earliest", "latest"):
            raise ValueError(
                f"kinesis start_from must be 'earliest' or 'latest', "
                f"got {self.start_from!r}"
            )

    def parser_config(self):
        return self.parser


class _KinesisQueueClient:
    """QueueSource client: shard ids index into a stable partition list;
    sequence numbers checkpoint through the coordinator."""

    STATE_KEY = "kinesis_sequences"

    def __init__(self, params: KinesisSourceParams, transfer_id: str,
                 coordinator: Optional[Coordinator]):
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.client = KinesisClient(
            region=params.region, access_key=params.access_key,
            secret_key=params.secret_key, endpoint=params.endpoint,
        )
        self.shards = self.client.list_shards(params.stream)
        if not self.shards:
            raise KinesisError(f"stream {params.stream!r} has no shards")
        saved = {}
        if self.cp is not None:
            saved = self.cp.get_transfer_state(transfer_id).get(
                self.STATE_KEY, {}
            )
        self.iterators: dict[str, str] = {}
        self._last_poll: dict[str, float] = {}
        # last sequence seen/committed per shard — iterator rebuild point
        # when a shard iterator expires (~5 min TTL)
        self._last_seq: dict[str, Optional[str]] = {
            s: saved.get(s) for s in self.shards
        }
        # shards whose INITIAL iterator was LATEST — only those may
        # rebuild as LATEST; reshard children start TRIM_HORIZON and must
        # never skip to the tip on an expired-iterator rebuild
        self._latest_start: set[str] = set(
            s for s in self.shards
            if saved.get(s) is None and params.start_from == "latest"
        )
        self._closed: set[str] = set()  # drained parents post-reshard
        # virtual offset per shard: a dense int the sequencer can order;
        # the real checkpoint token is the sequence number
        self.offsets: dict[str, int] = {s: 0 for s in self.shards}
        self.sequences: dict[str, dict[int, str]] = {
            s: {} for s in self.shards
        }
        for s in self.shards:
            seq = saved.get(s)
            self.iterators[s] = self.client.shard_iterator(
                params.stream, s, after_sequence=seq,
                latest=params.start_from == "latest",
            )

    MIN_POLL_INTERVAL = 0.25  # AWS allows 5 reads/sec/shard; stay under

    def _refresh_shards(self) -> None:
        """Pick up reshard children (a closed shard's iterator goes empty).

        self.shards only ever appends — partition indices must stay stable
        for the sequencer's (topic, partition) bookkeeping."""
        for shard in self.client.list_shards(self.params.stream):
            if shard not in self.offsets:
                logger.info("kinesis reshard: new shard %s", shard)
                self.shards.append(shard)
                self.offsets[shard] = 0
                self.sequences[shard] = {}
                self.iterators[shard] = self.client.shard_iterator(
                    self.params.stream, shard,
                )

    def fetch(self, max_messages: int = 1024) -> list[FetchedBatch]:
        import time as _time

        out = []
        drained = [s for s, it in self.iterators.items()
                   if not it and s not in self._closed]
        if drained:
            # a closed shard's iterator goes empty exactly once: look for
            # reshard children then mark it closed so the steady-state
            # fetch loop doesn't re-issue ListShards forever
            self._refresh_shards()
            self._closed.update(drained)
        now = _time.monotonic()
        for idx, shard in enumerate(self.shards):
            it = self.iterators.get(shard)
            if not it:
                continue
            if now - self._last_poll.get(shard, 0.0) \
                    < self.MIN_POLL_INTERVAL:
                continue
            self._last_poll[shard] = now
            try:
                resp = self.client.get_records(it, limit=max_messages)
            except KinesisError as e:
                if "ExpiredIterator" in e.code:
                    # shard iterators expire after ~5 min; re-acquire from
                    # the last seen sequence instead of wedging the shard
                    logger.info(
                        "kinesis shard %s iterator expired; rebuilding",
                        shard)
                    self.iterators[shard] = self.client.shard_iterator(
                        self.params.stream, shard,
                        after_sequence=self._last_seq.get(shard),
                        latest=(self._last_seq.get(shard) is None
                                and shard in self._latest_start),
                    )
                    continue
                raise
            self.iterators[shard] = resp.get("NextShardIterator") or ""
            records = resp.get("Records", [])
            if not records:
                continue
            msgs = []
            for r in records:
                off = self.offsets[shard]
                self.offsets[shard] = off + 1
                self.sequences[shard][off] = r["SequenceNumber"]
                self._last_seq[shard] = r["SequenceNumber"]
                msgs.append(Message(
                    value=base64.b64decode(r["Data"]),
                    key=r.get("PartitionKey", "").encode(),
                    topic=self.params.stream,
                    partition=idx,
                    offset=off,
                    write_time_ns=int(float(
                        r.get("ApproximateArrivalTimestamp", 0)
                    ) * 1e9),
                ))
            out.append(FetchedBatch(self.params.stream, idx, msgs))
        return out

    def commit(self, topic: str, partition: int, offset: int) -> None:
        if self.cp is None:
            return
        shard = self.shards[partition]
        seqs = self.sequences[shard]
        seq = seqs.get(offset)
        if seq is None:
            return
        # drop tokens at/below the committed offset
        for o in [o for o in seqs if o <= offset]:
            if o != offset:
                seqs.pop(o, None)
        state = self.cp.get_transfer_state(self.transfer_id).get(
            self.STATE_KEY, {}
        )
        state[shard] = seq
        self.cp.set_transfer_state(self.transfer_id,
                                   {self.STATE_KEY: state})

    def close(self) -> None:
        pass


@register_provider
class KinesisProvider(Provider):
    NAME = "kinesis"

    def source(self):
        if isinstance(self.transfer.src, KinesisSourceParams):
            p = self.transfer.src
            client = _KinesisQueueClient(p, self.transfer.id,
                                         self.coordinator)
            return QueueSource(client, p.parser,
                               parallelism=p.parallelism,
                               metrics=self.metrics,
                               transfer_id=self.transfer.id)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        p = self.transfer.src
        try:
            KinesisClient(
                region=p.region, access_key=p.access_key,
                secret_key=p.secret_key, endpoint=p.endpoint,
            ).list_shards(p.stream)
            result.add("list_shards")
        except Exception as e:
            result.add("list_shards", e)
        return result
