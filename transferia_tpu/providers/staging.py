"""Shared staging machinery for staged-commit sinks.

Every capable sink (memory, fs, arrow_ipc, flight, mq) drives its
stage → publish lifecycle through this module so the protocol behaves
identically across targets:

- `PartStage` is one part's staging state: the `(key, epoch)` identity,
  an optional in-memory batch buffer (sinks that publish from memory —
  memory/flight/mq — hold; file-backed sinks write through to a staging
  directory and only route batches through `stage()` for the dedup
  window), and the torn-write **dedup window**;
- the dedup window drops REPLAYED torn-write prefixes before publish.
  A replay is recognized by two independent signals, both required:
  the retry layer ARMS the window (`StagedSinker.note_push_retry`,
  called by the sink Retrier before re-pushing a failed batch — only a
  failure can cause a replay), and the incoming batch's row-key
  sequence (`ops/rowhash.batch_row_keys`, the same dict-native lanes
  the chaos auditor and table fingerprints use) starts with the
  previously staged push's key sequence in ORDER — a torn write lands
  a batch prefix, so its retry replays that exact ordered prefix.
  Only the matched prefix drops.  Mere content equality never drops:
  genuinely duplicate rows across batches of one part (PK-less
  sources, constant-valued tables emitting identical consecutive
  batches) are source multiplicity, not replay, and pass through
  untouched;
- `EpochFence` is the sink-side publish fence: the last accepted
  publish epoch per part key; an older epoch raises
  `StaleEpochPublishError` (zombie publish), an equal-or-newer epoch
  replaces (idempotent republish / superseding owner);
- `publish_guard` wraps every publish with the `sink.publish`
  failpoint and a trace span; `PartStage.stage` owns `sink.stage` —
  single call sites, per the FPT001 one-owner contract.
"""

from __future__ import annotations

import threading
from typing import Optional

from transferia_tpu.abstract.errors import StaleEpochPublishError
from transferia_tpu.abstract.interfaces import Batch, is_columnar
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.stats import trace
from transferia_tpu.stats.ledger import LEDGER

# cap on the row keys remembered from the last staged push (the only
# state a replay can match against).  Batches are bufferer-flush sized
# in practice; a push beyond the cap simply stops dedup-matching (safe
# direction: duplicates land and the at-least-once bound covers them).
DEDUP_WINDOW_ROWS = 1 << 20


class DedupWindow:
    """Torn-write replay detector over one part's staged pushes.

    A torn write lands a PREFIX of a batch, then the push errors and
    the sink-level Retrier re-pushes the WHOLE batch.  The replay is
    recognized only when BOTH hold:

    - the window was ARMED (`arm_replay`) since the last row push —
      the Retrier arms it before every re-push, so an unarmed push can
      never be a replay (nothing failed);
    - the incoming batch's key sequence STARTS WITH the previously
      staged push's key sequence, in order — the landed prefix.

    Exactly the matched prefix is dropped.  Content membership alone
    never drops anything: identical rows arriving in different batches
    (PK-less duplicates, constant-valued tables) are legitimate source
    multiplicity and exactly-once must preserve them."""

    def __init__(self, max_rows: int = DEDUP_WINDOW_ROWS):
        self.max_rows = max_rows
        self._prev = None  # np.uint64 keys of the last staged row push
        self._armed = False

    def arm_replay(self) -> None:
        """The next row push is a retry of a failed one (Retrier)."""
        self._armed = True

    def filter(self, batch: "Batch") -> tuple["Batch", int]:
        """Drop the replayed prefix when this push is a recognized
        replay, else pass through.  Returns (batch, rows dropped).
        Control items pass through untouched (and do not consume the
        armed flag) — they carry no row identity."""
        import numpy as np

        keys = _row_keys(batch)
        if keys is None or len(keys) == 0:
            return batch, 0
        armed, self._armed = self._armed, False
        prev = self._prev
        dropped = 0
        if armed and prev is not None and 0 < len(prev) <= len(keys) \
                and np.array_equal(keys[:len(prev)], prev):
            dropped = int(len(prev))
            batch = _drop_prefix(batch, dropped)
        # remember THIS push in full (a later tear replays it whole,
        # dropped prefix included), up to the cap
        self._prev = keys if len(keys) <= self.max_rows else None
        return batch, dropped


def _row_keys(batch: "Batch"):
    """Content keys (np.uint64, row order) for a pushed batch; None =
    no row content."""
    from transferia_tpu.columnar.batch import ColumnBatch
    from transferia_tpu.ops.rowhash import batch_row_keys

    if is_columnar(batch):
        if batch.n_rows == 0:
            return None
        return batch_row_keys(batch)
    rows = [it for it in batch if it.is_row_event()]
    if not rows or len(rows) != len(batch):
        # mixed/control batch: NonRowSeparator upstream makes this rare;
        # pass through rather than misattribute identities
        return None
    return batch_row_keys(ColumnBatch.from_rows(rows))


def _drop_prefix(batch: "Batch", k: int) -> "Batch":
    if is_columnar(batch):
        return batch.slice(k, batch.n_rows)
    return batch[k:]


class PartStage:
    """One part's staging state inside a sink.

    `hold=True` buffers staged batches in memory (sinks that publish
    from the buffer); `hold=False` only runs the dedup window and
    returns the filtered batch for the sink to persist into its own
    staging area (file-backed sinks)."""

    def __init__(self, key: str, epoch: int, hold: bool = True,
                 dedup_rows: int = DEDUP_WINDOW_ROWS):
        self.key = key
        self.epoch = epoch
        self.hold = hold
        self.batches: list[Batch] = []
        self.rows = 0
        self.dedup_dropped = 0
        self.poisoned = False
        self._window = DedupWindow(dedup_rows)

    def mark_failed(self) -> None:
        """Poison the stage after a failure DOWNSTREAM of the dedup
        window (staging write / serialization died after the window
        recorded the batch's keys).  The staged state is then unknown —
        a push-level retry against this stage could silently drop the
        unwritten suffix (its keys are already in the window), so every
        further stage() fails until the PART retries and `begin_part`
        replaces the whole stage."""
        self.poisoned = True

    def note_push_retry(self) -> None:
        """The sink Retrier is about to re-push a failed batch: arm
        the dedup window so the replayed prefix (if one landed) is
        recognized and dropped."""
        self._window.arm_replay()

    def stage(self, batch: "Batch") -> "Batch":
        """Route one pushed batch through the staging plane: fire the
        `sink.stage` failpoint (a fault here must fail the push with
        nothing newly visible), dedup against the window, account, and
        (when holding) buffer."""
        if self.poisoned:
            raise ConnectionError(
                f"stage for {self.key!r} poisoned by an earlier staging "
                f"failure; the part must restage from scratch")
        failpoint("sink.stage")
        sp = trace.span("sink_stage", part=self.key, epoch=self.epoch)
        with sp:
            batch, dropped = self._window.filter(batch)
            if dropped:
                self.dedup_dropped += dropped
                LEDGER.add(dedup_rows_dropped=dropped)
            n = batch.n_rows if is_columnar(batch) else sum(
                1 for it in batch if it.is_row_event())
            self.rows += n
            if sp:
                sp.add(rows=n, dedup_dropped=dropped)
            if self.hold:
                self.batches.append(batch)
        return batch


class publish_guard:
    """Context manager every `publish_part` implementation enters:
    fires the `sink.publish` failpoint (a fault here must leave the
    target either fully unpublished or fully replaced — never torn) and
    records the publish as a trace span."""

    def __init__(self, key: str, epoch: int):
        self._sp = trace.span("sink_publish", part=key, epoch=epoch)
        failpoint("sink.publish")

    def __enter__(self):
        self._sp.__enter__()
        return self._sp

    def __exit__(self, *exc):
        return self._sp.__exit__(*exc)


def part_slug(key: str) -> str:
    """Filesystem/wire-safe stable identity for a part key (used in
    published file names and Flight part keys, so replacement can find
    an older publish of the same part regardless of epoch/token)."""
    import re

    return re.sub(r"[^A-Za-z0-9._-]", "_", key)


# -- wire-target staging conventions -----------------------------------------
#
# The five wire sinks (postgres, clickhouse, ydb, kafka, s3 objects)
# share one naming scheme so an operator can recognize transferia's
# transient state in any target:
#
# - `__trtpu_` prefixes every object the staged-commit plane creates in
#   a target (staging tables, the commit fence table, the hidden part
#   column).  Target-side READ paths (destination storages) hide these:
#   a checksum of the delivered table never sees the machinery;
# - `__trtpu_commits` is the per-target fence table: one row per part
#   key carrying the last accepted publish epoch — the persisted twin
#   of staging.EpochFence, so the fence survives sink restarts and
#   fences ZOMBIE PROCESSES, not just stale objects;
# - `__trtpu_part` is the hidden part-identity column database sinks
#   add to the final table: "publish replaces" needs per-row part
#   identity a DELETE/REPLACE PARTITION can address.

META_PREFIX = "__trtpu"
META_COLUMN = "__trtpu_part"
COMMITS_TABLE = "__trtpu_commits"


def is_meta_name(name: str) -> bool:
    """True for identifiers owned by the staging plane (hidden from
    destination-storage reads)."""
    return name.startswith(META_PREFIX)


def stage_ident_prefix(key: str, prefix: str = "__trtpu_stg_") -> str:
    """Identifier prefix shared by ALL epochs' staging tables of one
    part key — `begin_part` enumerates tables under it to sweep the
    leftovers of crashed earlier attempts (a steal bumps the epoch, so
    the crashed owner's staging would otherwise leak forever)."""
    import hashlib

    h = hashlib.sha1(key.encode()).hexdigest()[:12]
    return f"{prefix}{h}_e"


def stage_ident(key: str, epoch: int, prefix: str = "__trtpu_stg_") -> str:
    """Short, identifier-safe staging-table name for (part key, epoch).

    Hash-based: part keys embed operation ids and table fqtns that
    overflow identifier limits (postgres truncates at 63 bytes, which
    would silently collide two parts).  The epoch is IN the identity —
    as a readable suffix on the key hash: a zombie and the survivor
    that stole its part stage side by side and never clobber each
    other's staging area, while any owner can ENUMERATE every epoch's
    staging for the key (stage_ident_prefix) to sweep crashed
    attempts' leftovers."""
    return f"{stage_ident_prefix(key, prefix)}{epoch}"


class WireStage:
    """One part's staging state inside a wire sink (pg/ch/ydb/s3
    share it): the (key, epoch) identity, its slug and staging-table
    ident, the dedup-window PartStage, and the first staged batch's
    table/schema (wire sinks learn the shape from the data)."""

    def __init__(self, key: str, epoch: int):
        self.key = key
        self.epoch = epoch
        self.slug = part_slug(key)
        self.table = stage_ident(key, epoch)
        self.state = PartStage(key, epoch, hold=False)
        self.tid = None
        self.schema = None


class DirectoryPartStage:
    """File-backed staging for directory sinks (fs, arrow_ipc).

    An inner sink instance writes the part's batches into
    `<root>/.staging/<slug>/` (dotdir: invisible to the storage
    readers' globs); `publish` renames the staged files into `<root>`
    under part-keyed names (`<staged name>.part-<slug>.<ext>`),
    REPLACING any files a previous publish of the same part landed,
    behind a marker-file epoch fence
    (`<root>/.staging/.published.<slug>.json`)."""

    def __init__(self, root: str, key: str, epoch: int, make_inner,
                 dedup_rows: int = DEDUP_WINDOW_ROWS):
        import os
        import shutil

        import re as _re

        self.root = root
        self.key = key
        self.epoch = epoch
        self.slug = part_slug(key)
        # staging dir carries the epoch: a zombie and the survivor that
        # reclaimed its part stage side by side and never clobber each
        # other — only the fenced publish decides whose files land
        self.dir = os.path.join(root, ".staging",
                                f"{self.slug}.e{epoch}")
        # begin replaces — for EVERY epoch of this key: a crashed
        # earlier owner's staging dir (different epoch, different
        # name) would otherwise leak forever.  Exact-match the epoch
        # suffix so a dotted sibling slug can never be swept.
        staging_root = os.path.join(root, ".staging")
        pat = _re.compile(rf"^{_re.escape(self.slug)}\.e\d+$")
        try:
            leftovers = os.listdir(staging_root)
        except OSError:
            leftovers = []
        for name in leftovers:
            if pat.match(name):
                shutil.rmtree(os.path.join(staging_root, name),
                              ignore_errors=True)
        os.makedirs(self.dir, exist_ok=True)
        self.inner = make_inner(self.dir)
        self.state = PartStage(key, epoch, hold=False,
                               dedup_rows=dedup_rows)
        self._closed = False

    def push(self, batch: "Batch") -> None:
        staged = self.state.stage(batch)
        try:
            self.inner.push(staged)
        except BaseException:
            # the staging write died after the dedup window recorded
            # this batch: the staged files hold an unknown prefix, so
            # only a full part restage (begin replaces) is safe
            self.state.mark_failed()
            raise

    def note_push_retry(self) -> None:
        self.state.note_push_retry()

    def _marker(self) -> str:
        import os

        return os.path.join(self.root, ".staging",
                            f".published.{self.slug}.json")

    def publish(self) -> int:
        import json
        import os

        with publish_guard(self.key, self.epoch):
            if not self._closed:
                self.inner.close()
                self._closed = True
            # sink-side epoch fence, persisted next to the staging area
            marker = self._marker()
            try:
                with open(marker) as fh:
                    prev = int(json.load(fh).get("epoch", -1))
            except (FileNotFoundError, ValueError, OSError):
                prev = None
            if prev is not None and self.epoch < prev:
                raise StaleEpochPublishError(self.key, self.epoch, prev)
            # replace: drop what an older publish of this part landed
            infix = f".part-{self.slug}."
            for fname in os.listdir(self.root):
                if infix in fname:
                    os.remove(os.path.join(self.root, fname))
            # then move the staged files in (same-fs atomic renames; a
            # crash mid-loop is recovered by the retried part's
            # republish, which replaces everything again)
            for fname in sorted(os.listdir(self.dir)):
                stem, dot, ext = fname.rpartition(".")
                out = f"{stem}{infix}{ext}" if dot else \
                    f"{fname}{infix}dat"
                os.replace(os.path.join(self.dir, fname),
                           os.path.join(self.root, out))
            tmp = f"{marker}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump({"epoch": self.epoch, "key": self.key}, fh)
            os.replace(tmp, marker)
            os.rmdir(self.dir)
            return self.state.rows

    def abort(self) -> None:
        import shutil

        if not self._closed:
            try:
                self.inner.close()
            except Exception:  # trtpu: ignore[EXC001] — best-effort abort
                pass
            self._closed = True
        shutil.rmtree(self.dir, ignore_errors=True)


class EpochFence:
    """Sink-side publish fence: last accepted publish epoch per key.

    `check_and_advance` rejects epochs OLDER than the last accepted
    publish (zombie) and records the new epoch otherwise; equal epochs
    pass (idempotent republish — the publish itself replaces)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._published: dict[str, int] = {}

    def check_and_advance(self, key: str, epoch: int) -> Optional[int]:
        """Returns the previously published epoch (None = first
        publish) or raises StaleEpochPublishError."""
        with self._lock:
            prev = self._published.get(key)
            if prev is not None and epoch < prev:
                raise StaleEpochPublishError(key, epoch, prev)
            self._published[key] = epoch
            return prev

    def published_epoch(self, key: str) -> Optional[int]:
        with self._lock:
            return self._published.get(key)
