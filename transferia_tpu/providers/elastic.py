"""Elasticsearch / OpenSearch provider.

Reference parity: pkg/providers/elastic/ + opensearch/ — index dump
(snapshot via scroll/search_after) and restore (bulk indexing).  Pure
stdlib HTTP against the REST API; the same implementation registers under
both provider names (the reference's opensearch provider delegates to
elastic the same way).

Real-service behaviors intentionally NOT covered (the fakes mirror
what is implemented, so e2e cannot prove these): deep mapping-edge
handling (multi-fields, nested/join datatypes, dynamic-template
interactions flatten to ANY), index aliases/rollover during a dump, and
cross-version mapping migrations — schemas derive from the top-level
property types only.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Optional

import http.client
import urllib.parse

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)


class ESError(CategorizedError):
    pass


class ESClient:
    def __init__(self, host: str, port: int, user: str = "",
                 password: str = "", secure: bool = False,
                 timeout: float = 120.0):
        self.host = host
        self.port = port
        self.user = user
        self.password = password
        self.secure = secure
        self.timeout = timeout

    def request(self, method: str, path: str,
                body: Optional[object] = None,
                raw_body: Optional[bytes] = None,
                content_type: str = "application/json") -> dict:
        cls = http.client.HTTPSConnection if self.secure \
            else http.client.HTTPConnection
        conn = cls(self.host, self.port, timeout=self.timeout)
        try:
            headers = {"Content-Type": content_type}
            if self.user:
                import base64

                cred = base64.b64encode(
                    f"{self.user}:{self.password}".encode()
                ).decode()
                headers["Authorization"] = f"Basic {cred}"
            payload = raw_body if raw_body is not None else (
                json.dumps(body).encode() if body is not None else None
            )
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 300:
                raise ESError(
                    CategorizedError.TARGET,
                    f"elastic HTTP {resp.status}: {data[:300].decode('utf-8', 'replace')}",
                )
            return json.loads(data) if data else {}
        except (ConnectionError, OSError, http.client.HTTPException) as e:
            raise ESError(CategorizedError.TARGET,
                          f"elastic connection failed: {e}") from e
        finally:
            conn.close()


def _es_params(provider_name: str):
    @dataclass
    class SourceParams(EndpointParams):
        PROVIDER = provider_name
        IS_SOURCE = True

        host: str = "localhost"
        port: int = 9200
        user: str = ""
        password: str = ""
        secure: bool = False
        index: str = ""            # empty = all non-system indices
        batch_rows: int = 5_000

    @dataclass
    class TargetParams(EndpointParams):
        PROVIDER = provider_name
        IS_TARGET = True

        host: str = "localhost"
        port: int = 9200
        user: str = ""
        password: str = ""
        secure: bool = False

    SourceParams.__name__ = f"{provider_name.title()}SourceParams"
    TargetParams.__name__ = f"{provider_name.title()}TargetParams"
    return register_endpoint(SourceParams), register_endpoint(TargetParams)


ElasticSourceParams, ElasticTargetParams = _es_params("elastic")
OpenSearchSourceParams, OpenSearchTargetParams = _es_params("opensearch")

DOC_SCHEMA = TableSchema([
    ColSchema("_id", CanonicalType.UTF8, primary_key=True),
    ColSchema("_index", CanonicalType.UTF8),
    ColSchema("doc", CanonicalType.ANY),
])


class ESStorage(Storage):
    """Index dump via search_after pagination (PIT-less, sorted on _id)."""

    def __init__(self, params):
        self.params = params
        self.client = ESClient(params.host, params.port, params.user,
                               params.password, params.secure)

    def _indices(self) -> list[str]:
        if self.params.index:
            return [self.params.index]
        out = self.client.request("GET", "/_cat/indices?format=json")
        return sorted(
            r["index"] for r in out if not r["index"].startswith(".")
        )

    def table_list(self, include=None):
        tables = {}
        for idx in self._indices():
            tid = TableID("", idx)
            if include and not any(tid.include_matches(p) for p in include):
                continue
            count = self.client.request("GET", f"/{idx}/_count").get(
                "count", 0
            )
            tables[tid] = TableInfo(eta_rows=int(count), schema=DOC_SCHEMA)
        return tables

    def table_schema(self, table: TableID) -> TableSchema:
        return DOC_SCHEMA

    def exact_table_rows_count(self, table: TableID) -> int:
        return int(self.client.request(
            "GET", f"/{table.name}/_count"
        ).get("count", 0))

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        search_after = None
        while True:
            body = {
                "size": self.params.batch_rows,
                "sort": [{"_id": "asc"}],
                "query": {"match_all": {}},
            }
            if search_after is not None:
                body["search_after"] = search_after
            out = self.client.request(
                "POST", f"/{table.id.name}/_search", body
            )
            hits = out.get("hits", {}).get("hits", [])
            if not hits:
                return
            batch = ColumnBatch.from_pydict(table.id, DOC_SCHEMA, {
                "_id": [h["_id"] for h in hits],
                "_index": [h["_index"] for h in hits],
                "doc": [h["_source"] for h in hits],
            })
            pusher(batch)
            search_after = hits[-1]["sort"]
            if len(hits) < self.params.batch_rows:
                return

    def ping(self) -> None:
        self.client.request("GET", "/")


class ESSinker(Sinker):
    """Bulk indexing sink: doc-shaped batches index their `doc` column;
    arbitrary tables index the whole row as the document."""

    def __init__(self, params):
        self.params = params
        self.client = ESClient(params.host, params.port, params.user,
                               params.password, params.secure)

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        index = batch.table_id.name.lower()
        data = batch.to_pydict()
        keys = [c.name for c in batch.schema.key_columns()]
        lines = []
        for i in range(batch.n_rows):
            if "doc" in data and "_id" in data:
                doc_id = data["_id"][i]
                doc = data["doc"][i]
            else:
                doc = {
                    k: (v[i].decode("utf-8", "replace")
                        if isinstance(v[i], bytes) else v[i])
                    for k, v in data.items()
                }
                doc_id = "_".join(str(data[k][i]) for k in keys) \
                    if keys else None
            action = {"index": {"_index": index}}
            if doc_id is not None:
                action["index"]["_id"] = str(doc_id)
            lines.append(json.dumps(action, default=str))
            lines.append(json.dumps(doc, default=str))
        payload = ("\n".join(lines) + "\n").encode()
        out = self.client.request(
            "POST", "/_bulk", raw_body=payload,
            content_type="application/x-ndjson",
        )
        if out.get("errors"):
            first = next(
                (item["index"].get("error")
                 for item in out.get("items", [])
                 if item.get("index", {}).get("error")),
                "unknown",
            )
            raise ESError(CategorizedError.TARGET,
                          f"bulk indexing failed: {first}")


def _make_provider(name: str, src_cls, dst_cls):
    class _Provider(Provider):
        NAME = name

        def storage(self):
            if isinstance(self.transfer.src, src_cls):
                return ESStorage(self.transfer.src)
            return None

        def sinker(self):
            if isinstance(self.transfer.dst, dst_cls):
                return ESSinker(self.transfer.dst)
            return None

        def test(self) -> TestResult:
            result = TestResult(ok=True)
            params = self.transfer.src if isinstance(
                self.transfer.src, src_cls) else self.transfer.dst
            try:
                ESClient(params.host, params.port, params.user,
                         params.password, params.secure).request("GET", "/")
                result.add("ping")
            except Exception as e:
                result.add("ping", e)
            return result

    _Provider.__name__ = f"{name.title()}Provider"
    return register_provider(_Provider)


ElasticProvider = _make_provider("elastic", ElasticSourceParams,
                                 ElasticTargetParams)
OpenSearchProvider = _make_provider("opensearch", OpenSearchSourceParams,
                                    OpenSearchTargetParams)
