"""Greenplum provider (reference: pkg/providers/greenplum/).

Greenplum speaks the PostgreSQL protocol; the provider specializes the PG
storage with segment-parallel reads: `gp_segment_id` partitions a table
across segments, so shard_table emits one part per segment (the
reference's segment-parallel snapshot, referenced directly by the
snapshot loader, load_snapshot.go:23).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.models.endpoint import register_endpoint
from transferia_tpu.providers.postgres.provider import (
    PGSinker,
    PGSourceParams,
    PGStorage,
    PGTargetParams,
)
from transferia_tpu.providers.postgres.wire import PGError
from transferia_tpu.providers.registry import Provider, register_provider

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class GPSourceParams(PGSourceParams):
    PROVIDER = "greenplum"

    segment_parallel: bool = True


@register_endpoint
@dataclass
class GPTargetParams(PGTargetParams):
    PROVIDER = "greenplum"


class GPStorage(PGStorage):
    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        if not getattr(self.params, "segment_parallel", True):
            return super().shard_table(table)
        try:
            n_segments = int(self.conn.scalar(
                "SELECT count(*) FROM gp_segment_configuration "
                "WHERE role = 'p' AND content >= 0"
            ) or 0)
        except PGError:
            # not actually a Greenplum cluster: plain-PG ctid split
            return super().shard_table(table)
        if n_segments <= 1:
            return [table]
        return [
            TableDescription(
                id=table.id,
                filter=f"gp_segment_id = {seg}",
                eta_rows=table.eta_rows // n_segments,
            )
            for seg in range(n_segments)
        ]


@register_provider
class GreenplumProvider(Provider):
    NAME = "greenplum"

    def storage(self):
        if isinstance(self.transfer.src, GPSourceParams):
            return GPStorage(self.transfer.src)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, GPTargetParams):
            return PGSinker(self.transfer.dst)
        return None
