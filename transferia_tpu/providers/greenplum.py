"""Greenplum provider (reference: pkg/providers/greenplum/).

Greenplum speaks the PostgreSQL protocol; the provider specializes the PG
storage two ways:

  - segment-parallel reads THROUGH THE MASTER: `gp_segment_id`
    partitions a table across segments, one part per segment
    (load_snapshot.go:23) — correct everywhere, but every byte rides
    the master connection
  - the gpfdist SEGMENT-DIRECT path (gpfdist_storage.go /
    gpfdist_sink.go): the worker runs an in-process gpfdist endpoint
    (providers/gpfdist.py) and the master only executes CREATE EXTERNAL
    TABLE + INSERT...SELECT control statements; the DATA flows straight
    between the segments and the worker over HTTP, which is what makes
    Greenplum bulk load/unload fast

Unload: CREATE WRITABLE EXTERNAL TABLE (LIKE src) LOCATION
('gpfdist://worker/slot'); INSERT INTO ext SELECT * FROM src — segments
POST their rows as CSV to the worker, which decodes through the same CSV
-> ColumnBatch path as PG COPY.  Load: CREATE READABLE EXTERNAL TABLE
(LIKE target); INSERT INTO target SELECT * FROM ext — segments GET CSV
chunks from the worker.  Filtered parts (predicate pushdown) keep the
master path: gpfdist transfers are whole-table.

Real-service behaviors intentionally NOT covered (FakeGP plays the
segment side of the protocol, so e2e cannot prove these): the gpfdist
TLS variant (gpfdists://), segment-host liveness monitoring during a
long transfer (reference liveness_monitor.go restarts stalled
segments), and multi-NIC worker addressing — gpfdist_host is a single
address all segments must reach.
"""

from __future__ import annotations

import io
import logging
import threading
import uuid
from dataclasses import dataclass

from transferia_tpu.abstract.interfaces import Pusher, is_columnar
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import register_endpoint
from transferia_tpu.providers.gpfdist import GpfdistServer
from transferia_tpu.providers.postgres.provider import (
    PGSinker,
    PGSourceParams,
    PGStorage,
    PGTargetParams,
    _conn,
)
from transferia_tpu.providers.postgres.wire import PGError
from transferia_tpu.providers.registry import Provider, register_provider

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class GPSourceParams(PGSourceParams):
    PROVIDER = "greenplum"

    segment_parallel: bool = True
    # segment-direct unload through an in-process gpfdist endpoint
    gpfdist: bool = False
    gpfdist_host: str = "127.0.0.1"   # address segments can reach


@register_endpoint
@dataclass
class GPTargetParams(PGTargetParams):
    PROVIDER = "greenplum"

    # segment-direct load through an in-process gpfdist endpoint
    gpfdist: bool = False
    gpfdist_host: str = "127.0.0.1"


class GPStorage(PGStorage):
    def _segment_count(self) -> int:
        try:
            return int(self.conn.scalar(
                "SELECT count(*) FROM gp_segment_configuration "
                "WHERE role = 'p' AND content >= 0"
            ) or 0)
        except PGError:
            return 0  # not actually a Greenplum cluster

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        if self.params.gpfdist:
            # one gpfdist transfer moves the whole table with the
            # segments as the parallel axis: no part fan-out needed
            return [table]
        if not getattr(self.params, "segment_parallel", True):
            return super().shard_table(table)
        n_segments = self._segment_count()
        if n_segments == 0:
            # plain-PG fallback: ctid split
            return super().shard_table(table)
        if n_segments <= 1:
            return [table]
        return [
            TableDescription(
                id=table.id,
                filter=f"gp_segment_id = {seg}",
                eta_rows=table.eta_rows // n_segments,
            )
            for seg in range(n_segments)
        ]

    # -- gpfdist segment-direct unload (gpfdist_storage.go) ------------------
    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        if not self.params.gpfdist or table.filter:
            # filtered parts (predicate pushdown) keep the master path
            return super().load_table(table, pusher)
        n_segments = self._segment_count()
        if n_segments == 0:
            return super().load_table(table, pusher)
        schema = self.table_schema(table.id)
        slot = f"u{uuid.uuid4().hex[:12]}"
        server = GpfdistServer(self.params.gpfdist_host).start()
        ext = (f'"{table.id.namespace}".'
               f'"{table.id.name}__trtpu_wext_{slot}"')
        lock = threading.Lock()
        # PER-SEGMENT reframing state: each segment's stream splits at
        # its own arbitrary byte boundaries, and a record boundary is a
        # newline at EVEN quote parity (CSV-quoted fields may embed
        # newlines, so plain rfind-newline reframing is unsound)
        tails: dict[str, bytes] = {}

        def _safe_split(data: bytes) -> int:
            last = -1
            in_quote = False
            for i, b in enumerate(data):
                if b == 0x22:            # '"' (doubled quotes toggle twice)
                    in_quote = not in_quote
                elif b == 0x0A and not in_quote:
                    last = i
            return last

        def on_chunk(seg: str, data: bytes, done: bool) -> None:
            with lock:
                data = tails.pop(seg, b"") + data
                if done:
                    if data:
                        if not data.endswith(b"\n"):
                            data += b"\n"
                        self._flush_csv(io.BytesIO(data), table.id,
                                        schema, pusher)
                    return
                nl = _safe_split(data)
                if nl < 0:
                    tails[seg] = data
                    return
                tails[seg] = data[nl + 1:]
                self._flush_csv(io.BytesIO(data[:nl + 1]), table.id,
                                schema, pusher)

        try:
            server.register_sink(slot, on_chunk, n_segments)
            control = _conn(self.params)
            try:
                control.query(
                    f"CREATE WRITABLE EXTERNAL TABLE {ext} "
                    f"(LIKE {table.id.fqtn()}) "
                    f"LOCATION ('{server.location(slot)}') "
                    f"FORMAT 'CSV'")
                cols = ", ".join(f'"{c.name}"' for c in schema)
                control.query(
                    f"INSERT INTO {ext} SELECT {cols} "
                    f"FROM {table.id.fqtn()}")
                server.wait_done(slot)
            finally:
                try:
                    control.query(f"DROP EXTERNAL TABLE IF EXISTS {ext}")
                finally:
                    control.close()
        finally:
            server.release(slot)
            server.stop()


class GPSinker(PGSinker):
    """PG sink plus the gpfdist segment-direct bulk-insert path
    (gpfdist_sink.go:193): snapshot batches load via READABLE EXTERNAL
    TABLE with the segments pulling CSV straight from the worker; CDC
    row events keep the per-statement master path."""

    def __init__(self, params: GPTargetParams):
        super().__init__(params)
        self._server: GpfdistServer | None = None

    def _gpfdist(self) -> GpfdistServer:
        if self._server is None:
            self._server = GpfdistServer(
                self.params.gpfdist_host).start()
        return self._server

    def close(self) -> None:
        super().close()
        if self._server is not None:
            self._server.stop()
            self._server = None

    def _copy_insert(self, batch: ColumnBatch) -> None:
        if not getattr(self.params, "gpfdist", False):
            return super()._copy_insert(batch)
        server = self._gpfdist()
        slot = f"l{uuid.uuid4().hex[:12]}"
        tid = batch.table_id
        ext = f'"{tid.namespace}"."{tid.name}__trtpu_rext_{slot}"'
        data = batch.to_pydict()
        names = list(batch.columns)
        lines = []
        for i in range(batch.n_rows):
            lines.append(",".join(
                self._csv_cell(data[n][i]) for n in names))
        server.put_chunk(slot, ("\n".join(lines) + "\n").encode())
        server.finish(slot)
        cols = ", ".join(f'"{n}"' for n in names)
        # EXPLICIT column defs in batch-column order: (LIKE target) would
        # bind the positional CSV to the target's full column list, which
        # breaks when the target pre-exists with extra/reordered columns
        from transferia_tpu.typesystem.rules import map_target_type

        by_name = {c.name: c for c in batch.schema}
        defs = ", ".join(
            f'"{n}" {map_target_type("pg", by_name[n].data_type)}'
            for n in names)
        try:
            self.conn.query(
                f"CREATE READABLE EXTERNAL TABLE {ext} ({defs}) "
                f"LOCATION ('{server.location(slot)}') FORMAT 'CSV'")
            self.conn.query(
                f"INSERT INTO {tid.fqtn()} ({cols}) "
                f"SELECT {cols} FROM {ext}")
        finally:
            try:
                self.conn.query(f"DROP EXTERNAL TABLE IF EXISTS {ext}")
            finally:
                server.release(slot)


@register_provider
class GreenplumProvider(Provider):
    NAME = "greenplum"

    def storage(self):
        if isinstance(self.transfer.src, GPSourceParams):
            return GPStorage(self.transfer.src)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, GPTargetParams):
            return GPSinker(self.transfer.dst)
        return None
