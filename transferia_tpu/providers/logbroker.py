"""Logbroker source + sink (reference: pkg/providers/logbroker/).

The reference speaks the proprietary persqueue SDK
(native_source.go, sink.go); modern Logbroker installations (and its
YC incarnation) expose a Kafka-compatible surface, which is the one a
dependency-free client can speak.  This provider maps the reference's
LbSource/LbDestination models (model_lb_source.go:11-27,
model_destination.go) onto the framework's Kafka wire client — topics,
consumer offsets, TLS + SASL, parser plumbing and the at-least-once ack
discipline are exactly the Kafka provider's.

Parser presets: Logbroker feeds are conventionally line-delimited JSON /
TSKV / Cloud Logging / Audit Trails; `parser_preset` expands to the
matching registered parser config so transfer specs stay one-line.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)

# reference model_lb_source.go:31-39 cluster aliases -> default ports
DEFAULT_PORT = 9092
DEFAULT_TLS_PORT = 9093

_PARSER_PRESETS: dict[str, dict] = {
    # preset name -> parser registry config (parsers/generic.py+plugins.py)
    "json": {"json": {"table": ""}},
    "tskv": {"tskv": {"table": ""}},
    "cloud_logging": {"cloudlogging": {}},
    "audit_trails": {"audittrailsv1": {}},
    "raw": {"raw_to_table": {"table": ""}},
}


def _resolve_parser(preset: str, parser: Optional[dict],
                    topic: str) -> Optional[dict]:
    if parser is not None:
        return parser
    if not preset:
        return None
    cfg = _PARSER_PRESETS.get(preset)
    if cfg is None:
        raise ValueError(
            f"unknown logbroker parser preset {preset!r}; "
            f"one of {sorted(_PARSER_PRESETS)}")
    out = {k: dict(v) for k, v in cfg.items()}
    for v in out.values():
        if "table" in v and not v["table"]:
            v["table"] = topic.rsplit("/", 1)[-1] or "logbroker"
    return out


@register_endpoint
@dataclass
class LogbrokerSourceParams(EndpointParams):
    PROVIDER = "logbroker"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    instance: str = ""      # cluster host (reference LbSource.Instance)
    topic: str = ""
    consumer: str = ""      # kept for reference parity; offsets live in
    #                         the coordinator on the Kafka surface
    database: str = ""      # YC topic-service database path
    token: str = ""         # IAM/OAuth token -> SASL PLAIN password
    port: int = 0           # 0 -> 9093 when tls else 9092
    tls: bool = False
    tls_ca: str = ""
    parser: Optional[dict] = None
    parser_preset: str = ""  # json | tskv | cloud_logging | audit_trails
    parallelism: int = 4
    start_from: str = "earliest"

    def parser_config(self):
        return _resolve_parser(self.parser_preset, self.parser, self.topic)

    def to_kafka_params(self):
        from transferia_tpu.providers.kafka.provider import (
            KafkaSourceParams,
        )

        port = self.port or (DEFAULT_TLS_PORT if self.tls
                             else DEFAULT_PORT)
        return KafkaSourceParams(
            brokers=[f"{self.instance}:{port}"],
            topic=self.topic,
            parser=self.parser_config(),
            parallelism=self.parallelism,
            start_from=self.start_from,
            tls=self.tls,
            tls_ca=self.tls_ca,
            sasl_mechanism="PLAIN" if self.token else "",
            sasl_username=self.database or "@",
            sasl_password=self.token,
        )


@register_endpoint
@dataclass
class LogbrokerTargetParams(EndpointParams):
    PROVIDER = "logbroker"
    IS_SOURCE = False

    instance: str = ""
    topic: str = ""            # "" -> per-table "<ns>.<name>"
    database: str = ""
    token: str = ""
    port: int = 0
    tls: bool = False
    tls_ca: str = ""
    serializer: str = "json"
    serializer_config: dict = None  # type: ignore[assignment]
    compression: str = ""

    def __post_init__(self):
        if self.serializer_config is None:
            self.serializer_config = {}

    def to_kafka_params(self):
        from transferia_tpu.providers.kafka.provider import (
            KafkaTargetParams,
        )

        port = self.port or (DEFAULT_TLS_PORT if self.tls
                             else DEFAULT_PORT)
        return KafkaTargetParams(
            brokers=[f"{self.instance}:{port}"],
            topic=self.topic,
            serializer=self.serializer,
            serializer_config=dict(self.serializer_config),
            compression=self.compression,
            tls=self.tls,
            tls_ca=self.tls_ca,
            sasl_mechanism="PLAIN" if self.token else "",
            sasl_username=self.database or "@",
            sasl_password=self.token,
        )


@register_provider
class LogbrokerProvider(Provider):
    NAME = "logbroker"

    def source(self):
        if not isinstance(self.transfer.src, LogbrokerSourceParams):
            return None
        from transferia_tpu.providers.kafka.provider import (
            _KafkaQueueClient,
        )
        from transferia_tpu.providers.queue_common import QueueSource

        p = self.transfer.src
        client = _KafkaQueueClient(p.to_kafka_params(), self.transfer.id,
                                   self.coordinator)
        return QueueSource(client, p.parser_config(),
                           parallelism=p.parallelism,
                           metrics=self.metrics,
                           transfer_id=self.transfer.id)

    def sinker(self):
        if not isinstance(self.transfer.dst, LogbrokerTargetParams):
            return None
        from transferia_tpu.providers.kafka.provider import KafkaSinker

        return KafkaSinker(self.transfer.dst.to_kafka_params())

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = None
        if isinstance(self.transfer.src, LogbrokerSourceParams):
            params = self.transfer.src.to_kafka_params()
            topic = self.transfer.src.topic
        elif isinstance(self.transfer.dst, LogbrokerTargetParams):
            params = self.transfer.dst.to_kafka_params()
            topic = self.transfer.dst.topic
        if params is None:
            return result
        try:
            from transferia_tpu.providers.kafka.client import KafkaClient

            client = KafkaClient(
                params.brokers, tls=params.tls, tls_ca=params.tls_ca,
                sasl_mechanism=params.sasl_mechanism,
                sasl_username=params.sasl_username,
                sasl_password=params.sasl_password,
            )
            client.metadata([topic] if topic else [])
            client.close()
            result.add("connect")
        except Exception as e:
            result.add("connect", e)
        return result
