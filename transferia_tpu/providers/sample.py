"""Synthetic sample provider — the built-in load generator.

Reference parity: pkg/providers/sample/ (model_source.go:20-27 iot/user
presets, sharded_storage.go).  Generates deterministic columnar batches
directly (no row pivot): the data is born device-ready, which is what makes
it the benchmark source for the TPU path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from transferia_tpu.abstract.change_item import (
    done_table_load,
    init_table_load,
)
from transferia_tpu.abstract.interfaces import (
    AsyncSink,
    Pusher,
    ShardingStorage,
    Source,
    Storage,
    TableInfo,
)
from transferia_tpu.abstract.schema import TableID, TableSchema, new_table_schema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import Column, ColumnBatch
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.runtime import lockwatch
from transferia_tpu.providers.registry import Provider, register_provider
from transferia_tpu.typesystem.rules import register_source_rules
from transferia_tpu.abstract.schema import CanonicalType


@register_endpoint
@dataclass
class SampleSourceParams(EndpointParams):
    PROVIDER = "sample"
    IS_SOURCE = True

    preset: str = "iot"          # iot | users
    table: str = "events"
    rows: int = 100_000          # snapshot rows
    batch_rows: int = 16_384
    rate: float = 0.0            # replication rows/sec, 0 = unthrottled
    replication_batch: int = 1024
    seed: int = 7
    shard_parts: int = 0         # >0: advertise ShardingStorage parts
    # emit the low-cardinality utf8 columns (iot status/device_id,
    # users country) as DictEnc over a per-preset shared pool — the
    # dict-heavy source shape the code-native reduction plane is
    # measured against (bytes identical to the flat emission)
    dict_encode: bool = False


_IOT_SCHEMA = new_table_schema([
    ("event_id", "int64", True),
    ("device_id", "utf8"),
    ("ts", "timestamp"),
    ("temperature", "double"),
    ("humidity", "double"),
    ("status", "utf8"),
])

_USERS_SCHEMA = new_table_schema([
    ("user_id", "int64", True),
    ("name", "utf8"),
    ("email", "utf8"),
    ("age", "int32"),
    ("score", "double"),
    ("country", "utf8"),
])

_STATUSES = np.array(["ok", "warn", "error", "offline"])
_COUNTRIES = np.array(["de", "us", "fr", "jp", "br", "in"])

register_source_rules("sample", {
    "int64": CanonicalType.INT64, "utf8": CanonicalType.UTF8,
    "timestamp": CanonicalType.TIMESTAMP, "double": CanonicalType.DOUBLE,
    "int32": CanonicalType.INT32,
})


def _utf8_column(name: str, values: np.ndarray) -> Column:
    """Build a var-width column from a numpy unicode array (vectorized)."""
    joined = "\x00".join(values.tolist())
    data = np.frombuffer(joined.encode(), dtype=np.uint8)
    # recompute offsets from encoded lengths (ascii-safe presets)
    lens = np.array([len(v.encode()) for v in values.tolist()], dtype=np.int64)
    from transferia_tpu.columnar.batch import _offsets_from_lengths

    offsets = _offsets_from_lengths(lens)
    # strip separators
    out = np.empty(int(offsets[-1]), dtype=np.uint8)
    pos = 0
    src = 0
    for L in lens:
        out[pos:pos + L] = data[src:src + L]
        pos += L
        src += L + 1
    return Column(name, CanonicalType.UTF8, out, offsets)


# per-(preset, column) stable DictPools for dict_encode batches: pool
# BYTES build fresh per batch (exactly like a parquet file's
# per-row-group dict pages) and converge on one object through the
# content-interning layer (columnar/batch.intern_pool) — every batch of
# a load references ONE pool, so downstream memos (hexed HMAC pool,
# rowhash accumulators, device digest matrices) amortize across the
# whole transfer AND the cross-row-group sharing machinery is exercised
# by the in-repo source, not only by real parquet files.


# fallback identity cache for TRANSFERIA_TPU_POOL_SHARING=0: the kill
# switch must restore the pre-sharing behavior (one stable pool per
# (preset, column) per process), not regress to a fresh pool per batch
_DICT_POOLS: dict = {}
_DICT_POOL_LOCK = lockwatch.named_lock("pool.sample_dict")


def _shared_pool(key: str, values: list[str]):
    from transferia_tpu.columnar.batch import (
        _offsets_from_lengths,
        intern_pool,
        pool_sharing_enabled,
    )

    if not pool_sharing_enabled():
        with _DICT_POOL_LOCK:
            pool = _DICT_POOLS.get(key)
        if pool is not None:
            return pool
    bufs = [v.encode() for v in values]
    data = np.frombuffer(b"".join(bufs), dtype=np.uint8).copy()
    # one extra empty-bytes sentinel entry for null rows (none in the
    # sample presets, but the pool contract carries it)
    off = _offsets_from_lengths([len(b) for b in bufs] + [0])
    pool = intern_pool(("sample", key), data, off, null_code=len(bufs))
    with _DICT_POOL_LOCK:
        pool = _DICT_POOLS.setdefault(key, pool)
    return pool


def _dict_column(name: str, key: str, values: list[str],
                 codes: np.ndarray) -> Column:
    from transferia_tpu.columnar.batch import DictEnc

    pool = _shared_pool(key, values)
    return Column(name, CanonicalType.UTF8,
                  dict_enc=DictEnc(codes.astype(np.int32), pool=pool))


def make_batch(preset: str, table: TableID, start: int, n: int,
               seed: int, dict_encode: bool = False) -> ColumnBatch:
    """Deterministic batch of n rows with ids [start, start+n).

    dict_encode=True emits the low-cardinality utf8 columns as DictEnc
    over shared pools; materializing them yields byte-identical flat
    buffers to the default emission (pinned by tests)."""
    rng = np.random.default_rng(seed + start)
    ids = np.arange(start, start + n, dtype=np.int64)
    if preset == "iot":
        dev = rng.integers(0, 1000, n)
        dev_values = ["dev-" + str(i) for i in range(1000)]
        cols = {
            "event_id": Column("event_id", CanonicalType.INT64, ids),
            "device_id": _dict_column(
                "device_id", "iot.device_id", dev_values, dev)
            if dict_encode else _utf8_column(
                "device_id",
                np.char.add("dev-", dev.astype("U6")),
            ),
            "ts": Column("ts", CanonicalType.TIMESTAMP,
                         np.int64(1_700_000_000_000_000) + ids * 1000),
            "temperature": Column(
                "temperature", CanonicalType.DOUBLE,
                np.round(rng.normal(21.0, 5.0, n), 3),
            ),
            "humidity": Column(
                "humidity", CanonicalType.DOUBLE,
                np.round(rng.uniform(0, 100, n), 3),
            ),
            "status": _dict_column(
                "status", "iot.status", _STATUSES.tolist(),
                rng.integers(0, 4, n))
            if dict_encode else _utf8_column(
                "status", _STATUSES[rng.integers(0, 4, n)].astype("U8")
            ),
        }
        return ColumnBatch(table, _IOT_SCHEMA, cols)
    if preset == "users":
        cols = {
            "user_id": Column("user_id", CanonicalType.INT64, ids),
            "name": _utf8_column(
                "name", np.char.add("user_", ids.astype("U12"))
            ),
            "email": _utf8_column(
                "email",
                np.char.add(np.char.add("u", ids.astype("U12")),
                            "@example.com"),
            ),
            "age": Column("age", CanonicalType.INT32,
                          rng.integers(18, 90, n).astype(np.int32)),
            "score": Column("score", CanonicalType.DOUBLE,
                            np.round(rng.uniform(0, 1000, n), 2)),
            "country": _dict_column(
                "country", "users.country", _COUNTRIES.tolist(),
                rng.integers(0, 6, n))
            if dict_encode else _utf8_column(
                "country", _COUNTRIES[rng.integers(0, 6, n)].astype("U4")
            ),
        }
        return ColumnBatch(table, _USERS_SCHEMA, cols)
    raise ValueError(f"sample: unknown preset {preset!r}")


def preset_schema(preset: str) -> TableSchema:
    return _IOT_SCHEMA if preset == "iot" else _USERS_SCHEMA


class SampleStorage(Storage, ShardingStorage):
    """Snapshot storage over the generator (sample/sharded_storage.go)."""

    def __init__(self, params: SampleSourceParams):
        self.params = params
        self.table = TableID("sample", params.table)

    def table_list(self, include=None):
        info = TableInfo(eta_rows=self.params.rows,
                         schema=preset_schema(self.params.preset))
        tables = {self.table: info}
        if include:
            tables = {
                t: i for t, i in tables.items()
                if any(t.include_matches(p) for p in include)
            }
        return tables

    def table_schema(self, table: TableID) -> TableSchema:
        return preset_schema(self.params.preset)

    def estimate_table_rows_count(self, table: TableID) -> int:
        return self.params.rows

    def exact_table_rows_count(self, table: TableID) -> int:
        return self.params.rows

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        parts = self.params.shard_parts
        if parts <= 1:
            return [table]
        total = self.params.rows
        per = (total + parts - 1) // parts
        out = []
        for i in range(parts):
            lo = i * per
            hi = min(total, lo + per)
            if lo >= hi:
                break
            out.append(TableDescription(
                id=table.id, filter=f"rows:{lo}:{hi}", offset=lo,
                eta_rows=hi - lo,
            ))
        return out

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        from transferia_tpu.chaos.failpoints import failpoint

        failpoint("storage.part.open")
        if table.filter.startswith("rows:"):
            _, lo_s, hi_s = table.filter.split(":")
            lo, hi = int(lo_s), int(hi_s)
        else:
            lo, hi = 0, self.params.rows
        from transferia_tpu.stats import trace

        bs = self.params.batch_rows
        for start in range(lo, hi, bs):
            n = min(bs, hi - start)
            failpoint("storage.part.read")
            sp = trace.span("source_decode")
            if sp:
                sp.add(rows=n)
            with sp:
                batch = make_batch(self.params.preset, table.id, start, n,
                                   self.params.seed,
                                   dict_encode=self.params.dict_encode)
            # synthetic data's event time IS its generation instant —
            # stamped on the read path (not in make_batch, whose output
            # is compared byte-identically by tests) so the freshness
            # plane measures real generate→publish lag on demo runs
            batch.commit_times = np.full(n, time.time_ns(),
                                         dtype=np.int64)
            pusher(batch)


class SampleReplicationSource(Source):
    """Endless insert stream (replication mode load generator)."""

    def __init__(self, params: SampleSourceParams):
        self.params = params
        self.table = TableID("sample", params.table)
        self._stop = threading.Event()

    def run(self, sink: AsyncSink) -> None:
        lsn = 0
        start = self.params.rows  # continue after snapshot range
        bs = self.params.replication_batch
        schema = preset_schema(self.params.preset)
        futures = []
        while not self._stop.is_set():
            batch = make_batch(self.params.preset, self.table, start, bs,
                               self.params.seed)
            lsn += 1
            batch.lsns = np.full(bs, lsn, dtype=np.int64)
            batch.commit_times = np.full(bs, time.time_ns(),
                                         dtype=np.int64)
            futures.append(sink.async_push(batch))
            if len(futures) > 16:
                futures.pop(0).result()
            start += bs
            if self.params.rate > 0:
                self._stop.wait(bs / self.params.rate)
        for f in futures:
            f.result()

    def stop(self) -> None:
        self._stop.set()


@register_provider
class SampleProvider(Provider):
    NAME = "sample"

    def storage(self):
        return SampleStorage(self.transfer.src)

    def source(self):
        return SampleReplicationSource(self.transfer.src)
