"""Local-filesystem object provider: parquet/jsonl/csv files as tables.

Reference parity: the S3 provider's format-reader stack
(pkg/providers/s3/reader/registry/: csv/json/line/parquet + schema
inference reader/abstract.go:40-52) operating on a local directory; the S3
provider proper layers remote listing on top of this (providers/s3.py).

Parquet is the columnar fast path: row groups map straight to ColumnBatch
via arrow with no row pivot (the ClickBench north-star read path), and each
row group is a shardable part.
"""

from __future__ import annotations

import glob as globmod
import json
import os
import threading
import uuid
from dataclasses import dataclass
from typing import Optional

import numpy as np

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    ScanPredicateStorage,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID, TableSchema

from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch, arrow_to_table_schema
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.runtime import knobs
from transferia_tpu.providers.registry import Provider, register_provider


@register_endpoint
@dataclass
class FileSourceParams(EndpointParams):
    PROVIDER = "fs"
    IS_SOURCE = True

    path: str = ""            # file, dir, or glob
    format: str = "parquet"   # parquet | jsonl | csv
    table: str = "data"       # logical table name
    namespace: str = "fs"
    batch_rows: int = 65_536
    # decode-pipeline knobs (ARCHITECTURE.md "Decode pipeline"):
    # decode_threads: column-parallel native decode width; 0 = auto
    # (effective CPUs / upload workers — parts already decode in
    # parallel across workers, so K only widens when cores are spare).
    # readahead_groups: decoded row groups in flight per part (the one
    # the consumer holds + queued + decoding); -1 = auto (2 with >1
    # effective CPU, else 0), 0 = serial decode.  readahead_bytes adds
    # an optional in-flight decoded-payload cap on top (0 = none).
    # rowgroups_per_part: consecutive row groups per shard part; 0 =
    # auto (1 with readahead off — today's per-group parts — else up to
    # 4, keeping ~4 parts queued per upload worker).  Parts spanning
    # several groups are what give the per-part readahead a g+1 to
    # prefetch.
    decode_threads: int = 0
    readahead_groups: int = -1
    readahead_bytes: int = 0
    rowgroups_per_part: int = 0


@register_endpoint
@dataclass
class FileTargetParams(EndpointParams):
    PROVIDER = "fs"
    IS_TARGET = True

    path: str = ""            # output directory
    format: str = "parquet"   # parquet | jsonl


def _expand(path: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(
            p for p in globmod.glob(os.path.join(path, "**", "*"),
                                    recursive=True)
            if os.path.isfile(p)
        )
    return sorted(globmod.glob(path))


class FileStorage(Storage, ShardingStorage, ScanPredicateStorage):
    def __init__(self, params: FileSourceParams, metrics=None,
                 upload_workers: int = 1):
        self.params = params
        self.table = TableID(params.namespace, params.table)
        self._schema: Optional[TableSchema] = None
        self._scan_predicates: dict[TableID, object] = {}
        self._pred_fns: dict[TableID, object] = {}
        self._pruned_lock = threading.Lock()
        self.scan_rows_pruned = 0
        self._upload_workers = max(1, upload_workers)
        self._readahead_gauges = None
        if metrics is not None:
            from transferia_tpu.stats.registry import DeviceStats

            ds = DeviceStats(metrics)
            self._readahead_gauges = (ds.readahead_depth,
                                      ds.readahead_bytes)

    # -- decode-pipeline knob resolution ------------------------------------
    def _decode_threads(self) -> int:
        env = knobs.env_raw("TRANSFERIA_TPU_DECODE_THREADS")
        k = int(env) if env else self.params.decode_threads
        if k <= 0:
            # auto: each upload worker already runs a consumer thread
            # and a readahead decoder, so claim only half the per-worker
            # core share — K = cpus/(2*workers), measured neutral at 4
            # workers on 24 cores where cpus/workers oversubscribed ~9%
            from transferia_tpu.runtime.limits import effective_cpus

            k = int(effective_cpus()) // (2 * self._upload_workers)
        return max(1, min(8, k))

    def _readahead_groups(self) -> int:
        env = knobs.env_raw("TRANSFERIA_TPU_READAHEAD_GROUPS")
        n = int(env) if env else self.params.readahead_groups
        if n < 0:  # auto: overlap decode unless there's a single core
            from transferia_tpu.runtime.limits import effective_cpus

            n = 2 if effective_cpus() >= 2 else 0
        return n

    def _readahead(self, groups, decode, nbytes):
        from transferia_tpu.providers.readahead import RowGroupReadahead

        return RowGroupReadahead(
            groups, decode,
            max_groups=self._readahead_groups(),
            max_bytes=self.params.readahead_bytes or None,
            nbytes=nbytes, gauges=self._readahead_gauges)

    def _count_pruned(self, n: int) -> None:
        # upload workers share this storage across threads
        with self._pruned_lock:
            self.scan_rows_pruned += n

    def _files(self) -> list[str]:
        files = _expand(self.params.path)
        if not files:
            raise FileNotFoundError(
                f"fs source: no files match {self.params.path!r}"
            )
        return files

    # -- schema inference ---------------------------------------------------
    def table_schema(self, table: TableID) -> TableSchema:
        if self._schema is None:
            f = self._files()[0]
            if self.params.format == "parquet":
                import pyarrow.parquet as pq

                self._schema = arrow_to_table_schema(
                    pq.read_schema(f)
                )
            elif self.params.format == "csv":
                import pyarrow.csv as pacsv

                # stream only the first block — never parse the whole file
                # just to learn the schema
                with pacsv.open_csv(f) as reader:
                    self._schema = arrow_to_table_schema(reader.schema)
            else:  # jsonl: sample first lines
                import pyarrow as pa

                rows = []
                with open(f) as fh:
                    for line in fh:
                        if not line.strip():
                            continue  # skip blanks like the loader does
                        rows.append(json.loads(line))
                        if len(rows) >= 100:
                            break
                tbl = pa.Table.from_pylist(rows)
                self._schema = arrow_to_table_schema(tbl.schema)
        return self._schema

    def table_list(self, include=None):
        if include and not any(
                self.table.include_matches(p) for p in include):
            return {}
        eta = 0
        if self.params.format == "parquet":
            from transferia_tpu.providers.parquet_native import (
                parquet_metadata,
            )

            for f in self._files():
                eta += parquet_metadata(f).num_rows
        return {self.table: TableInfo(
            eta_rows=eta, schema=self.table_schema(self.table)
        )}

    def estimate_table_rows_count(self, table: TableID) -> int:
        info = self.table_list().get(self.table)
        return info.eta_rows if info else 0

    # -- sharding: parquet shards per row-group run, other formats per file -
    def _groups_per_part(self, n_groups: int) -> int:
        """Row groups per shard part.  One group per part (the original
        sharding) maximizes worker-level parallelism but starves the
        per-part readahead — there is no g+1 inside a single-group part.
        Auto keeps ~4 parts queued per upload worker and caps the run
        at 4 groups so one straggler part never serializes the tail."""
        p = self.params.rowgroups_per_part
        if p <= 0:
            if self._readahead_groups() <= 0:
                return 1  # serial decode: per-group parts, as before
            p = min(4, max(1, n_groups // (4 * self._upload_workers)))
        return max(1, p)

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        files = self._files()
        out = []
        for f in files:
            if self.params.format == "parquet":
                from transferia_tpu.providers.parquet_native import (
                    parquet_metadata,
                )

                meta = parquet_metadata(f)
                n_groups = meta.num_row_groups
                step = self._groups_per_part(n_groups)
                for lo in range(0, n_groups, step):
                    hi = min(lo + step, n_groups)
                    out.append(TableDescription(
                        id=table.id, filter=f"rg:{lo}:{hi}:{f}",
                        eta_rows=sum(meta.row_group(g).num_rows
                                     for g in range(lo, hi)),
                    ))
            else:
                out.append(TableDescription(id=table.id, filter=f"file:{f}"))
        return out

    # -- load ---------------------------------------------------------------
    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        schema = self.table_schema(table.id)
        if table.filter.startswith("rg:"):
            _, lo, hi, path = table.filter.split(":", 3)
            self._load_row_groups(path, int(lo), int(hi), table.id, schema,
                                  pusher)
            return
        if table.filter.startswith("file:"):
            files = [table.filter[5:]]
        else:
            files = self._files()
        for f in files:
            self._load_file(f, table.id, schema, pusher)

    def set_scan_predicate(self, table: TableID, node) -> bool:
        """ScanPredicateStorage: arrow-side pre-filter of record batches
        before the columnar pivot (predicate/arroweval.py).  Advisory —
        batches where arrow evaluation bails flow through unfiltered and
        the chain's own filter drops the rows instead."""
        self._scan_predicates[table] = node
        return True

    def _scan_filter(self, tid: TableID, rb):
        node = self._scan_predicates.get(tid)
        if node is None or rb.num_rows == 0:
            return rb
        from transferia_tpu.predicate.arroweval import eval_mask

        mask = eval_mask(node, rb)
        if mask is None:
            return rb
        filtered = rb.filter(mask)  # null mask entries drop (SQL 3VL)
        self._count_pruned(rb.num_rows - filtered.num_rows)
        return filtered

    def _prune_row_groups(self, pf, groups: list[int],
                          tid: TableID) -> list[int]:
        """Zone-map pruning: drop whole row groups whose min/max stats
        disprove the scan predicate (predicate/stats.py) — the only form
        of pushdown that skips DECODE, not just pivot/transform."""
        node = self._scan_predicates.get(tid)
        if node is None:
            return groups
        from transferia_tpu.predicate.stats import (
            ColumnRange,
            range_disproves,
        )

        pred_cols = node.columns()
        kept = []
        for g in groups:
            rg = pf.metadata.row_group(g)
            ranges = {}
            for ci in range(rg.num_columns):
                col = rg.column(ci)
                if col.path_in_schema not in pred_cols:
                    continue  # wide tables: only the predicate's columns
                st = col.statistics
                if st is None or not st.has_min_max:
                    continue
                ranges[col.path_in_schema] = ColumnRange(
                    min=st.min, max=st.max,
                    null_count=(st.null_count
                                if st.has_null_count else None))
            try:
                if ranges and range_disproves(node, ranges):
                    self._count_pruned(rg.num_rows)
                    continue
            except Exception:  # trtpu: ignore[EXC001]
                pass  # odd stats types: scan the group normally
            kept.append(g)
        return kept

    def _batch_filter(self, tid: TableID, batch: ColumnBatch
                      ) -> ColumnBatch:
        """Scan-predicate filter over an already-pivoted batch (native
        decode path) — numpy compiler, same 3VL as the chain's filter."""
        node = self._scan_predicates.get(tid)
        if node is None or batch.n_rows == 0:
            return batch
        from transferia_tpu.predicate.compile import compile_mask

        fn = self._pred_fns.get(tid)
        if fn is None:
            fn = compile_mask(node)
            self._pred_fns[tid] = fn
        keep = fn(batch)
        if keep.all():
            return batch
        out = batch.filter(keep)
        self._count_pruned(batch.n_rows - out.n_rows)
        return out

    def _has_huge_row_groups(self, pf, groups: list[int]) -> bool:
        """Row groups too large to materialize whole per part thread
        (externally-written files can carry ~1M-row groups): both the
        native and arrow paths stream those through iter_batches."""
        max_rg_rows = max(
            pf.metadata.row_group(g).num_rows for g in groups)
        return max_rg_rows > max(8 * self.params.batch_rows, 1 << 20)

    def _load_groups_native(self, pf, path: str, groups: list[int],
                            tid: TableID, schema: TableSchema,
                            pusher: Pusher) -> bool:
        """Decode row groups via the C++ chunk decoder; False -> use arrow."""
        from transferia_tpu.providers.parquet_native import (
            NativeParquetReader,
            slice_columns,
        )
        from transferia_tpu.stats import stagetimer

        if self._has_huge_row_groups(pf, groups):
            return False  # stream huge row groups through arrow instead
        reader = NativeParquetReader.open(
            path, pf, schema, decode_threads=self._decode_threads())
        if reader is None:
            return False

        def decode(g):
            with stagetimer.stage("source_decode"):
                return reader.read_row_group(g)

        def cols_nbytes(cols):
            return sum(c.nbytes() for c in cols.values())

        with self._readahead(groups, decode, cols_nbytes) as ra:
            for g, cols in ra:
                n = pf.metadata.row_group(g).num_rows
                for b_lo in range(0, n, self.params.batch_rows):
                    b_hi = min(b_lo + self.params.batch_rows, n)
                    with stagetimer.stage("pivot"):
                        batch = ColumnBatch(
                            tid, schema, slice_columns(cols, b_lo, b_hi))
                        batch.read_bytes = batch.nbytes()
                    with stagetimer.stage("source_decode"):
                        batch = self._batch_filter(tid, batch)
                    if batch.n_rows:
                        pusher(batch)
        return True

    def _load_groups_arrow(self, pf, groups: list[int], tid: TableID,
                           schema: TableSchema, pusher: Pusher) -> None:
        """Arrow decode with the same row-group readahead as the native
        path: whole-group reads release the GIL inside arrow C++, so
        dict-heavy/nested files overlap decode with downstream too."""
        from transferia_tpu.stats import stagetimer

        def decode(g):
            with stagetimer.stage("source_decode"):
                return pf.read_row_group(g, use_threads=False)

        with self._readahead(groups, decode, lambda t: t.nbytes) as ra:
            for g, tbl in ra:
                for rb in tbl.to_batches(
                        max_chunksize=self.params.batch_rows):
                    with stagetimer.stage("source_decode"):
                        rb = self._scan_filter(tid, rb)
                    if rb.num_rows:
                        with stagetimer.stage("pivot"):
                            batch = ColumnBatch.from_arrow(rb, tid, schema)
                            batch.read_bytes = rb.nbytes
                        pusher(batch)

    def _load_row_groups(self, path: str, lo: int, hi: int, tid: TableID,
                         schema: TableSchema, pusher: Pusher) -> None:
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.providers.parquet_native import (
            parquet_file_cached,
        )
        from transferia_tpu.stats import stagetimer

        failpoint("storage.file.open")
        # footer metadata memoizes per (path, mtime, size): a multi-part
        # load re-opens the same file once per part, and the thrift
        # footer parse was 3.9% of the BENCH_r05 profile
        pf = parquet_file_cached(path)
        groups = self._prune_row_groups(pf, list(range(lo, hi)), tid)
        from transferia_tpu.stats import trace

        trace.instant("file_part_open", path=path, lo=lo, hi=hi,
                      groups=len(groups))
        if not groups:
            return
        if self._load_groups_native(pf, path, groups, tid, schema,
                                    pusher):
            return
        # whole-row-group reads beat iter_batches for dict-heavy files
        # (one dictionary unification per group, not per batch) and the
        # batch slices share dictionary buffers — which is what lets the
        # columnar layer pool-cache them (batch.py _adopt_dict_pool).
        # Var-width columns read dict-PRESERVING (read_dictionary): dict
        # pages surface as DictionaryArrays and adopt as shared
        # DictPools instead of decoding flat just to re-encode
        # downstream.  Externally-written files can carry huge row
        # groups (pyarrow default ~1M rows); cap the per-part
        # materialization and stream those through iter_batches instead.
        pf = self._dict_preserving_reader(pf, path, schema)
        if self._has_huge_row_groups(pf, groups):
            it = pf.iter_batches(batch_size=self.params.batch_rows,
                                 row_groups=groups)
            while True:
                with stagetimer.stage("source_decode"):
                    rb = next(it, None)
                    if rb is not None:
                        rb = self._scan_filter(tid, rb)
                if rb is None:
                    return
                if rb.num_rows:
                    with stagetimer.stage("pivot"):
                        batch = ColumnBatch.from_arrow(rb, tid, schema)
                        batch.read_bytes = rb.nbytes
                    pusher(batch)
            return
        self._load_groups_arrow(pf, groups, tid, schema, pusher)

    @staticmethod
    def _dict_preserving_reader(pf, path: str, schema: TableSchema):
        """A reader whose dict-encoded var-width columns keep their
        encoding (arrow paths only; the native path adopts pages
        itself).  Only columns whose chunks actually carry dictionary
        encodings qualify — read_dictionary on a PLAIN column would
        make arrow BUILD a dictionary, a pure loss for
        high-cardinality strings."""
        from transferia_tpu.providers.parquet_native import (
            dict_encoded_columns,
            parquet_file_cached,
        )

        var_cols = [cs.name for cs in schema
                    if cs.data_type.is_variable_width]
        dict_cols = dict_encoded_columns(pf.metadata, var_cols)
        if not dict_cols:
            return pf
        return parquet_file_cached(path, read_dictionary=dict_cols)

    def _load_file(self, path: str, tid: TableID, schema: TableSchema,
                   pusher: Pusher) -> None:
        fmt = self.params.format
        if fmt == "parquet":
            from transferia_tpu.providers.parquet_native import (
                parquet_metadata,
            )

            n_groups = parquet_metadata(path).num_row_groups
            self._load_row_groups(path, 0, n_groups, tid, schema, pusher)
        elif fmt == "csv":
            import pyarrow.csv as pacsv

            with pacsv.open_csv(
                path,
                read_options=pacsv.ReadOptions(
                    block_size=max(1 << 20, self.params.batch_rows * 64)
                ),
            ) as reader:
                for rb in reader:
                    rb = self._scan_filter(tid, rb)
                    if rb.num_rows:
                        batch = ColumnBatch.from_arrow(rb, tid, schema)
                        batch.read_bytes = rb.nbytes
                        pusher(batch)
        elif fmt == "jsonl":
            rows: list[dict] = []
            nbytes = 0
            with open(path, "rb") as fh:
                for line in fh:
                    if not line.strip():
                        continue
                    rows.append(json.loads(line))
                    nbytes += len(line)
                    if len(rows) >= self.params.batch_rows:
                        self._push_json_rows(rows, nbytes, tid, schema, pusher)
                        rows, nbytes = [], 0
            if rows:
                self._push_json_rows(rows, nbytes, tid, schema, pusher)
        else:
            raise ValueError(f"fs source: unknown format {fmt!r}")

    @staticmethod
    def _push_json_rows(rows: list[dict], nbytes: int, tid: TableID,
                        schema: TableSchema, pusher: Pusher) -> None:
        data = {c.name: [r.get(c.name) for r in rows] for c in schema}
        batch = ColumnBatch.from_pydict(tid, schema, data)
        batch.read_bytes = nbytes
        pusher(batch)


class FileSinker(Sinker, StagedSinker):
    """Writes per-table files; parquet goes through arrow zero-pivot.

    File names embed a per-sinker instance token: the snapshot loader builds
    one sink pipeline per table part in parallel (load_snapshot.go per-part
    sinks), so concurrent instances must never share an output path —
    the same contract as the reference S3 sink's part-scoped file splitting
    (s3/sink/file_splitter.go).

    Staged-commit capable (abstract/commit.py): with an open part stage
    the batches write into `<path>/.staging/<part>/` and only an
    epoch-fenced `publish_part` renames them into the output directory
    (replacing any files an earlier publish of the same part landed).
    """

    def __init__(self, params: FileTargetParams):
        self.params = params
        os.makedirs(params.path, exist_ok=True)
        self._token = uuid.uuid4().hex[:8]
        self._writers: dict[TableID, object] = {}
        self._counters: dict[TableID, int] = {}
        self._stage = None  # staging.DirectoryPartStage when open

    # -- StagedSinker -------------------------------------------------------
    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import DirectoryPartStage

        self._stage = DirectoryPartStage(
            self.params.path, key, epoch,
            lambda d: FileSinker(FileTargetParams(
                path=d, format=self.params.format)))

    def publish_part(self, key: str, epoch: int) -> int:
        if self._stage is None:
            raise RuntimeError(f"fs sink: no open stage for {key!r}")
        rows = self._stage.publish()
        self.last_dedup_dropped = self._stage.state.dedup_dropped
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        if self._stage is not None:
            self._stage.abort()
            self._stage = None

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.note_push_retry()

    def _base_name(self, tid: TableID) -> str:
        # empty namespaces must not produce hidden ".name..." dotfiles
        return f"{tid.namespace}.{tid.name}" if tid.namespace \
            else tid.name

    def _out_path(self, tid: TableID, ext: str) -> str:
        self._counters[tid] = self._counters.get(tid, 0)
        return os.path.join(
            self.params.path,
            f"{self._base_name(tid)}.{self._token}."
            f"{self._counters[tid]:06d}.{ext}",
        )

    def push(self, batch: Batch) -> None:
        if self._stage is not None:
            self._stage.push(batch)
            return
        if is_columnar(batch):
            self._write_columnar(batch)
            return
        # rows before a done-marker must land in the file that marker
        # finalizes; reuse the shared ordering-preserving splitter
        from transferia_tpu.middlewares.helpers import split_rows_controls

        for part in split_rows_controls(batch):
            items = list(part)
            if items and items[0].is_row_event():
                self._write_columnar(ColumnBatch.from_rows(items))
                continue
            for it in items:
                if it.kind in (Kind.DONE_TABLE_LOAD,
                               Kind.DONE_SHARDED_TABLE_LOAD):
                    self._finish_table(it.table_id)

    def _write_columnar(self, batch: ColumnBatch) -> None:
        tid = batch.table_id
        if self.params.format == "parquet":
            import pyarrow.parquet as pq

            rb = batch.to_arrow()
            w = self._writers.get(tid)
            if w is None:
                w = pq.ParquetWriter(
                    self._out_path(tid, "parquet"), rb.schema
                )
                self._writers[tid] = w
            if rb.schema != w.schema:
                # encoding can vary per batch (dictionary-encoded row
                # groups decode as dict, fallback/plain groups as flat
                # strings) — cast to the writer's schema (arrow C++
                # dict<->string casts) so one file stays one schema
                rb = rb.cast(w.schema)
            w.write_batch(rb)
        elif self.params.format == "jsonl":
            path = os.path.join(
                self.params.path,
                f"{self._base_name(tid)}.{self._token}.jsonl",
            )
            with open(path, "a") as fh:
                for row in batch.to_rows():
                    fh.write(json.dumps(row.as_dict(), default=str) + "\n")
        else:
            raise ValueError(f"fs sink: unknown format {self.params.format!r}")

    def _finish_table(self, tid: TableID) -> None:
        w = self._writers.pop(tid, None)
        if w is not None:
            w.close()
            self._counters[tid] = self._counters.get(tid, 0) + 1

    def close(self) -> None:
        if self._stage is not None:
            # an unpublished stage at close is an abandoned attempt
            # (error path / fenced part): discard, never auto-publish
            self._stage.abort()
            self._stage = None
        for w in self._writers.values():
            w.close()
        self._writers.clear()


@register_provider
class FileProvider(Provider):
    NAME = "fs"

    def storage(self):
        return FileStorage(
            self.transfer.src, metrics=self.metrics,
            upload_workers=self.transfer.runtime.sharding.process_count)

    def sinker(self):
        return FileSinker(self.transfer.dst)

    def cleanup(self, tables: list) -> None:
        """Drop the named tables' output files (every sink run writes
        uniquely-suffixed files, so without cleanup a reupload would
        duplicate data side by side).  Matches the exact
        `<base>.<8-hex-token>.<6-digit-counter>.<ext>` layout so a table
        named "A"."B" never deletes "A"."B.X" files."""
        import re as _re

        path = getattr(self.transfer.dst, "path", "")
        if not path or not os.path.isdir(path):
            return
        for t in tables or []:
            tid = getattr(t, "id", t)
            base = f"{tid.namespace}.{tid.name}" if tid.namespace \
                else tid.name
            # parquet: base.token.counter.ext; jsonl: base.token.jsonl
            # also matches staged-commit published names, which insert
            # a `.part-<slug>` infix before the extension
            pat = _re.compile(
                _re.escape(base)
                + r"\.[0-9a-f]{8}(\.\d{6})?(\.part-[\w.-]+)?\.\w+$")
            for fname in os.listdir(path):
                if pat.fullmatch(fname):
                    os.unlink(os.path.join(path, fname))
