"""In-memory message broker provider.

The local stand-in for Kafka-shaped sources/sinks (reference test recipes
spin up real brokers via testcontainers, tests/tcrecipes/ — this image has
no docker, so the broker lives in-process).  It exercises the exact queue
replication machinery (QueueSource: sequencer + parsequeue + post-push
offset commits; queue sink: serializer + key-hash partitioning) that the
kafka provider shares.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.errors import StaleEpochPublishError
from transferia_tpu.abstract.interfaces import Batch, Sinker
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.parsers import Message
from transferia_tpu.providers.queue_common import FetchedBatch, QueueSource
from transferia_tpu.providers.registry import Provider, register_provider
from transferia_tpu.serializers import make_queue_serializer
from transferia_tpu.transform.plugins.sharder import hash_column_to_shards


# tombstone for a superseded staged-commit publish: entries are
# REPLACED in place (never deleted) so partition offsets — list
# indices consumers commit — stay stable across a part republish
_SUPERSEDED = object()


class MemoryBroker:
    """topic -> partition -> list of (key, value, timestamp_ns)."""

    def __init__(self, n_partitions: int = 1):
        self.lock = threading.RLock()
        self.n_partitions = n_partitions
        self.topics: dict[str, list[list[tuple]]] = {}
        self.committed: dict[tuple[str, str, int], int] = {}  # (group,t,p)
        # staged-commit publish registry (the broker-side half of the
        # mq offset-commit exactly-once path): part key -> (epoch,
        # [(topic, partition, message tuple)]) of the LAST accepted
        # publish, so a republish replaces instead of appending and a
        # stale-epoch publish is rejected
        self.published_parts: dict[str, tuple[int, list]] = {}

    def _topic(self, name: str) -> list[list[tuple]]:
        with self.lock:
            if name not in self.topics:
                self.topics[name] = [[] for _ in range(self.n_partitions)]
            return self.topics[name]

    def produce(self, topic: str, key: bytes, value: Optional[bytes],
                partition: Optional[int] = None) -> None:
        parts = self._topic(topic)
        if partition is None:
            partition = (hash(bytes(key or b"")) & 0x7FFFFFFF) % len(parts)
        with self.lock:
            parts[partition % len(parts)].append(
                (key, value, time.time_ns())
            )

    def fetch_from(self, topic: str, partition: int, offset: int,
                   max_messages: int) -> list[Message]:
        parts = self._topic(topic)
        picked: list[tuple] = []
        with self.lock:
            plist = parts[partition]
            # scan past superseded-publish tombstones: they hold their
            # index (committed offsets stay valid) but carry no message
            i = offset
            while i < len(plist) and len(picked) < max_messages:
                entry = plist[i]
                if entry is not _SUPERSEDED:
                    picked.append((i, entry))
                i += 1
        return [
            Message(value=v if v is not None else b"", key=k or b"",
                    topic=topic, partition=partition, offset=idx,
                    write_time_ns=ts)
            for idx, (k, v, ts) in picked
        ]

    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        with self.lock:
            self.committed[(group, topic, partition)] = offset

    def committed_offset(self, group: str, topic: str,
                         partition: int) -> int:
        with self.lock:
            return self.committed.get((group, topic, partition), -1)

    def size(self, topic: str) -> int:
        parts = self._topic(topic)
        with self.lock:
            return sum(1 for p in parts for e in p
                       if e is not _SUPERSEDED)

    def publish_part(self, part_key: str, epoch: int,
                     messages: list[tuple]) -> int:
        """Transactional part publish: land `messages` — entries of
        (topic, partition, key, value) — atomically, REPLACING whatever
        an earlier publish of the same part key landed (the in-memory
        twin of a kafka transaction + replace-on-republish), behind the
        epoch fence.  Partition None routes by key hash, like
        produce()."""
        with self.lock:
            prev = self.published_parts.get(part_key)
            if prev is not None and epoch < prev[0]:
                raise StaleEpochPublishError(part_key, epoch, prev[0])
            if prev is not None:
                # tombstone in place — NEVER delete: offsets are list
                # indices and consumer groups have committed them
                for topic, p, entry in prev[1]:
                    plist = self._topic(topic)[p]
                    for i, cur in enumerate(plist):
                        if cur is entry:
                            plist[i] = _SUPERSEDED
                            break
            landed = []
            for topic, partition, key, value in messages:
                parts = self._topic(topic)
                if partition is None:
                    partition = (hash(bytes(key or b"")) & 0x7FFFFFFF) \
                        % len(parts)
                entry = (key, value, time.time_ns())
                parts[partition % len(parts)].append(entry)
                landed.append((topic, partition % len(parts), entry))
            self.published_parts[part_key] = (epoch, landed)
            return len(landed)


_BROKERS: dict[str, MemoryBroker] = {}


def get_broker(broker_id: str, n_partitions: int = 1) -> MemoryBroker:
    if broker_id not in _BROKERS:
        _BROKERS[broker_id] = MemoryBroker(n_partitions)
    return _BROKERS[broker_id]


@register_endpoint
@dataclass
class MQSourceParams(EndpointParams):
    PROVIDER = "mq"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    broker_id: str = "default"
    topic: str = "topic"
    group: str = "transfer"
    parser: Optional[dict] = None          # one-of parser config
    parallelism: int = 4
    n_partitions: int = 1

    def parser_config(self):
        return self.parser


@register_endpoint
@dataclass
class MQTargetParams(EndpointParams):
    PROVIDER = "mq"
    IS_TARGET = True

    broker_id: str = "default"
    topic: str = ""                # empty -> per-table "<ns>.<name>"
    serializer: str = "json"       # json | native | debezium | mirror
    serializer_config: dict = field(default_factory=dict)
    n_partitions: int = 1
    partition_by: str = ""         # column for shard hashing; "" = key hash


class _MQClient:
    """QueueSource client over a MemoryBroker consumer group."""

    def __init__(self, params: MQSourceParams):
        self.broker = get_broker(params.broker_id, params.n_partitions)
        self.topic = params.topic
        self.group = params.group
        self.positions = {
            p: self.broker.committed_offset(self.group, self.topic, p) + 1
            for p in range(params.n_partitions)
        }

    def fetch(self, max_messages: int = 1024) -> list[FetchedBatch]:
        out = []
        for p, pos in self.positions.items():
            msgs = self.broker.fetch_from(self.topic, p, pos, max_messages)
            if msgs:
                self.positions[p] = msgs[-1].offset + 1
                out.append(FetchedBatch(self.topic, p, msgs))
        return out

    def commit(self, topic: str, partition: int, offset: int) -> None:
        self.broker.commit(self.group, topic, partition, offset)

    def seek(self, topic: str, partition: int, offset: int) -> None:
        """Reposition the fetch cursor (kafka consumer seek): the MVCC
        pump resumes partitions from the offsets its admitted layers
        already cover, not from the group's committed offset — those
        only commit inside the cutover fence (mvcc/pump.py)."""
        if topic == self.topic and partition in self.positions:
            self.positions[partition] = int(offset)

    def close(self) -> None:
        pass


class MQSinker(Sinker, StagedSinker):
    """Queue sink: serialize rows, partition by key/column hash
    (reference kafka/sink.go + writer/).

    Staged-commit capable (abstract/commit.py): with an open part stage
    the serialized messages buffer sink-side and land in the broker
    through one epoch-fenced `MemoryBroker.publish_part` transaction —
    the in-memory twin of a kafka transactional produce tied to the
    offset-commit path."""

    def __init__(self, params: MQTargetParams):
        self.params = params
        self.broker = get_broker(params.broker_id, params.n_partitions)
        self.serializer = make_queue_serializer(
            params.serializer, **(params.serializer_config or {})
        )
        self._stage = None  # staging.PartStage when open
        self._staged_messages: list[tuple] = []

    def _messages_for(self, batch: Batch) -> list[tuple]:
        """Serialize one batch to (topic, partition, key, value)
        message tuples (partition None = key hash at produce time)."""
        from transferia_tpu.abstract.interfaces import is_columnar

        pairs = self.serializer.serialize_messages(batch)
        partitions: Optional[list[int]] = None
        if is_columnar(batch):
            topic = self.params.topic or str(batch.table_id)
            # column-hash partitioning is only sound when pairs align 1:1
            # with rows; serializers may expand rows (e.g. debezium
            # tombstones), in which case fall back to key-hash so a
            # tombstone always lands in its delete's partition
            if self.params.partition_by and \
                    self.params.partition_by in batch.columns and \
                    len(pairs) == batch.n_rows:
                partitions = hash_column_to_shards(
                    batch.column(self.params.partition_by),
                    self.params.n_partitions,
                ).tolist()
        else:
            rows = [it for it in batch if it.is_row_event()]
            topic = self.params.topic or (
                str(rows[0].table_id) if rows else "controls"
            )
        return [
            (topic, partitions[i] if partitions is not None else None,
             key, value)
            for i, (key, value) in enumerate(pairs)
        ]

    def push(self, batch: Batch) -> None:
        if self._stage is not None:
            batch = self._stage.stage(batch)
            try:
                self._staged_messages.extend(self._messages_for(batch))
            except BaseException:
                # serialization died after the dedup window recorded
                # the batch: only a full part restage is safe
                self._stage.mark_failed()
                raise
            return
        for topic, partition, key, value in self._messages_for(batch):
            self.broker.produce(topic, key, value, partition=partition)

    # -- StagedSinker -------------------------------------------------------
    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import PartStage

        # hold=False: the serialized message list is the buffer; the
        # PartStage only runs the dedup window over the pushed batches
        self._stage = PartStage(key, epoch, hold=False)
        self._staged_messages = []

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.providers.staging import publish_guard

        if self._stage is None:
            raise RuntimeError(f"mq sink: no open stage for {key!r}")
        with publish_guard(key, epoch):
            n = self.broker.publish_part(key, epoch,
                                         self._staged_messages)
        self.last_dedup_dropped = self._stage.dedup_dropped
        self._stage = None
        self._staged_messages = []
        return n

    def abort_part(self, key: str) -> None:
        self._stage = None
        self._staged_messages = []

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.note_push_retry()


@register_provider
class MQProvider(Provider):
    NAME = "mq"

    def source(self):
        p = self.transfer.src
        client = _MQClient(p)
        return QueueSource(client, p.parser, parallelism=p.parallelism,
                           metrics=self.metrics,
                           transfer_id=self.transfer.id)

    def sinker(self):
        return MQSinker(self.transfer.dst)
