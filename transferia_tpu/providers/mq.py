"""In-memory message broker provider.

The local stand-in for Kafka-shaped sources/sinks (reference test recipes
spin up real brokers via testcontainers, tests/tcrecipes/ — this image has
no docker, so the broker lives in-process).  It exercises the exact queue
replication machinery (QueueSource: sequencer + parsequeue + post-push
offset commits; queue sink: serializer + key-hash partitioning) that the
kafka provider shares.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.interfaces import Batch, Sinker
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.parsers import Message
from transferia_tpu.providers.queue_common import FetchedBatch, QueueSource
from transferia_tpu.providers.registry import Provider, register_provider
from transferia_tpu.serializers import make_queue_serializer
from transferia_tpu.transform.plugins.sharder import hash_column_to_shards


class MemoryBroker:
    """topic -> partition -> list of (key, value, timestamp_ns)."""

    def __init__(self, n_partitions: int = 1):
        self.lock = threading.RLock()
        self.n_partitions = n_partitions
        self.topics: dict[str, list[list[tuple]]] = {}
        self.committed: dict[tuple[str, str, int], int] = {}  # (group,t,p)

    def _topic(self, name: str) -> list[list[tuple]]:
        with self.lock:
            if name not in self.topics:
                self.topics[name] = [[] for _ in range(self.n_partitions)]
            return self.topics[name]

    def produce(self, topic: str, key: bytes, value: Optional[bytes],
                partition: Optional[int] = None) -> None:
        parts = self._topic(topic)
        if partition is None:
            partition = (hash(bytes(key or b"")) & 0x7FFFFFFF) % len(parts)
        with self.lock:
            parts[partition % len(parts)].append(
                (key, value, time.time_ns())
            )

    def fetch_from(self, topic: str, partition: int, offset: int,
                   max_messages: int) -> list[Message]:
        parts = self._topic(topic)
        with self.lock:
            rows = parts[partition][offset:offset + max_messages]
        return [
            Message(value=v if v is not None else b"", key=k or b"",
                    topic=topic, partition=partition, offset=offset + i,
                    write_time_ns=ts)
            for i, (k, v, ts) in enumerate(rows)
        ]

    def commit(self, group: str, topic: str, partition: int,
               offset: int) -> None:
        with self.lock:
            self.committed[(group, topic, partition)] = offset

    def committed_offset(self, group: str, topic: str,
                         partition: int) -> int:
        with self.lock:
            return self.committed.get((group, topic, partition), -1)

    def size(self, topic: str) -> int:
        parts = self._topic(topic)
        with self.lock:
            return sum(len(p) for p in parts)


_BROKERS: dict[str, MemoryBroker] = {}


def get_broker(broker_id: str, n_partitions: int = 1) -> MemoryBroker:
    if broker_id not in _BROKERS:
        _BROKERS[broker_id] = MemoryBroker(n_partitions)
    return _BROKERS[broker_id]


@register_endpoint
@dataclass
class MQSourceParams(EndpointParams):
    PROVIDER = "mq"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    broker_id: str = "default"
    topic: str = "topic"
    group: str = "transfer"
    parser: Optional[dict] = None          # one-of parser config
    parallelism: int = 4
    n_partitions: int = 1

    def parser_config(self):
        return self.parser


@register_endpoint
@dataclass
class MQTargetParams(EndpointParams):
    PROVIDER = "mq"
    IS_TARGET = True

    broker_id: str = "default"
    topic: str = ""                # empty -> per-table "<ns>.<name>"
    serializer: str = "json"       # json | native | debezium | mirror
    serializer_config: dict = field(default_factory=dict)
    n_partitions: int = 1
    partition_by: str = ""         # column for shard hashing; "" = key hash


class _MQClient:
    """QueueSource client over a MemoryBroker consumer group."""

    def __init__(self, params: MQSourceParams):
        self.broker = get_broker(params.broker_id, params.n_partitions)
        self.topic = params.topic
        self.group = params.group
        self.positions = {
            p: self.broker.committed_offset(self.group, self.topic, p) + 1
            for p in range(params.n_partitions)
        }

    def fetch(self, max_messages: int = 1024) -> list[FetchedBatch]:
        out = []
        for p, pos in self.positions.items():
            msgs = self.broker.fetch_from(self.topic, p, pos, max_messages)
            if msgs:
                self.positions[p] = msgs[-1].offset + 1
                out.append(FetchedBatch(self.topic, p, msgs))
        return out

    def commit(self, topic: str, partition: int, offset: int) -> None:
        self.broker.commit(self.group, topic, partition, offset)

    def close(self) -> None:
        pass


class MQSinker(Sinker):
    """Queue sink: serialize rows, partition by key/column hash
    (reference kafka/sink.go + writer/)."""

    def __init__(self, params: MQTargetParams):
        self.params = params
        self.broker = get_broker(params.broker_id, params.n_partitions)
        self.serializer = make_queue_serializer(
            params.serializer, **(params.serializer_config or {})
        )

    def push(self, batch: Batch) -> None:
        from transferia_tpu.abstract.interfaces import is_columnar

        pairs = self.serializer.serialize_messages(batch)
        partitions: Optional[list[int]] = None
        if is_columnar(batch):
            topic = self.params.topic or str(batch.table_id)
            # column-hash partitioning is only sound when pairs align 1:1
            # with rows; serializers may expand rows (e.g. debezium
            # tombstones), in which case fall back to key-hash so a
            # tombstone always lands in its delete's partition
            if self.params.partition_by and \
                    self.params.partition_by in batch.columns and \
                    len(pairs) == batch.n_rows:
                partitions = hash_column_to_shards(
                    batch.column(self.params.partition_by),
                    self.params.n_partitions,
                ).tolist()
        else:
            rows = [it for it in batch if it.is_row_event()]
            topic = self.params.topic or (
                str(rows[0].table_id) if rows else "controls"
            )
        for i, (key, value) in enumerate(pairs):
            self.broker.produce(
                topic, key, value,
                partition=partitions[i] if partitions is not None else None,
            )


@register_provider
class MQProvider(Provider):
    NAME = "mq"

    def source(self):
        p = self.transfer.src
        client = _MQClient(p)
        return QueueSource(client, p.parser, parallelism=p.parallelism,
                           metrics=self.metrics)

    def sinker(self):
        return MQSinker(self.transfer.dst)
