"""YDB provider: snapshot storage, changefeed CDC source, bulk-upsert sink.

Reference parity: pkg/providers/ydb/ (7.3 KLoC) — storage.go (snapshot
reads), storage_sharded.go (key-range sharding), source.go + cdc_event.go
(changefeed JSON events from a topic), sink.go (BulkUpsert writer),
typesystem.go.  The transport is the dependency-free gRPC client
(client.py + wire.py) instead of ydb-go-sdk.

Changefeed events are the documented YDB CDC JSON records
(cdc_event.go:5-12): {"key": [...], "update": {...}, "erase": {...},
"newImage": {...}, "ts": [step, txId]} read from the feed's topic
`<table>/<feed>`; commits ride the same read session after a durable
sink push (at-least-once, source.go:180-211).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    AsyncSink,
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Source,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.providers.ydb import wire as w
from transferia_tpu.providers.ydb.client import (
    YdbClient,
    YdbError,
    yql_quote_ident as _q,
)
from transferia_tpu.typesystem.rules import (
    map_target_type,
    register_source_rules,
    register_target_rules,
)

logger = logging.getLogger(__name__)

# -- typesystem (pkg/providers/ydb/typesystem.go) ----------------------------
# single source of truth: (ydb type name, wire type id, canonical type)

_YDB_TYPES = [
    ("Bool", w.T_BOOL, CanonicalType.BOOLEAN),
    ("Int8", w.T_INT8, CanonicalType.INT8),
    ("Int16", w.T_INT16, CanonicalType.INT16),
    ("Int32", w.T_INT32, CanonicalType.INT32),
    ("Int64", w.T_INT64, CanonicalType.INT64),
    ("Uint8", w.T_UINT8, CanonicalType.UINT8),
    ("Uint16", w.T_UINT16, CanonicalType.UINT16),
    ("Uint32", w.T_UINT32, CanonicalType.UINT32),
    ("Uint64", w.T_UINT64, CanonicalType.UINT64),
    ("Float", w.T_FLOAT, CanonicalType.FLOAT),
    ("Double", w.T_DOUBLE, CanonicalType.DOUBLE),
    ("String", w.T_STRING, CanonicalType.STRING),
    ("Utf8", w.T_UTF8, CanonicalType.UTF8),
    ("Json", w.T_JSON, CanonicalType.ANY),
    ("JsonDocument", w.T_JSON_DOCUMENT, CanonicalType.ANY),
    ("Date", w.T_DATE, CanonicalType.DATE),
    ("Datetime", w.T_DATETIME, CanonicalType.DATETIME),
    ("Timestamp", w.T_TIMESTAMP, CanonicalType.TIMESTAMP),
    ("Interval", w.T_INTERVAL, CanonicalType.INTERVAL),
]
_YDB_TO_CANONICAL = {name: canon for name, _tid, canon in _YDB_TYPES}
_NAME_BY_ID = {tid: name for name, tid, _canon in _YDB_TYPES}
register_source_rules("ydb", _YDB_TO_CANONICAL)
register_target_rules("ydb", {
    CanonicalType.BOOLEAN: "Bool",
    CanonicalType.INT8: "Int8",
    CanonicalType.INT16: "Int16",
    CanonicalType.INT32: "Int32",
    CanonicalType.INT64: "Int64",
    CanonicalType.UINT8: "Uint8",
    CanonicalType.UINT16: "Uint16",
    CanonicalType.UINT32: "Uint32",
    CanonicalType.UINT64: "Uint64",
    CanonicalType.FLOAT: "Float",
    CanonicalType.DOUBLE: "Double",
    CanonicalType.STRING: "String",
    CanonicalType.UTF8: "Utf8",
    CanonicalType.ANY: "JsonDocument",
    CanonicalType.DECIMAL: "Utf8",
    CanonicalType.DATE: "Date",
    CanonicalType.DATETIME: "Datetime",
    CanonicalType.TIMESTAMP: "Timestamp",
    CanonicalType.INTERVAL: "Interval",
    "*": "Utf8",
})

_PRIMITIVE_BY_ID = {tid: canon for _name, tid, canon in _YDB_TYPES}
_ID_BY_CANONICAL = {v: k for k, v in _PRIMITIVE_BY_ID.items()}
_ID_BY_CANONICAL[CanonicalType.ANY] = w.T_JSON_DOCUMENT
_ID_BY_CANONICAL[CanonicalType.DECIMAL] = w.T_UTF8


def _wire_type_to_canonical(t) -> CanonicalType:
    kind, info = t
    if kind == "optional":
        return _wire_type_to_canonical(info)
    if kind == "primitive":
        return _PRIMITIVE_BY_ID.get(info, CanonicalType.ANY)
    return CanonicalType.ANY


# -- endpoint params ---------------------------------------------------------


@register_endpoint
@dataclass
class YdbSourceParams(EndpointParams):
    PROVIDER = "ydb"
    IS_SOURCE = True

    endpoint: str = ""          # host:port (grpc)
    database: str = ""
    auth_token: str = ""
    tables: list = field(default_factory=list)  # paths relative to db root
    batch_rows: int = 10_000
    shard_parts: int = 0        # split snapshot by key ranges when > 1
    changefeed: str = "updates"  # feed name for CDC
    consumer: str = "transferia"


@register_endpoint
@dataclass
class YdbTargetParams(EndpointParams):
    PROVIDER = "ydb"
    IS_TARGET = True

    endpoint: str = ""
    database: str = ""
    auth_token: str = ""
    cleanup: str = "drop"       # drop | truncate | disabled


def _full_path(database: str, table: str) -> str:
    return f"{database.rstrip('/')}/{table.lstrip('/')}"


# -- snapshot storage (storage.go / storage_sharded.go) ----------------------


class YdbStorage(Storage, ShardingStorage):
    def __init__(self, params: YdbSourceParams):
        self.params = params
        self._client: Optional[YdbClient] = None
        self._schemas: dict[TableID, TableSchema] = {}
        self._keys: dict[TableID, list[str]] = {}

    @property
    def client(self) -> YdbClient:
        if self._client is None:
            self._client = YdbClient(self.params.endpoint,
                                     self.params.database,
                                     self.params.auth_token)
        return self._client

    def _table_paths(self) -> list[str]:
        if self.params.tables:
            return list(self.params.tables)
        out = []

        def walk(prefix: str):
            for entry in self.client.list_directory(
                    _full_path(self.params.database, prefix) if prefix
                    else self.params.database):
                name = f"{prefix}/{entry['name']}" if prefix \
                    else entry["name"]
                if entry["type"] == 2:        # TABLE
                    out.append(name)
                elif entry["type"] == 1:      # DIRECTORY
                    walk(name)

        walk("")
        from transferia_tpu.providers.staging import is_meta_name

        return sorted(p for p in out
                      if not is_meta_name(p.rsplit("/", 1)[-1]))

    def _tid(self, path: str) -> TableID:
        if "/" in path:
            ns, name = path.rsplit("/", 1)
        else:
            ns, name = "", path
        return TableID(ns, name)

    def _path(self, tid: TableID) -> str:
        return f"{tid.namespace}/{tid.name}" if tid.namespace else tid.name

    def table_schema(self, table: TableID) -> TableSchema:
        if table not in self._schemas:
            from transferia_tpu.providers.staging import is_meta_name

            desc = self.client.describe_table(
                _full_path(self.params.database, self._path(table)))
            pkey = set(desc["primary_key"])
            cols = [
                ColSchema(name, _wire_type_to_canonical(t),
                          primary_key=name in pkey,
                          original_type=f"ydb:{_type_name(t)}")
                for name, t in desc["columns"]
                if not is_meta_name(name)
            ]
            self._schemas[table] = TableSchema(cols)
            self._keys[table] = desc["primary_key"]
        return self._schemas[table]

    def table_list(self, include=None):
        out = {}
        for path in self._table_paths():
            tid = self._tid(path)
            if include and not any(tid.include_matches(p)
                                   for p in include):
                continue
            out[tid] = TableInfo(eta_rows=0,
                                 schema=self.table_schema(tid))
        return out

    def estimate_table_rows_count(self, table: TableID) -> int:
        return 0

    def shard_table(self, table: TableDescription
                    ) -> list[TableDescription]:
        parts = self.params.shard_parts
        if parts <= 1:
            return [table]
        # key-range split on the first (integer) key column, like
        # storage_sharded.go splits by uniform key ranges
        self.table_schema(table.id)
        keys = self._keys.get(table.id) or []
        if not keys:
            return [table]
        k = keys[0]
        rows = self.client.execute_query(
            f"SELECT MIN({_q(k)}) AS lo, MAX({_q(k)}) AS hi "
            f"FROM {_q(self._path(table.id))}")
        if not rows or not rows[0]["rows"]:
            return [table]
        lo, hi = rows[0]["rows"][0]
        if lo is None or hi is None or not isinstance(lo, int):
            return [table]
        span = (hi - lo + 1 + parts - 1) // parts
        out = []
        for i in range(parts):
            a, b = lo + i * span, min(hi + 1, lo + (i + 1) * span)
            if a >= b:
                break
            out.append(TableDescription(
                id=table.id, filter=f"range:{k}:{a}:{b}",
                eta_rows=table.eta_rows // parts))
        return out or [table]

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        schema = self.table_schema(table.id)
        keys = self._keys.get(table.id) or [schema.names()[0]]
        where = ""
        if table.filter.startswith("range:"):
            _, k, a, b = table.filter.split(":", 3)
            where = f"WHERE {_q(k)} >= {a} AND {_q(k)} < {b}"
        order = ", ".join(_q(k) for k in keys)
        cursor = None
        names = schema.names()
        while True:
            cond = where
            if cursor is not None:
                kexpr = self._cursor_cond(keys, cursor)
                cond = (f"{where} AND {kexpr}" if where
                        else f"WHERE {kexpr}")
            yql = (f"SELECT {', '.join(_q(n) for n in names)} "
                   f"FROM {_q(self._path(table.id))} {cond} "
                   f"ORDER BY {order} LIMIT {self.params.batch_rows}")
            rs = self.client.execute_query(yql)
            rows = rs[0]["rows"] if rs else []
            if not rows:
                return
            data = {n: [r[i] for r in rows]
                    for i, n in enumerate(names)}
            pusher(ColumnBatch.from_pydict(table.id, schema, data))
            if len(rows) < self.params.batch_rows:
                return
            cursor = {k: rows[-1][names.index(k)] for k in keys}

    @staticmethod
    def _cursor_cond(keys: list[str], cursor: dict) -> str:
        from transferia_tpu.providers.ydb.client import yql_literal

        # lexicographic keyset pagination over the pk tuple
        parts = []
        for i in range(len(keys)):
            eqs = [f"{_q(keys[j])} = {yql_literal(cursor[keys[j]])}"
                   for j in range(i)]
            eqs.append(f"{_q(keys[i])} > {yql_literal(cursor[keys[i]])}")
            parts.append("(" + " AND ".join(eqs) + ")")
        return "(" + " OR ".join(parts) + ")"

    def ping(self) -> None:
        self.client.list_directory(self.params.database)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()


def _type_name(t) -> str:
    kind, info = t
    if kind == "optional":
        return _type_name(info)
    if kind == "primitive":
        return _NAME_BY_ID.get(info, "Utf8")
    return "Utf8"


# -- sink (sink.go: BulkUpsert writer) ---------------------------------------


class YdbSinker(Sinker, StagedSinker):
    """BulkUpsert sink.

    Staged-commit capable (abstract/commit.py): with an open part stage
    batches bulk-upsert into a per-(part, epoch) staging table and the
    publish is ONE interactive transaction (a single multi-statement
    ExecuteDataQuery) carrying the part epoch in a commit-marker row:
    DELETE this part's previous rows from the final table (addressed by
    the hidden `__trtpu_part` column), UPSERT the staged rows in, and
    UPSERT the `(part_key, epoch)` marker into `__trtpu_commits` —
    append-only (PK includes the epoch), so the max-epoch fence a
    zombie's stale-epoch publish breaks on can never regress.  Same
    fence bound as the PG sink: the epoch check reads before the
    publish txn — the coordinator's commit_part keeps two LIVE owners
    apart; this fence is the late-zombie backstop."""

    def __init__(self, params: YdbTargetParams):
        self.params = params
        self.client = YdbClient(params.endpoint, params.database,
                                params.auth_token)
        self._created: set[TableID] = set()
        self._stage = None  # staging.WireStage when open
        self._fence_ready = False

    def _path(self, tid: TableID) -> str:
        name = f"{tid.namespace}/{tid.name}" if tid.namespace \
            else tid.name
        return _full_path(self.params.database, name)

    def _ensure_table(self, tid: TableID, schema: TableSchema) -> None:
        if tid in self._created:
            return
        from transferia_tpu.providers.staging import META_COLUMN

        cols = [
            f"{_q(c.name)} {map_target_type('ydb', c.data_type, 'Utf8')}"
            for c in schema
        ]
        if self._stage is not None:
            # staged lifecycle: the final table carries the hidden part
            # column the transactional publish replaces by
            cols.append(f"{_q(META_COLUMN)} Utf8")
        keys = [c.name for c in schema if c.primary_key] \
            or [schema.names()[0]]
        ddl = (f"CREATE TABLE IF NOT EXISTS {_q(self._path(tid))} "
               f"({', '.join(cols)}, "
               f"PRIMARY KEY ({', '.join(_q(k) for k in keys)}))")
        self.client.execute_scheme(ddl)
        self._created.add(tid)

    def _cleanup(self, tid: TableID) -> None:
        if self.params.cleanup == "disabled":
            return
        try:
            if self.params.cleanup == "truncate":
                self.client.execute_query(
                    f"DELETE FROM {_q(self._path(tid))}")
            else:
                self.client.execute_scheme(
                    f"DROP TABLE {_q(self._path(tid))}")
                self._created.discard(tid)
        except (YdbError, w.YdbOperationError):
            pass  # absent table

    def push(self, batch: Batch) -> None:
        if is_columnar(batch):
            if self._stage is not None:
                self._stage_push(batch)
                return
            self._push_rows(batch.table_id, batch.schema, batch.to_rows())
            return
        items = list(batch)
        rows: list[ChangeItem] = []
        for it in items:
            if it.kind in (Kind.INIT_TABLE_LOAD, Kind.INIT_SHARDED_TABLE_LOAD):
                if it.table_schema is not None:
                    if (it.kind == Kind.INIT_SHARDED_TABLE_LOAD or
                            not it.part_id) and self._stage is None:
                        # staged lifecycle skips the drop: "publish
                        # replaces" makes it unnecessary, and a zombie's
                        # late init must never destroy the survivor's
                        # published rows
                        self._cleanup(it.table_id)
                    self._ensure_table(it.table_id, it.table_schema)
                continue
            if not it.is_row_event():
                continue
            rows.append(it)
        if rows and self._stage is not None:
            # row-event batches under an open stage route through the
            # staging table like every other sink — a direct write
            # would be visible pre-publish and unaddressable by the
            # publish's DELETE (no part column)
            self._stage_push(ColumnBatch.from_rows(rows))
            return
        if rows:
            by_table: dict[TableID, list[ChangeItem]] = {}
            for it in rows:
                by_table.setdefault(it.table_id, []).append(it)
            for tid, its in by_table.items():
                self._push_rows(tid, its[0].table_schema, its)

    def _push_rows(self, tid: TableID, schema: TableSchema,
                   items: list[ChangeItem]) -> None:
        """Apply row events IN STREAM ORDER: consecutive upserts batch
        into one BulkUpsert, deletes flush the pending batch first — a
        [erase k, re-insert k] sequence must not end with k missing."""
        if schema is None or not items:
            return
        self._ensure_table(tid, schema)
        pending: list[ChangeItem] = []
        for it in items:
            if it.kind in (Kind.INSERT, Kind.UPDATE):
                pending.append(it)
                continue
            if it.kind == Kind.DELETE:
                if pending:
                    self._bulk_upsert(tid, schema, pending)
                    pending = []
                self._delete(tid, schema, it)
        if pending:
            self._bulk_upsert(tid, schema, pending)

    def _bulk_upsert(self, tid: TableID, schema: TableSchema,
                     upserts: list[ChangeItem],
                     path: Optional[str] = None) -> None:
        members = []
        type_ids = []
        for c in schema:
            type_id = _ID_BY_CANONICAL.get(c.data_type, w.T_UTF8)
            members.append((c.name, w.type_optional(
                w.type_primitive(type_id))))
            type_ids.append(type_id)
        row_type = w.type_struct(members)
        rows = []
        for it in upserts:
            vals = it.as_dict()
            parts = []
            for c, type_id in zip(schema, type_ids):
                v = vals.get(c.name)
                if v is None:
                    parts.append(w.value_null())
                else:
                    if c.data_type == CanonicalType.ANY and \
                            not isinstance(v, str):
                        v = json.dumps(v)
                    parts.append(w.value_primitive(type_id, v))
            rows.append(w.value_items(parts))
        self.client.bulk_upsert(path or self._path(tid), row_type, rows)

    def _delete(self, tid: TableID, schema: TableSchema,
                it: ChangeItem) -> None:
        from transferia_tpu.providers.ydb.client import yql_literal

        if it.old_keys.key_names:
            key_map = dict(zip(it.old_keys.key_names,
                               it.old_keys.key_values))
        else:
            vals = it.as_dict()
            names = [c.name for c in schema.key_columns()] \
                or list(vals)
            key_map = {n: vals.get(n) for n in names}
        cond = " AND ".join(
            f"{_q(k)} = {yql_literal(v)}"
            for k, v in key_map.items())
        self.client.execute_query(
            f"DELETE FROM {_q(self._path(tid))} WHERE {cond}")

    def close(self) -> None:
        self.client.close()

    # -- StagedSinker (publish = one interactive transaction) ---------------
    def _stage_path(self, stage) -> str:
        return _full_path(self.params.database, stage.table)

    def _commits_path(self) -> str:
        from transferia_tpu.providers.staging import COMMITS_TABLE

        return _full_path(self.params.database, COMMITS_TABLE)

    def _ensure_fence_table(self) -> None:
        if self._fence_ready:
            return
        # APPEND-ONLY fence (PK includes the epoch): a zombie's marker
        # row never regresses the max-epoch fence value
        self.client.execute_scheme(
            f"CREATE TABLE IF NOT EXISTS {_q(self._commits_path())} "
            f"(`part_key` Utf8, `epoch` Int64, "
            f"PRIMARY KEY (`part_key`, `epoch`))")
        self._fence_ready = True

    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import (
            WireStage,
            stage_ident_prefix,
        )

        stage = WireStage(key, epoch)
        # begin replaces — for EVERY epoch of this key (a crashed
        # earlier owner's staging table would otherwise leak forever)
        pfx = stage_ident_prefix(key)
        try:
            names = [e["name"] for e in
                     self.client.list_directory(self.params.database)
                     if e["type"] == 2]
        except (YdbError, w.YdbOperationError):
            names = [stage.table]  # listing degraded: current only
        for name in names:
            if not name.startswith(pfx):
                continue
            try:
                self.client.execute_scheme(
                    f"DROP TABLE "
                    f"{_q(_full_path(self.params.database, name))}")
            except (YdbError, w.YdbOperationError):
                pass  # raced another sweeper / absent
        self._ensure_fence_table()
        self._stage = stage

    def _stage_push(self, batch) -> None:
        stage = self._stage
        staged = stage.state.stage(batch)
        if stage.schema is None:
            stage.tid = batch.table_id
            stage.schema = batch.schema
            cols = ", ".join(
                f"{_q(c.name)} "
                f"{map_target_type('ydb', c.data_type, 'Utf8')}"
                for c in batch.schema)
            keys = [c.name for c in batch.schema if c.primary_key] \
                or [batch.schema.names()[0]]
            self.client.execute_scheme(
                f"CREATE TABLE IF NOT EXISTS "
                f"{_q(self._stage_path(stage))} ({cols}, "
                f"PRIMARY KEY ({', '.join(_q(k) for k in keys)}))")
        if staged.n_rows == 0:
            return
        try:
            self._bulk_upsert(stage.tid, stage.schema, staged.to_rows(),
                              path=self._stage_path(stage))
        except BaseException:
            # the staging write died after the dedup window recorded
            # this batch: only a full part restage is safe
            stage.state.mark_failed()
            raise

    def _fence_epoch(self, slug: str):
        from transferia_tpu.providers.ydb.client import yql_literal

        rs = self.client.execute_query(
            f"SELECT `epoch` FROM {_q(self._commits_path())} "
            f"WHERE `part_key` = {yql_literal(slug)}")
        rows = rs[0]["rows"] if rs else []
        epochs = [int(r[0]) for r in rows if r and r[0] is not None]
        return max(epochs) if epochs else None

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.abstract.errors import StaleEpochPublishError
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.providers.staging import (
            META_COLUMN,
            publish_guard,
        )
        from transferia_tpu.providers.ydb.client import yql_literal
        from transferia_tpu.stats import trace

        stage = self._stage
        if stage is None or stage.key != key:
            raise RuntimeError(f"ydb sink: no open stage for {key!r}")
        with publish_guard(key, epoch):
            prev = self._fence_epoch(stage.slug)
            if prev is not None and epoch < prev:
                raise StaleEpochPublishError(key, epoch, prev)
            trace.instant("ydb_publish_txn", part=key, epoch=epoch,
                          rows=stage.state.rows)
            failpoint("sink.ydb.publish")
            slug_lit = yql_literal(stage.slug)
            stmts = []
            if stage.schema is not None:
                self._ensure_table(stage.tid, stage.schema)
                final = _q(self._path(stage.tid))
                try:
                    # retrofit the part column onto a final table an
                    # at-least-once run created (idempotent: an
                    # already-present column errors and is ignored)
                    self.client.execute_scheme(
                        f"ALTER TABLE {final} ADD COLUMN "
                        f"{_q(META_COLUMN)} Utf8")
                except (YdbError, w.YdbOperationError):
                    pass  # column already exists
                stmts.append(
                    f"DELETE FROM {final} "
                    f"WHERE {_q(META_COLUMN)} = {slug_lit}")
                stmts.append(
                    f"UPSERT INTO {final} "
                    f"SELECT *, {slug_lit} AS {_q(META_COLUMN)} "
                    f"FROM {_q(self._stage_path(stage))}")
            stmts.append(
                f"UPSERT INTO {_q(self._commits_path())} "
                f"(`part_key`, `epoch`) VALUES ({slug_lit}, {epoch})")
            # one interactive transaction: the part's replacement and
            # its commit-marker row land atomically or not at all
            self.client.execute_query(";\n".join(stmts))
            try:
                self.client.execute_scheme(
                    f"DROP TABLE {_q(self._stage_path(stage))}")
            except (YdbError, w.YdbOperationError):
                pass  # empty part never created the staging table
            self.last_dedup_dropped = stage.state.dedup_dropped
            rows = stage.state.rows
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        stage = self._stage
        if stage is None or stage.key != key:
            return
        self._stage = None
        try:
            self.client.execute_scheme(
                f"DROP TABLE {_q(self._stage_path(stage))}")
        except (YdbError, w.YdbOperationError):
            pass  # nothing staged

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.state.note_push_retry()


# -- changefeed CDC source (source.go + cdc_converter.go) --------------------


class YdbChangefeedSource(Source):
    def __init__(self, params: YdbSourceParams, transfer_id: str,
                 coordinator: Optional[Coordinator]):
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self._stop = threading.Event()
        self.storage = YdbStorage(params)

    def run(self, sink: AsyncSink) -> None:
        if not self.params.tables:
            raise YdbError("ydb replication needs explicit tables")
        sessions = []
        try:
            for path in self.params.tables:
                topic = _full_path(
                    self.params.database,
                    f"{path}/{self.params.changefeed}")
                sessions.append((
                    path,
                    self.storage.client.topic_read_session(
                        topic, self.params.consumer),
                ))
            while not self._stop.is_set():
                idle = True
                for path, session in sessions:
                    batch = session.read_batch(timeout=0.2)
                    if not batch:
                        continue
                    idle = False
                    items = []
                    max_off: dict[int, int] = {}
                    tid = self.storage._tid(path)
                    schema = self.storage.table_schema(tid)
                    keys = self.storage._keys[tid]
                    for psid, offset, data in batch:
                        item = self._convert(tid, schema, keys, data)
                        if item is not None:
                            items.append(item)
                        max_off[psid] = max(max_off.get(psid, -1),
                                            offset)
                    if items:
                        sink.async_push(items).result()
                    # at-least-once: commit offsets only after the push
                    for psid, off in max_off.items():
                        session.commit(psid, off + 1)
                if idle:
                    self._stop.wait(0.05)
        finally:
            for _, session in sessions:
                session.close()
            self.storage.close()

    def _convert(self, tid: TableID, schema: TableSchema,
                 keys: list[str], data: bytes) -> Optional[ChangeItem]:
        """cdc_event.go JSON record -> ChangeItem (cdc_converter.go)."""
        try:
            ev = json.loads(data)
        except ValueError:
            logger.warning("undecodable changefeed event: %r", data[:100])
            return None
        key_vals = ev.get("key") or []
        key_dict = dict(zip(keys, key_vals))
        ts = ev.get("ts") or [0, 0]
        if "erase" in ev:
            return ChangeItem(
                kind=Kind.DELETE, schema=tid.namespace, table=tid.name,
                column_names=tuple(keys),
                column_values=tuple(key_vals),
                table_schema=schema,
                lsn=int(ts[0]) if ts else 0,
            )
        new = ev.get("newImage") or ev.get("update") or {}
        row = dict(key_dict)
        row.update(new)
        # changefeed JSON base64-encodes String (bytes) columns
        # (cdc_converter.go does the same inverse mapping)
        import base64

        for c in schema:
            if c.data_type == CanonicalType.STRING and \
                    isinstance(row.get(c.name), str):
                try:
                    row[c.name] = base64.b64decode(row[c.name])
                except Exception as e:
                    # keep the raw string; the sink will surface a type
                    # error if it actually matters downstream
                    logger.debug("changefeed column %s: not valid "
                                 "base64 (%s); keeping raw value",
                                 c.name, e)
        names = [n for n in schema.names() if n in row]
        return ChangeItem(
            kind=Kind.UPDATE if ev.get("update") is not None
            or ev.get("newImage") is not None else Kind.INSERT,
            schema=tid.namespace, table=tid.name,
            column_names=tuple(names),
            column_values=tuple(row[n] for n in names),
            table_schema=schema,
            lsn=int(ts[0]) if ts else 0,
        )

    def stop(self) -> None:
        self._stop.set()


# -- provider ---------------------------------------------------------------


@register_provider
class YdbProvider(Provider):
    NAME = "ydb"

    def storage(self):
        if isinstance(self.transfer.src, YdbSourceParams):
            return YdbStorage(self.transfer.src)
        return None

    def source(self):
        if isinstance(self.transfer.src, YdbSourceParams):
            return YdbChangefeedSource(self.transfer.src,
                                       self.transfer.id,
                                       self.coordinator)
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, YdbTargetParams):
            return YdbSinker(self.transfer.dst)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            if isinstance(self.transfer.src, YdbSourceParams):
                YdbStorage(self.transfer.src).ping()
            result.add("connect")
        except Exception as e:
            result.add("connect", e)
        return result
