"""Hand-rolled protobuf wire codec for the YDB gRPC API subset.

The client encodes/decodes YDB API messages directly at the protobuf wire
level (varint/fixed/length-delimited) — the same dependency-free style as
the MySQL/Mongo/Kafka wire clients.  Field numbers and enums follow the
public ydb-api-protos definitions (Ydb.Value/Type, table/scheme services,
operation envelope); the in-repo fake server decodes with
protoc-generated code from tests/recipes/ydb_protos/*.proto, so the hand
codec is cross-validated against an independent parser.

Reference being re-implemented: pkg/providers/ydb/ uses ydb-go-sdk; this
framework talks the API without an SDK.
"""

from __future__ import annotations

import struct
from typing import Any, Iterator, Optional

# -- generic protobuf wire ---------------------------------------------------

VARINT, FIXED64, BYTES, FIXED32 = 0, 1, 2, 5


def _varint(n: int) -> bytes:
    out = bytearray()
    if n < 0:
        n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def field(num: int, wire: int, payload) -> bytes:
    tag = _varint((num << 3) | wire)
    if wire == VARINT:
        return tag + _varint(payload)
    if wire == FIXED64:
        return tag + struct.pack("<Q", payload & (1 << 64) - 1)
    if wire == FIXED32:
        return tag + struct.pack("<I", payload & (1 << 32) - 1)
    return tag + _varint(len(payload)) + payload


def f_varint(num: int, value: int) -> bytes:
    return field(num, VARINT, value)


def f_bool(num: int, value: bool) -> bytes:
    return field(num, VARINT, 1 if value else 0)


def f_bytes(num: int, value: bytes) -> bytes:
    return field(num, BYTES, value)


def f_str(num: int, value: str) -> bytes:
    return field(num, BYTES, value.encode())


def f_msg(num: int, value: bytes) -> bytes:
    return field(num, BYTES, value)


def f_double(num: int, value: float) -> bytes:
    return field(num, FIXED64, struct.unpack("<Q",
                                             struct.pack("<d", value))[0])


def f_float(num: int, value: float) -> bytes:
    return field(num, FIXED32, struct.unpack("<I",
                                             struct.pack("<f", value))[0])


def read_varint(data: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint overflow")


def iter_fields(data: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over a message's fields."""
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = read_varint(data, pos)
        num, wire = tag >> 3, tag & 7
        if wire == VARINT:
            val, pos = read_varint(data, pos)
        elif wire == FIXED64:
            val = struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        elif wire == FIXED32:
            val = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        elif wire == BYTES:
            ln, pos = read_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield num, wire, val


def fields_dict(data: bytes) -> dict[int, list]:
    out: dict[int, list] = {}
    for num, _wire, val in iter_fields(data):
        out.setdefault(num, []).append(val)
    return out


def first(fd: dict[int, list], num: int, default=None):
    vals = fd.get(num)
    return vals[0] if vals else default


def to_signed(v: int, bits: int = 64) -> int:
    if v >= 1 << (bits - 1):
        v -= 1 << bits
    return v


# -- Ydb.Type / Ydb.Value (public ydb_value.proto) ---------------------------

# PrimitiveTypeId enum values
T_BOOL = 0x0006
T_INT8 = 0x0007
T_UINT8 = 0x0005
T_INT16 = 0x0008
T_UINT16 = 0x0009
T_INT32 = 0x0001
T_UINT32 = 0x0002
T_INT64 = 0x0003
T_UINT64 = 0x0004
T_FLOAT = 0x0021
T_DOUBLE = 0x0020
T_DATE = 0x0030
T_DATETIME = 0x0031
T_TIMESTAMP = 0x0032
T_INTERVAL = 0x0033
T_STRING = 0x1001
T_UTF8 = 0x1200
T_YSON = 0x1201
T_JSON = 0x1202
T_JSON_DOCUMENT = 0x1204

# Type message field numbers
TYPE_ID = 1            # PrimitiveTypeId
TYPE_OPTIONAL = 101    # OptionalType{ Type item = 1 }
TYPE_LIST = 102        # ListType{ Type item = 1 }
TYPE_STRUCT = 104      # StructType{ repeated StructMember members = 1 }
# StructMember{ string name = 1; Type type = 2 }

# Value message field numbers
V_BOOL = 1
V_INT32 = 2
V_UINT32 = 3
V_INT64 = 4
V_UINT64 = 5
V_FLOAT = 6
V_DOUBLE = 7
V_BYTES = 8
V_TEXT = 9
V_NULL_FLAG = 10
V_NESTED = 11
V_ITEMS = 12


def type_primitive(type_id: int) -> bytes:
    return f_varint(TYPE_ID, type_id)


def type_optional(item: bytes) -> bytes:
    return f_msg(TYPE_OPTIONAL, f_msg(1, item))


def type_list(item: bytes) -> bytes:
    return f_msg(TYPE_LIST, f_msg(1, item))


def type_struct(members: list[tuple[str, bytes]]) -> bytes:
    body = b"".join(
        f_msg(1, f_str(1, name) + f_msg(2, t)) for name, t in members
    )
    return f_msg(TYPE_STRUCT, body)


def value_null() -> bytes:
    return f_varint(V_NULL_FLAG, 0)  # NullValue.NULL_VALUE


def value_primitive(type_id: int, v) -> bytes:
    """Encode a python value for a primitive type id."""
    if type_id == T_BOOL:
        return f_bool(V_BOOL, bool(v))
    if type_id in (T_INT8, T_INT16, T_INT32):
        return field(V_INT32, VARINT, int(v) & (1 << 64) - 1
                     if int(v) < 0 else int(v))
    if type_id in (T_UINT8, T_UINT16, T_UINT32, T_DATE, T_DATETIME):
        return f_varint(V_UINT32, int(v))
    if type_id in (T_INT64, T_INTERVAL):
        return field(V_INT64, VARINT, int(v) & (1 << 64) - 1
                     if int(v) < 0 else int(v))
    if type_id in (T_UINT64, T_TIMESTAMP):
        return f_varint(V_UINT64, int(v))
    if type_id == T_FLOAT:
        return f_float(V_FLOAT, float(v))
    if type_id == T_DOUBLE:
        return f_double(V_DOUBLE, float(v))
    if type_id == T_STRING:
        return f_bytes(V_BYTES, v if isinstance(v, bytes) else
                       str(v).encode())
    if type_id in (T_UTF8, T_JSON, T_YSON, T_JSON_DOCUMENT):
        return f_str(V_TEXT, v if isinstance(v, str) else
                     v.decode("utf-8", "replace"))
    raise ValueError(f"unsupported ydb primitive type 0x{type_id:x}")


def value_items(items: list[bytes]) -> bytes:
    return b"".join(f_msg(V_ITEMS, i) for i in items)


def decode_type(data: bytes) -> tuple[str, Any]:
    """-> ("primitive", type_id) | ("optional", inner) | ("struct",
    [(name, decoded)]) | ("list", inner)"""
    fd = fields_dict(data)
    if TYPE_ID in fd:
        return ("primitive", fd[TYPE_ID][0])
    if TYPE_OPTIONAL in fd:
        inner = first(fields_dict(fd[TYPE_OPTIONAL][0]), 1, b"")
        return ("optional", decode_type(inner))
    if TYPE_LIST in fd:
        inner = first(fields_dict(fd[TYPE_LIST][0]), 1, b"")
        return ("list", decode_type(inner))
    if TYPE_STRUCT in fd:
        members = []
        for m in fields_dict(fd[TYPE_STRUCT][0]).get(1, []):
            mf = fields_dict(m)
            members.append((first(mf, 1, b"").decode(),
                            decode_type(first(mf, 2, b""))))
        return ("struct", members)
    raise ValueError("undecodable ydb type")


def decode_value(data: bytes, typ: tuple[str, Any]):
    """Decode a Ydb.Value against its decoded type."""
    kind, info = typ
    fd = fields_dict(data)
    if kind == "optional":
        if V_NULL_FLAG in fd:
            return None
        if V_NESTED in fd:
            return decode_value(fd[V_NESTED][0], info)
        return decode_value(data, info)
    if kind == "list":
        return [decode_value(i, info) for i in fd.get(V_ITEMS, [])]
    if kind == "struct":
        items = fd.get(V_ITEMS, [])
        return {name: decode_value(item, t)
                for (name, t), item in zip(info, items)}
    type_id = info
    if type_id == T_BOOL:
        return bool(first(fd, V_BOOL, 0))
    if type_id in (T_INT8, T_INT16, T_INT32):
        return to_signed(first(fd, V_INT32, 0), 64)
    if type_id in (T_UINT8, T_UINT16, T_UINT32, T_DATE, T_DATETIME):
        return first(fd, V_UINT32, 0)
    if type_id in (T_INT64, T_INTERVAL):
        return to_signed(first(fd, V_INT64, 0), 64)
    if type_id in (T_UINT64, T_TIMESTAMP):
        return first(fd, V_UINT64, 0)
    if type_id == T_FLOAT:
        raw = first(fd, V_FLOAT, 0)
        return struct.unpack("<f", struct.pack("<I", raw))[0]
    if type_id == T_DOUBLE:
        raw = first(fd, V_DOUBLE, 0)
        return struct.unpack("<d", struct.pack("<Q", raw))[0]
    if type_id == T_STRING:
        return bytes(first(fd, V_BYTES, b""))
    if type_id in (T_UTF8, T_JSON, T_YSON, T_JSON_DOCUMENT):
        return first(fd, V_TEXT, b"").decode()
    raise ValueError(f"unsupported ydb primitive type 0x{type_id:x}")


# -- operation envelope (ydb_operation.proto / ydb_status_codes.proto) -------

STATUS_SUCCESS = 400000


class YdbOperationError(Exception):
    def __init__(self, status: int, issues: list[str]):
        self.status = status
        self.issues = issues
        super().__init__(
            f"ydb operation failed: status={status} issues={issues}")


def unwrap_operation(response: bytes) -> bytes:
    """<X>Response{operation=1} -> packed result Any's value bytes.

    Operation: id=1, ready=2, status=3, issues=4, result=5 (Any:
    type_url=1, value=2).
    """
    op = first(fields_dict(response), 1, b"")
    fd = fields_dict(op)
    status = first(fd, 3, 0)
    if status != STATUS_SUCCESS:
        issues = []
        for iss in fd.get(4, []):
            msg = first(fields_dict(iss), 3, b"")  # IssueMessage.message=3
            if msg:
                issues.append(msg.decode("utf-8", "replace"))
        raise YdbOperationError(status, issues)
    result_any = first(fd, 5, b"")
    return first(fields_dict(result_any), 2, b"")  # Any.value


def wrap_operation(result_type_url: str, result: bytes,
                   status: int = STATUS_SUCCESS) -> bytes:
    """Build <X>Response{operation{ready,status,result}} (fake/test side
    of the hand codec; the fake server itself uses protoc-generated code
    — this helper exists for codec round-trip tests)."""
    any_msg = f_str(1, result_type_url) + f_bytes(2, result)
    op = (f_str(1, "op-1") + f_bool(2, True) + f_varint(3, status)
          + f_msg(5, any_msg))
    return f_msg(1, op)
