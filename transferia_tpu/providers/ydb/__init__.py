"""YDB provider (reference: pkg/providers/ydb/)."""

from transferia_tpu.providers.ydb.provider import (
    YdbChangefeedSource,
    YdbProvider,
    YdbSinker,
    YdbSourceParams,
    YdbStorage,
    YdbTargetParams,
)

__all__ = [
    "YdbChangefeedSource",
    "YdbProvider",
    "YdbSinker",
    "YdbSourceParams",
    "YdbStorage",
    "YdbTargetParams",
]
