"""YDB gRPC client over grpcio generic calls (no SDK, no generated code).

Requests/responses are encoded by the hand protobuf codec (wire.py).
Covers the table service (sessions, data/scheme queries, bulk upsert,
describe), the scheme service (directory listing) and a topic-service
read session for changefeed CDC.  Reference equivalent:
pkg/providers/ydb/client.go + ydb-go-sdk.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
from typing import Any, Iterable, Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.providers.ydb import wire as w

logger = logging.getLogger(__name__)

_IDENT = lambda b: b  # noqa: E731 - raw-bytes (de)serializer


class YdbError(CategorizedError):
    def __init__(self, message: str):
        super().__init__(CategorizedError.SOURCE, message)


class YdbClient:
    def __init__(self, endpoint: str, database: str,
                 auth_token: str = "", timeout: float = 30.0):
        import grpc

        self.database = database
        self.timeout = timeout
        self.channel = grpc.insecure_channel(endpoint)
        self._meta = [("x-ydb-database", database)]
        if auth_token:
            self._meta.append(("x-ydb-auth-ticket", auth_token))
        self._session_id: Optional[str] = None
        self._lock = threading.Lock()

    def _call(self, method: str, request: bytes) -> bytes:
        stub = self.channel.unary_unary(
            method, request_serializer=_IDENT,
            response_deserializer=_IDENT)
        return stub(request, metadata=self._meta, timeout=self.timeout)

    def close(self) -> None:
        self.channel.close()

    # -- sessions -----------------------------------------------------------
    def session(self) -> str:
        with self._lock:
            if self._session_id is None:
                resp = self._call(
                    "/Ydb.Table.V1.TableService/CreateSession", b"")
                result = w.unwrap_operation(resp)
                sid = w.first(w.fields_dict(result), 1, b"")
                self._session_id = sid.decode()
            return self._session_id

    # -- queries ------------------------------------------------------------
    def execute_query(self, yql: str,
                      parameters: Optional[dict[str, tuple[bytes, bytes]]]
                      = None) -> list[dict]:
        """Run YQL; parameters maps name -> (encoded Type, encoded Value).
        Returns result sets as [{"columns": [(name, type)], "rows":
        [[python values]]}]."""
        tx = w.f_msg(2, w.f_msg(2, w.f_msg(1, b"")) + w.f_bool(10, True))
        req = (w.f_str(1, self.session()) + tx
               + w.f_msg(3, w.f_str(1, yql)))
        for name, (t, v) in (parameters or {}).items():
            entry = w.f_str(1, name) + w.f_msg(
                2, w.f_msg(1, t) + w.f_msg(2, v))
            req += w.f_msg(4, entry)
        resp = self._call(
            "/Ydb.Table.V1.TableService/ExecuteDataQuery", req)
        result = w.unwrap_operation(resp)
        out = []
        for rs in w.fields_dict(result).get(1, []):
            rsf = w.fields_dict(rs)
            columns = []
            for col in rsf.get(1, []):
                cf = w.fields_dict(col)
                columns.append((
                    w.first(cf, 1, b"").decode(),
                    w.decode_type(w.first(cf, 2, b"")),
                ))
            rows = []
            for row in rsf.get(2, []):
                items = w.fields_dict(row).get(w.V_ITEMS, [])
                rows.append([
                    w.decode_value(item, columns[i][1])
                    for i, item in enumerate(items)
                ])
            out.append({"columns": columns, "rows": rows,
                        "truncated": bool(w.first(rsf, 3, 0))})
        return out

    def execute_scheme(self, yql: str) -> None:
        req = w.f_str(1, self.session()) + w.f_str(2, yql)
        resp = self._call(
            "/Ydb.Table.V1.TableService/ExecuteSchemeQuery", req)
        # result is an empty message; unwrap still raises on bad status
        op = w.first(w.fields_dict(resp), 1, b"")
        status = w.first(w.fields_dict(op), 3, 0)
        if status != w.STATUS_SUCCESS:
            raise YdbError(f"scheme query failed: status={status}")

    def bulk_upsert(self, table_path: str, row_type: bytes,
                    rows: Iterable[bytes]) -> None:
        """row_type: encoded struct Type; rows: encoded struct Values."""
        typed = (w.f_msg(1, w.type_list(row_type))
                 + w.f_msg(2, w.value_items(list(rows))))
        req = w.f_str(1, table_path) + w.f_msg(2, typed)
        resp = self._call("/Ydb.Table.V1.TableService/BulkUpsert", req)
        op = w.first(w.fields_dict(resp), 1, b"")
        status = w.first(w.fields_dict(op), 3, 0)
        if status != w.STATUS_SUCCESS:
            issues = []
            for iss in w.fields_dict(op).get(4, []):
                msg = w.first(w.fields_dict(iss), 3, b"")
                if msg:
                    issues.append(msg.decode("utf-8", "replace"))
            raise YdbError(f"bulk upsert failed: {status} {issues}")

    # -- schema -------------------------------------------------------------
    def describe_table(self, path: str) -> dict:
        req = w.f_str(1, self.session()) + w.f_str(2, path)
        resp = self._call("/Ydb.Table.V1.TableService/DescribeTable", req)
        result = w.unwrap_operation(resp)
        fd = w.fields_dict(result)
        columns = []
        for col in fd.get(2, []):
            cf = w.fields_dict(col)
            columns.append((
                w.first(cf, 1, b"").decode(),
                w.decode_type(w.first(cf, 2, b"")),
            ))
        pkey = [p.decode() for p in fd.get(3, [])]
        return {"columns": columns, "primary_key": pkey}

    def list_directory(self, path: str) -> list[dict]:
        req = w.f_str(2, path)
        resp = self._call("/Ydb.Scheme.V1.SchemeService/ListDirectory",
                          req)
        result = w.unwrap_operation(resp)
        out = []
        for child in w.fields_dict(result).get(2, []):
            cf = w.fields_dict(child)
            out.append({
                "name": w.first(cf, 1, b"").decode(),
                "type": w.first(cf, 5, 0),  # 1=dir 2=table 17=topic
            })
        return out

    # -- changefeed topic read (Ydb.Topic.V1 StreamRead subset) -------------
    def topic_read_session(self, topic_path: str, consumer: str
                          ) -> "TopicReadSession":
        return TopicReadSession(self, topic_path, consumer)


_STREAM_END = object()


class TopicReadSession:
    """Bidirectional StreamRead: init -> server pushes message batches;
    offsets commit back on the same stream (at-least-once; the reference
    consumes changefeeds the same way via ydb-go-sdk topicreader).

    A background thread drains the server stream into a queue so
    read_batch honors its timeout (a quiet topic must not stall the
    caller's round-robin) and a closed stream surfaces as an error for
    the runtime's reconnect/backoff path instead of a silent idle loop.
    """

    def __init__(self, client: YdbClient, topic_path: str, consumer: str):
        self.client = client
        self.topic = topic_path
        self.consumer = consumer
        self._requests: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._incoming: "queue.Queue" = queue.Queue()
        self._closed = False
        init = w.f_msg(1, (
            w.f_msg(1, w.f_str(1, topic_path))   # topics_read_settings
            + w.f_str(2, consumer)
        ))
        self._requests.put(init)
        # ask for data right away (flow control grant)
        self._requests.put(w.f_msg(2, w.f_varint(1, 1 << 20)))
        stub = client.channel.stream_stream(
            "/Ydb.Topic.V1.TopicService/StreamRead",
            request_serializer=_IDENT, response_deserializer=_IDENT)
        self._stream = stub(self._request_iter(), metadata=client._meta)
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _request_iter(self):
        while True:
            item = self._requests.get()
            if item is None:
                return
            yield item

    def _drain(self) -> None:
        try:
            for msg in self._stream:
                self._incoming.put(msg)
        except Exception as e:  # cancelled / transport error
            if not self._closed:
                self._incoming.put(e)
            return
        self._incoming.put(_STREAM_END)

    def read_batch(self, timeout: float = 1.0
                   ) -> list[tuple[int, int, bytes]]:
        """Next batch: [(partition_session_id, offset, data)].  Returns []
        on timeout or non-data server messages; raises YdbError when the
        stream ended (caller reconnects via the runtime retry loop)."""
        try:
            msg = self._incoming.get(timeout=timeout)
        except queue.Empty:
            return []
        if msg is _STREAM_END:
            raise YdbError(f"topic read stream closed: {self.topic}")
        if isinstance(msg, Exception):
            raise YdbError(f"topic read stream failed: {msg}")
        fd = w.fields_dict(msg)
        if 3 in fd:   # init_response
            return []
        if 8 in fd:   # start_partition_session_request -> confirm
            psf = w.fields_dict(w.first(w.fields_dict(fd[8][0]), 1, b""))
            psid = w.first(psf, 1, 0)
            self._requests.put(w.f_msg(6, w.f_varint(1, psid)))
            return []
        out = []
        if 4 in fd:   # read_response
            rr = w.fields_dict(fd[4][0])
            for pd in rr.get(1, []):
                pdf = w.fields_dict(pd)
                psid = w.first(pdf, 1, 0)
                for batch in pdf.get(2, []):
                    for m in w.fields_dict(batch).get(1, []):
                        mf = w.fields_dict(m)
                        out.append((psid, w.first(mf, 1, 0),
                                    w.first(mf, 5, b"")))
            # grant more flow-control budget
            self._requests.put(w.f_msg(2, w.f_varint(1, 1 << 20)))
        return out

    def commit(self, partition_session_id: int, end_offset: int) -> None:
        body = w.f_msg(1, (
            w.f_varint(1, partition_session_id)
            + w.f_msg(2, w.f_varint(1, 0) + w.f_varint(2, end_offset))
        ))
        self._requests.put(w.f_msg(3, body))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._requests.put(None)
            try:
                self._stream.cancel()
            except Exception as e:
                logger.debug("cancelling ydb topic stream failed: %s", e)


def yql_quote_ident(name: str) -> str:
    return "`" + name.replace("`", "``") + "`"


def yql_literal(v: Any) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, bytes):
        return json.dumps(v.decode("utf-8", "replace"))
    return json.dumps(str(v))
