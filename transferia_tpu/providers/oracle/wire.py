"""Oracle client: TNS transport + a reduced TTC session vocabulary.

Connect/auth/execute/fetch against an Oracle listener, dependency-free, the
way this framework's other wire clients work (PG v3, MySQL, Mongo OP_MSG).
Message flow:

    CONNECT -> ACCEPT (or REFUSE)
    DATA: protocol negotiation (0x01)
    DATA: FUNCTION auth phase one (0x76)  -> PARAMETER with AUTH_VFR_DATA
    DATA: FUNCTION auth phase two (0x73)  -> STATUS ok / ERROR ORA-01017
    DATA: FUNCTION execute (0x5E, sql)    -> DESCRIBE + ROWs + ERROR 1403
    DATA: FUNCTION fetch (0x05, cursor)   -> ROWs + ERROR 1403 at EOF
    DATA: FUNCTION logoff (0x09)

The frames, value codecs (NUMBER/DATE/TIMESTAMP), column type codes, and
the ORA-1403 end-of-fetch convention are Oracle's; the auth verifier is a
salted-SHA256 subset (a production client would speak O5LOGON's AES
session keys — out of scope for snapshot parity, and stated in
docs/PARITY.md).  Reference: pkg/providers/oracle/ (godror/OCI based).
"""

from __future__ import annotations

import hashlib
import logging
import socket
import struct
from typing import Any, Optional

from transferia_tpu.providers.oracle import tns
from transferia_tpu.providers.oracle.tns import (
    PKT_ACCEPT,
    PKT_CONNECT,
    PKT_DATA,
    PKT_REFUSE,
    TNSError,
    read_str,
    read_uint,
    write_str,
    write_uint,
)

logger = logging.getLogger(__name__)

# TTC message types
MSG_PROTOCOL = 0x01
MSG_FUNCTION = 0x03
MSG_ERROR = 0x04
MSG_ROW_HEADER = 0x06
MSG_ROW_DATA = 0x07
MSG_PARAMETER = 0x08
MSG_STATUS = 0x09
MSG_DESCRIBE = 0x10

# function codes
FN_FETCH = 0x05
FN_LOGOFF = 0x09
FN_EXECUTE = 0x5E
FN_AUTH_PHASE_TWO = 0x73
FN_AUTH_PHASE_ONE = 0x76

ORA_NO_DATA_FOUND = 1403
ORA_INVALID_LOGIN = 1017

DEFAULT_PREFETCH = 2000


class OracleError(Exception):
    def __init__(self, message: str, code: int = 0):
        super().__init__(message)
        self.code = code


class ColumnInfo:
    def __init__(self, name: str, type_code: int, precision: int = 0,
                 scale: int = 0, nullable: bool = True,
                 type_name: str = ""):
        self.name = name
        self.type_code = type_code
        self.precision = precision
        self.scale = scale
        self.nullable = nullable
        self.type_name = type_name


def auth_verifier(salt: bytes, password: str) -> str:
    """Salted-SHA256 verifier hex (subset; see module docstring)."""
    return hashlib.sha256(salt + password.encode()).hexdigest()


class OracleConnection:
    def __init__(self, host: str, port: int = 1521, user: str = "",
                 password: str = "", service_name: str = "", sid: str = "",
                 prefetch: int = DEFAULT_PREFETCH,
                 connect_timeout: float = 10.0):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.service_name, self.sid = service_name, sid
        self.prefetch = prefetch
        self.connect_timeout = connect_timeout
        self._sock: Optional[socket.socket] = None

    # -- transport ----------------------------------------------------------
    def _send(self, ptype: int, payload: bytes) -> None:
        self._sock.sendall(tns.pack_packet(ptype, payload))

    def _send_data(self, payload: bytes) -> None:
        # 2-byte data flags precede the TTC payload in DATA packets
        self._send(PKT_DATA, struct.pack(">H", 0) + payload)

    def _recv_data(self) -> bytes:
        ptype, payload = tns.read_packet(self._sock)
        if ptype == PKT_REFUSE:
            raise OracleError(f"refused: {tns.parse_refuse(payload)}")
        if ptype != PKT_DATA:
            raise OracleError(f"unexpected TNS packet type {ptype}")
        return payload[2:]

    # -- session ------------------------------------------------------------
    def connect(self) -> "OracleConnection":
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout)
        try:
            self._sock.settimeout(60.0)
            desc = tns.connect_descriptor(self.host, self.port,
                                          self.service_name, self.sid)
            self._send(PKT_CONNECT, tns.build_connect(desc))
            ptype, payload = tns.read_packet(self._sock)
            if ptype == PKT_REFUSE:
                raise OracleError(
                    f"listener refused: {tns.parse_refuse(payload)}")
            if ptype != PKT_ACCEPT:
                raise OracleError(
                    f"expected ACCEPT, got packet type {ptype}")
            tns.parse_accept(payload)
            self._negotiate()
            self._authenticate()
        except BaseException:
            # failed mid-handshake: do not leak the half-open socket
            self._sock.close()
            self._sock = None
            raise
        return self

    def _negotiate(self) -> None:
        self._send_data(
            bytes([MSG_PROTOCOL]) + b"\x06\x05\x04\x03\x02\x01\x00"
            + b"transferia_tpu\x00")
        resp = self._recv_data()
        if resp[0:1] != bytes([MSG_PROTOCOL]):
            raise OracleError("protocol negotiation failed")

    def _authenticate(self) -> None:
        # phase one: present the user, receive the verifier salt
        self._send_data(bytes([MSG_FUNCTION, FN_AUTH_PHASE_ONE])
                        + write_str(self.user))
        params = self._read_parameters()
        salt_hex = params.get("AUTH_VFR_DATA", "")
        salt = bytes.fromhex(salt_hex) if salt_hex else b""
        # phase two: salted verifier
        self._send_data(
            bytes([MSG_FUNCTION, FN_AUTH_PHASE_TWO])
            + write_str(self.user)
            + write_str(auth_verifier(salt, self.password)))
        self._read_status()

    def _read_parameters(self) -> dict[str, str]:
        buf = self._recv_data()
        if buf[0] == MSG_ERROR:
            self._raise_error(buf)
        if buf[0] != MSG_PARAMETER:
            raise OracleError(f"expected PARAMETER, got 0x{buf[0]:02x}")
        pos = 1
        n, pos = read_uint(buf, pos)
        out = {}
        for _ in range(n):
            k, pos = read_str(buf, pos)
            v, pos = read_str(buf, pos)
            out[k] = v
        return out

    def _read_status(self) -> None:
        buf = self._recv_data()
        if buf[0] == MSG_ERROR:
            self._raise_error(buf)
        if buf[0] != MSG_STATUS:
            raise OracleError(f"expected STATUS, got 0x{buf[0]:02x}")

    @staticmethod
    def _parse_error(buf: bytes) -> tuple[int, str]:
        pos = 1
        code, pos = read_uint(buf, pos)
        msg, pos = read_str(buf, pos)
        return code, msg or ""

    def _raise_error(self, buf: bytes) -> None:
        code, msg = self._parse_error(buf)
        raise OracleError(msg or f"ORA-{code:05d}", code)

    # -- queries ------------------------------------------------------------
    def execute(self, sql: str):
        """Run a statement; yields (columns, row-iterator) semantics rolled
        into a full fetch: returns (columns, rows)."""
        self._send_data(
            bytes([MSG_FUNCTION, FN_EXECUTE])
            + write_str(sql) + write_uint(self.prefetch))
        columns: list[ColumnInfo] = []
        rows: list[list[Any]] = []
        cursor_id = 0
        while True:
            buf = self._recv_data()
            msg = buf[0]
            if msg == MSG_ERROR:
                code, emsg = self._parse_error(buf)
                if code == ORA_NO_DATA_FOUND:
                    return columns, rows
                raise OracleError(emsg or f"ORA-{code:05d}", code)
            if msg == MSG_DESCRIBE:
                columns, cursor_id = self._parse_describe(buf)
                continue
            if msg == MSG_ROW_DATA:
                rows.append(self._parse_row(buf, columns))
                continue
            if msg == MSG_STATUS:
                # batch boundary: ask for more
                self._send_data(
                    bytes([MSG_FUNCTION, FN_FETCH])
                    + write_uint(cursor_id) + write_uint(self.prefetch))
                continue
            raise OracleError(f"unexpected TTC message 0x{msg:02x}")

    @staticmethod
    def _parse_describe(buf: bytes) -> tuple[list[ColumnInfo], int]:
        pos = 1
        cursor_id, pos = read_uint(buf, pos)
        n, pos = read_uint(buf, pos)
        cols = []
        for _ in range(n):
            name, pos = read_str(buf, pos)
            tcode, pos = read_uint(buf, pos)
            prec, pos = read_uint(buf, pos)
            scale, pos = read_uint(buf, pos)
            nullable, pos = read_uint(buf, pos)
            tname, pos = read_str(buf, pos)
            cols.append(ColumnInfo(name, tcode, prec, scale,
                                   bool(nullable), tname or ""))
        return cols, cursor_id

    @staticmethod
    def _parse_row(buf: bytes, columns: list[ColumnInfo]) -> list[Any]:
        pos = 1
        out = []
        for col in columns:
            v, pos = tns.decode_value(col.type_code, buf, pos)
            out.append(v)
        return out

    def query(self, sql: str) -> list[dict[str, Any]]:
        columns, rows = self.execute(sql)
        names = [c.name for c in columns]
        return [dict(zip(names, r)) for r in rows]

    def scalar(self, sql: str):
        _, rows = self.execute(sql)
        return rows[0][0] if rows else None

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._send_data(bytes([MSG_FUNCTION, FN_LOGOFF]))
            except OSError:
                pass
            try:
                self._sock.close()
            finally:
                self._sock = None
