from transferia_tpu.providers.oracle.provider import (
    OracleProvider,
    OracleSourceParams,
    OracleStorage,
)
from transferia_tpu.providers.oracle.wire import OracleConnection, OracleError

__all__ = [
    "OracleConnection",
    "OracleError",
    "OracleProvider",
    "OracleSourceParams",
    "OracleStorage",
]
