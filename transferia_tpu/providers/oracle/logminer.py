"""Oracle LogMiner CDC source.

Reference parity: pkg/providers/oracle/replication/log_miner/ —
source.go (START_LOGMNR/END_LOGMNR cycle over V$LOGMNR_CONTENTS with
STARTSCN, operation codes 1/2/3, CSF continuation rows, XID transaction
ids), sql_parse.go (redo-SQL statement parser), sql_cast.go (schema-driven
value casting), common/log_position.go (SCN checkpoint state).

Flow per cycle: START_LOGMNR pinned at the checkpointed SCN -> select the
redo rows -> END_LOGMNR -> parse each SQL_REDO into a ChangeItem (values
cast through the table schema) -> push -> after the sink confirms,
checkpoint the high SCN (at-least-once, like every CDC source here).
"""

from __future__ import annotations

import datetime as dt
import logging
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from transferia_tpu.abstract.change_item import ChangeItem, OldKeys
from transferia_tpu.abstract.interfaces import AsyncSink, Source
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    TableID,
    TableSchema,
)
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.providers.oracle.wire import OracleError

logger = logging.getLogger(__name__)

# V$LOGMNR_CONTENTS operation codes (source.go:46-49)
OP_INSERT = 1
OP_DELETE = 2
OP_UPDATE = 3
OP_COMMIT = 7

_KIND = {OP_INSERT: Kind.INSERT, OP_DELETE: Kind.DELETE,
         OP_UPDATE: Kind.UPDATE}


def _row_pos(r: dict) -> tuple:
    """Redo-row identity (SCN, RS_ID, SSN) — common/log_position.go."""
    return (int(r.get("SCN") or 0), str(r.get("RS_ID") or ""),
            int(r.get("SSN") or 0))


class RedoParseError(Exception):
    pass


@dataclass
class RedoStatement:
    """One parsed redo statement (sql_parse.go ParseResult)."""

    op: Kind
    owner: str
    table: str
    new_values: dict[str, Optional[str]] = field(default_factory=dict)
    conditions: dict[str, Optional[str]] = field(default_factory=dict)

    def table_id(self) -> TableID:
        return TableID(self.owner, self.table)


class _Tokens:
    """Minimal tokenizer over redo SQL: quoted identifiers, string
    literals with '' escapes, bare words, punctuation."""

    def __init__(self, sql: str):
        self.sql = sql
        self.pos = 0
        self.n = len(sql)

    def _skip_ws(self) -> None:
        while self.pos < self.n and self.sql[self.pos] in " \t\r\n;":
            self.pos += 1

    def peek(self) -> str:
        self._skip_ws()
        return self.sql[self.pos] if self.pos < self.n else ""

    def done(self) -> bool:
        self._skip_ws()
        return self.pos >= self.n

    def ident(self) -> str:
        """A "quoted" or bare identifier."""
        self._skip_ws()
        if self.peek() == '"':
            self.pos += 1
            start = self.pos
            while self.pos < self.n and self.sql[self.pos] != '"':
                self.pos += 1
            out = self.sql[start:self.pos]
            self.pos += 1  # closing quote
            return out
        start = self.pos
        while self.pos < self.n and (self.sql[self.pos].isalnum()
                                     or self.sql[self.pos] in "_$#"):
            self.pos += 1
        if start == self.pos:
            raise RedoParseError(
                f"expected identifier at {start} in {self.sql!r}")
        return self.sql[start:self.pos]

    def word(self) -> str:
        return self.ident().lower()

    def expect(self, ch: str) -> None:
        self._skip_ws()
        if self.pos >= self.n or self.sql[self.pos] != ch:
            raise RedoParseError(
                f"expected {ch!r} at {self.pos} in {self.sql!r}")
        self.pos += 1

    def expect_word(self, *words: str) -> None:
        got = self.word()
        if got not in words:
            raise RedoParseError(f"expected {words}, got {got!r}")

    def value(self) -> Optional[str]:
        """A literal: 'string' (with '' escapes), NULL, number, or a
        function-call literal like TO_TIMESTAMP('...', '...')."""
        self._skip_ws()
        if self.pos >= self.n:
            raise RedoParseError("unexpected end of redo sql")
        ch = self.sql[self.pos]
        if ch == "'":
            self.pos += 1
            out = []
            while self.pos < self.n:
                c = self.sql[self.pos]
                if c == "'":
                    if self.pos + 1 < self.n and \
                            self.sql[self.pos + 1] == "'":
                        out.append("'")
                        self.pos += 2
                        continue
                    self.pos += 1
                    return "".join(out)
                out.append(c)
                self.pos += 1
            raise RedoParseError("unterminated string literal")
        # bare token (number / NULL / function literal with parens)
        start = self.pos
        depth = 0
        while self.pos < self.n:
            c = self.sql[self.pos]
            if c == "(":
                depth += 1
            elif c == ")":
                if depth == 0:
                    break
                depth -= 1
            elif c == "'" and depth > 0:
                # string inside a function literal: skip it whole
                self.pos += 1
                while self.pos < self.n:
                    if self.sql[self.pos] == "'":
                        if self.pos + 1 < self.n and \
                                self.sql[self.pos + 1] == "'":
                            self.pos += 1
                        else:
                            break
                    self.pos += 1
            elif depth == 0 and c in ", \t\r\n;":
                break
            self.pos += 1
        raw = self.sql[start:self.pos].strip()
        if raw.upper() == "NULL":
            return None
        return raw


def parse_redo_sql(sql: str) -> RedoStatement:
    """insert/update/delete redo SQL -> RedoStatement (sql_parse.go)."""
    t = _Tokens(sql)
    verb = t.word()
    if verb == "insert":
        t.expect_word("into")
        owner = t.ident()
        t.expect(".")
        table = t.ident()
        t.expect("(")
        cols = [t.ident()]
        while t.peek() == ",":
            t.expect(",")
            cols.append(t.ident())
        t.expect(")")
        t.expect_word("values")
        t.expect("(")
        vals = [t.value()]
        while t.peek() == ",":
            t.expect(",")
            vals.append(t.value())
        t.expect(")")
        if len(cols) != len(vals):
            raise RedoParseError(
                f"{len(cols)} columns vs {len(vals)} values")
        return RedoStatement(Kind.INSERT, owner, table,
                             new_values=dict(zip(cols, vals)))
    if verb == "update":
        owner = t.ident()
        t.expect(".")
        table = t.ident()
        t.expect_word("set")
        new_values: dict[str, Optional[str]] = {}
        while True:
            col = t.ident()
            t.expect("=")
            new_values[col] = t.value()
            if t.peek() != ",":
                break
            t.expect(",")
        conditions = _parse_where(t)
        return RedoStatement(Kind.UPDATE, owner, table,
                             new_values=new_values,
                             conditions=conditions)
    if verb == "delete":
        t.expect_word("from")
        owner = t.ident()
        t.expect(".")
        table = t.ident()
        conditions = _parse_where(t)
        return RedoStatement(Kind.DELETE, owner, table,
                             conditions=conditions)
    raise RedoParseError(f"unsupported redo verb {verb!r}")


def _parse_where(t: _Tokens) -> dict[str, Optional[str]]:
    out: dict[str, Optional[str]] = {}
    if t.done():
        return out
    t.expect_word("where")
    while True:
        col = t.ident()
        t._skip_ws()
        # "C" = 'v'  |  "C" IS NULL
        if t.sql[t.pos:t.pos + 2].lower() == "is":
            t.word()            # IS
            t.expect_word("null")
            out[col] = None
        else:
            t.expect("=")
            out[col] = t.value()
        if t.done():
            return out
        t.expect_word("and")


# NLS mask tokens -> strptime directives, longest first (sql_cast.go
# handles the same masks through godror)
_MASK_TOKENS = [
    ("HH24", "%H"), ("YYYY", "%Y"), ("RRRR", "%Y"), ("MON", "%b"),
    ("MM", "%m"), ("DD", "%d"), ("RR", "%y"), ("YY", "%y"),
    ("HH", "%I"), ("MI", "%M"), ("SS", "%S"), ("AM", "%p"), ("PM", "%p"),
]


def _parse_oracle_datetime(value: str, mask: str) -> Optional[dt.datetime]:
    """Parse a TO_DATE/TO_TIMESTAMP literal using its NLS format mask
    ('29-JUL-26' + 'DD-MON-RR' included — the default-NLS redo form)."""
    mask = mask.strip().upper()
    mask = re.sub(r"\.FF\d?", ".%f", mask)
    fmt = ""
    i = 0
    while i < len(mask):
        for tok, directive in _MASK_TOKENS:
            if mask.startswith(tok, i):
                fmt += directive
                i += len(tok)
                break
        else:
            fmt += mask[i]
            i += 1
    for candidate in (value, value.title()):
        try:
            return dt.datetime.strptime(candidate, fmt)
        except ValueError:
            continue
    return None


def cast_redo_value(cs, raw: Optional[str]) -> Any:
    """Schema-driven cast of a redo literal (sql_cast.go)."""
    if raw is None:
        return None
    t = cs.data_type
    # TO_DATE('29-JUL-26', 'DD-MON-RR') / TO_TIMESTAMP(...) literals:
    # honor the mask argument instead of assuming ISO input
    if raw.upper().startswith(("TO_DATE(", "TO_TIMESTAMP(")):
        inner = _Tokens(raw[raw.index("(") + 1:])
        first = inner.value() or ""
        mask = ""
        if inner.peek() == ",":
            inner.expect(",")
            mask = inner.value() or ""
        if mask:
            parsed = _parse_oracle_datetime(first, mask)
            if parsed is not None:
                epoch = parsed.replace(
                    tzinfo=dt.timezone.utc).timestamp()
                if t == CanonicalType.TIMESTAMP:
                    return int(epoch) * 1_000_000 + parsed.microsecond
                if t == CanonicalType.DATETIME:
                    return int(epoch)
        raw = first
    if t.is_integer:
        try:
            return int(raw)
        except ValueError:
            return raw
    if t.is_float:
        try:
            return float(raw)
        except ValueError:
            return raw
    if t == CanonicalType.BOOLEAN:
        return raw not in ("0", "", "false", "F")
    if t in (CanonicalType.DATETIME, CanonicalType.TIMESTAMP):
        try:
            parsed = dt.datetime.fromisoformat(raw.strip())
        except ValueError:
            return raw
        epoch = parsed.replace(tzinfo=dt.timezone.utc).timestamp()
        if t == CanonicalType.TIMESTAMP:
            return int(epoch) * 1_000_000 + parsed.microsecond
        return int(epoch)
    if t == CanonicalType.STRING:
        return raw.encode()
    return raw


class OracleLogMinerSource(Source):
    """LogMiner polling CDC with post-push SCN checkpointing."""

    STATE_KEY = "oracle_scn"
    BOUNDARY_KEY = "oracle_scn_rows"
    LOGMNR_OPTIONS = ("SYS.DBMS_LOGMNR.DICT_FROM_ONLINE_CATALOG"
                      "+SYS.DBMS_LOGMNR.NO_SQL_DELIMITER"
                      "+SYS.DBMS_LOGMNR.NO_ROWID_IN_STMT"
                      "+SYS.DBMS_LOGMNR.STRING_LITERALS_IN_STMT")

    def __init__(self, params, transfer_id: str,
                 coordinator: Optional[Coordinator] = None,
                 poll_interval: float = 0.5,
                 batch_rows: int = 2048):
        from transferia_tpu.providers.oracle.provider import (
            OracleStorage,
            _conn,
        )

        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.poll_interval = poll_interval
        self.batch_rows = batch_rows
        self._stop = threading.Event()
        # (SCN, RS_ID, SSN) of rows already pushed at the checkpoint SCN
        self._delivered_at_boundary: set[tuple] = set()
        self._make_conn = lambda: _conn(params)
        self._schema_storage = OracleStorage(params)
        self._schemas: dict[TableID, TableSchema] = {}

    def _schema(self, tid: TableID) -> Optional[TableSchema]:
        if tid not in self._schemas:
            try:
                self._schemas[tid] = self._schema_storage.table_schema(tid)
            except OracleError as e:
                logger.warning("no schema for %s: %s", tid, e)
                self._schemas[tid] = None
        return self._schemas[tid]

    def _start_scn(self, conn) -> int:
        if self.cp is not None:
            state = self.cp.get_transfer_state(self.transfer_id)
            if state.get(self.STATE_KEY):
                # boundary rows already delivered before the restart: the
                # >= re-mine must not replay them
                self._delivered_at_boundary = {
                    tuple(pos) for pos in
                    state.get(self.BOUNDARY_KEY) or []
                }
                return int(state[self.STATE_KEY])
            # first replication start after a snapshot: resume from the
            # SCN the snapshot was pinned at, so changes committed during
            # the load are mined, not lost (SNAPSHOT_AND_INCREMENT
            # handoff; common/log_position.go)
            pos = state.get("snapshot_position") or {}
            if pos.get("scn"):
                return int(pos["scn"])
        return int(conn.scalar("SELECT current_scn FROM v$database") or 0)

    def _mine(self, conn, from_scn: int) -> list[dict]:
        """One START_LOGMNR/select/END_LOGMNR cycle (source.go:180-210).

        Mines SCN >= from_scn (not >): Oracle packs many rows of one
        transaction under a shared SCN, and a row at the boundary SCN may
        only become visible after an earlier cycle checkpointed it.  Rows
        already delivered at the boundary are dropped via their
        (SCN, RS_ID, SSN) identity — the reference's position tuple."""
        conn.execute(
            f"BEGIN DBMS_LOGMNR.START_LOGMNR(STARTSCN => {from_scn}, "
            f"OPTIONS => {self.LOGMNR_OPTIONS}); END;")
        try:
            owner = self.params.owner or self.params.user.upper()
            rows = conn.query(
                "SELECT SCN, RS_ID, SSN, TIMESTAMP, "
                "(XIDUSN||'.'||XIDSLT||'.'||XIDSQN) AS XID, "
                "OPERATION_CODE, SEG_OWNER, TABLE_NAME, SQL_REDO, CSF "
                "FROM V$LOGMNR_CONTENTS "
                f"WHERE SCN >= {from_scn} "
                f"AND OPERATION_CODE IN ({OP_INSERT}, {OP_DELETE}, "
                f"{OP_UPDATE}) "
                f"AND SEG_OWNER = '{owner}'"
            )
        finally:
            conn.execute("BEGIN DBMS_LOGMNR.END_LOGMNR(); END;")
        rows = [r for r in rows
                if _row_pos(r) not in self._delivered_at_boundary]
        # CSF=1 marks a continued statement: concatenate with the next row
        out: list[dict] = []
        pending: Optional[dict] = None
        for r in rows:
            if pending is not None:
                pending["SQL_REDO"] = (pending.get("SQL_REDO") or "") + \
                    (r.get("SQL_REDO") or "")
                pending["CSF"] = r.get("CSF")
                if not int(r.get("CSF") or 0):
                    out.append(pending)
                    pending = None
                continue
            if int(r.get("CSF") or 0):
                pending = dict(r)
                continue
            out.append(r)
        if pending is not None:
            logger.warning("dropping unterminated CSF redo row")
        return out

    def _to_item(self, row: dict) -> Optional[ChangeItem]:
        try:
            stmt = parse_redo_sql(row.get("SQL_REDO") or "")
        except RedoParseError as e:
            logger.warning("unparsed redo row at scn %s: %s",
                           row.get("SCN"), e)
            return None
        tid = stmt.table_id()
        schema = self._schema(tid)
        if schema is None:
            return None
        ts = row.get("TIMESTAMP")
        commit_ns = 0
        if isinstance(ts, dt.datetime):
            commit_ns = int(ts.replace(
                tzinfo=dt.timezone.utc).timestamp() * 1e9)
        by_name = {c.name: c for c in schema}

        def cast_map(vals: dict) -> dict:
            return {
                k: cast_redo_value(by_name[k], v)
                for k, v in vals.items() if k in by_name
            }

        new_vals = cast_map(stmt.new_values)
        cond_vals = cast_map(stmt.conditions)
        key_cols = [c.name for c in schema.key_columns()] or \
            list(cond_vals)
        old_keys = OldKeys()
        if stmt.op in (Kind.UPDATE, Kind.DELETE):
            old_keys = OldKeys(
                tuple(k for k in key_cols if k in cond_vals),
                tuple(cond_vals[k] for k in key_cols if k in cond_vals),
            )
        if stmt.op == Kind.UPDATE:
            # redo SET lists only changed columns; fill the rest from the
            # WHERE image so the row is complete
            merged = dict(cond_vals)
            merged.update(new_vals)
            new_vals = merged
        names = tuple(n for n in schema.names() if n in new_vals) \
            if stmt.op != Kind.DELETE else ()
        return ChangeItem(
            kind=stmt.op,
            schema=tid.namespace,
            table=tid.name,
            table_schema=schema,
            column_names=names,
            column_values=tuple(new_vals[n] for n in names),
            old_keys=old_keys,
            lsn=int(row.get("SCN") or 0),
            txn_id=str(row.get("XID") or ""),
            commit_time_ns=commit_ns,
        )

    def run(self, sink: AsyncSink) -> None:
        conn = self._make_conn()
        try:
            scn = self._start_scn(conn)
            logger.info("logminer: starting from SCN %d", scn)
            while not self._stop.is_set():
                rows = self._mine(conn, scn)
                if not rows:
                    self._stop.wait(self.poll_interval)
                    continue
                items = []
                high = scn
                for r in rows:
                    item = self._to_item(r)
                    if item is not None:
                        items.append(item)
                    high = max(high, int(r.get("SCN") or 0))
                futures = []
                for i in range(0, len(items), self.batch_rows):
                    futures.append(
                        sink.async_push(items[i:i + self.batch_rows]))
                for f in futures:
                    f.result()
                # confirmed: advance the SCN checkpoint (at-least-once);
                # remember the rows delivered at the new boundary so the
                # >= re-mine never re-delivers them in this run
                scn = high
                self._delivered_at_boundary = {
                    _row_pos(r) for r in rows
                    if int(r.get("SCN") or 0) == high
                }
                if self.cp is not None:
                    self.cp.set_transfer_state(
                        self.transfer_id,
                        {self.STATE_KEY: scn,
                         self.BOUNDARY_KEY: sorted(
                             list(p) for p in
                             self._delivered_at_boundary)})
        finally:
            conn.close()
            self._schema_storage.close()

    def stop(self) -> None:
        self._stop.set()
