"""Oracle provider: snapshot storage over the TNS/TTC wire client.

Reference parity: pkg/providers/oracle/ — model_source.go (connection
types SID/ServiceName/TNS, ConvertNumberToInt64, include/exclude),
snapshot/table_source.go:69 (SCN-consistent reads: ``select ... as of scn
N``), provider/sharding_storage.go (ROWID-range intra-table splits),
schema/ (ALL_TAB_COLUMNS-driven schema, type casts in snapshot/cast.go).
LogMiner CDC replication lives in logminer.py (reference
replication/log_miner/); SCN position checkpointing is shared between
the consistent snapshot and the CDC resume point.
"""

from __future__ import annotations

import calendar
import datetime as dt
import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.interfaces import (
    PositionalStorage,
    Pusher,
    SampleableStorage,
    ShardingStorage,
    SnapshotableStorage,
    Storage,
    TableInfo,
)
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.oracle.wire import OracleConnection, OracleError
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.typesystem.rules import register_source_rules

logger = logging.getLogger(__name__)

# source type rules (schema/ + snapshot/cast.go: NUMBER splits by scale and
# precision; CLOB family is utf8; RAW/BLOB are bytes)
register_source_rules("oracle", {
    "char": CanonicalType.UTF8, "varchar2": CanonicalType.UTF8,
    "nchar": CanonicalType.UTF8, "nvarchar2": CanonicalType.UTF8,
    "long": CanonicalType.UTF8,
    "clob": CanonicalType.UTF8, "nclob": CanonicalType.UTF8,
    "number": CanonicalType.DECIMAL,
    "float": CanonicalType.DOUBLE,
    "binary_float": CanonicalType.FLOAT,
    "binary_double": CanonicalType.DOUBLE,
    "raw": CanonicalType.STRING, "long raw": CanonicalType.STRING,
    "blob": CanonicalType.STRING,
    "date": CanonicalType.DATETIME,
    "timestamp": CanonicalType.TIMESTAMP,
    "timestamp with time zone": CanonicalType.TIMESTAMP,
    "timestamp with local time zone": CanonicalType.TIMESTAMP,
    "interval year to month": CanonicalType.UTF8,
    "interval day to second": CanonicalType.INTERVAL,
    "*": CanonicalType.ANY,
})


@register_endpoint
@dataclass
class OracleSourceParams(EndpointParams):
    PROVIDER = "oracle"
    IS_SOURCE = True

    host: str = "localhost"
    port: int = 1521
    # exactly one of these names the database (model_source.go
    # OracleConnectionType)
    service_name: str = ""
    sid: str = ""
    user: str = ""
    password: str = ""
    # schema (owner) whose tables are transferred
    owner: str = ""
    include_tables: list[str] = field(default_factory=list)
    exclude_tables: list[str] = field(default_factory=list)
    # NUMBER(p<=18, s=0) -> int64 instead of decimal
    # (model_source.go ConvertNumberToInt64)
    convert_number_to_int64: bool = True
    # flashback-consistent reads pinned to the activation SCN; disable
    # when UNDO retention is too small (IsNonConsistentSnapshot)
    consistent_snapshot: bool = True
    batch_rows: int = 65_536
    desired_shards: int = 4


def _conn(params: OracleSourceParams) -> OracleConnection:
    return OracleConnection(
        host=params.host, port=params.port, user=params.user,
        password=params.password, service_name=params.service_name,
        sid=params.sid,
    ).connect()


def _q(name: str) -> str:
    return '"' + name.replace('"', '""') + '"'


def _ora_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, float)):
        return str(v)
    # temporal keys must not depend on the session NLS_DATE_FORMAT:
    # render through explicit TO_DATE/TO_TIMESTAMP masks
    if isinstance(v, dt.datetime):
        if v.microsecond:
            return (f"TO_TIMESTAMP('{v:%Y-%m-%d %H:%M:%S.%f}', "
                    "'YYYY-MM-DD HH24:MI:SS.FF6')")
        return (f"TO_DATE('{v:%Y-%m-%d %H:%M:%S}', "
                "'YYYY-MM-DD HH24:MI:SS')")
    if isinstance(v, dt.date):
        return f"TO_DATE('{v:%Y-%m-%d}', 'YYYY-MM-DD')"
    s = str(v).replace("'", "''")
    return f"'{s}'"


class OracleStorage(Storage, PositionalStorage, ShardingStorage,
                    SampleableStorage, SnapshotableStorage):
    def __init__(self, params: OracleSourceParams):
        self.params = params
        self._c: Optional[OracleConnection] = None
        self._scn: Optional[int] = None
        self._conn_lock = threading.Lock()

    @property
    def conn(self) -> OracleConnection:
        with self._conn_lock:
            if self._c is None:
                self._c = _conn(self.params)
            return self._c

    def close(self) -> None:
        with self._conn_lock:
            c, self._c = self._c, None
        if c is not None:
            c.close()

    def ping(self) -> None:
        self.conn.scalar("SELECT 1 FROM dual")

    @property
    def owner(self) -> str:
        return self.params.owner or self.params.user.upper()

    # -- catalog ------------------------------------------------------------
    def table_list(self, include=None):
        rows = self.conn.query(
            "SELECT table_name, num_rows FROM all_tables "
            f"WHERE owner = {_ora_literal(self.owner)}"
        )
        out = {}
        inc = [t.upper() for t in self.params.include_tables]
        exc = [t.upper() for t in self.params.exclude_tables]
        for r in rows:
            name = r["TABLE_NAME"]
            if inc and name.upper() not in inc:
                continue
            if name.upper() in exc:
                continue
            tid = TableID(self.owner, name)
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(eta_rows=int(r["NUM_ROWS"] or 0))
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        return self._table_schema_on(self.conn, table)

    def _table_schema_on(self, conn: OracleConnection,
                         table: TableID) -> TableSchema:
        from transferia_tpu.typesystem.rules import map_source_type

        rows = conn.query(
            "SELECT column_name, data_type, data_precision, data_scale, "
            "nullable FROM all_tab_columns "
            f"WHERE owner = {_ora_literal(table.namespace)} "
            f"AND table_name = {_ora_literal(table.name)} "
            "ORDER BY column_id"
        )
        pk_cols = self._primary_keys_on(conn, table)
        cols = []
        for r in rows:
            dtype = (r["DATA_TYPE"] or "").lower()
            # TIMESTAMP(6) -> timestamp; INTERVAL DAY(2) TO SECOND(6) ->
            # interval day to second
            base = dtype
            while "(" in base:
                i = base.index("(")
                j = base.index(")", i)
                base = base[:i] + base[j + 1:]
            base = " ".join(base.split())
            ctype = map_source_type("oracle", base)
            if base == "number":
                scale = int(r["DATA_SCALE"] or 0)
                prec = int(r["DATA_PRECISION"] or 0)
                if scale == 0:
                    if 0 < prec <= 18 and self.params.convert_number_to_int64:
                        ctype = CanonicalType.INT64
                    elif prec == 0 and self.params.convert_number_to_int64:
                        # unconstrained NUMBER used as integer is common;
                        # cast.go maps it via ConvertNumberToInt64 too
                        ctype = CanonicalType.INT64
                elif prec and scale > 0:
                    ctype = CanonicalType.DOUBLE
            cols.append(ColSchema(
                name=r["COLUMN_NAME"],
                data_type=ctype,
                primary_key=r["COLUMN_NAME"] in pk_cols,
                required=r["NULLABLE"] == "N",
                original_type=f"oracle:{r['DATA_TYPE']}",
            ))
        return TableSchema(cols)

    def _primary_keys_on(self, conn: OracleConnection,
                         table: TableID) -> set[str]:
        rows = conn.query(
            "SELECT cols.column_name FROM all_constraints cons "
            "JOIN all_cons_columns cols "
            "ON cons.constraint_name = cols.constraint_name "
            "AND cons.owner = cols.owner "
            f"WHERE cons.owner = {_ora_literal(table.namespace)} "
            f"AND cons.table_name = {_ora_literal(table.name)} "
            "AND cons.constraint_type = 'P' ORDER BY cols.position"
        )
        return {r["COLUMN_NAME"] for r in rows}

    def exact_table_rows_count(self, table: TableID) -> int:
        return int(self.conn.scalar(
            f"SELECT COUNT(*) FROM {_q(table.namespace)}.{_q(table.name)}"
        ) or 0)

    def estimate_table_rows_count(self, table: TableID) -> int:
        info = self.table_list([table]).get(table)
        return info.eta_rows if info else 0

    def table_size_in_bytes(self, table: TableID) -> int:
        try:
            return int(self.conn.scalar(
                "SELECT SUM(bytes) FROM all_segments "
                f"WHERE owner = {_ora_literal(table.namespace)} "
                f"AND segment_name = {_ora_literal(table.name)}"
            ) or 0)
        except OracleError:
            return 0

    # -- PositionalStorage: SCN checkpoint (common/log_position.go) ---------
    def position(self) -> dict:
        scn = int(self.conn.scalar("SELECT current_scn FROM v$database")
                  or 0)
        self._scn = scn
        return {"scn": scn}

    def begin_snapshot(self) -> None:
        """Pin the flashback SCN all part reads are AS OF."""
        if self._scn is None:
            self.position()

    def end_snapshot(self) -> None:
        self._scn = None

    # -- snapshot load ------------------------------------------------------
    def _as_of(self) -> str:
        if self.params.consistent_snapshot and self._scn:
            return f" AS OF SCN {self._scn}"
        return ""

    def _select(self, table: TableID, schema: TableSchema,
                where: str = "", order: str = "", limit: int = 0) -> str:
        cols = ", ".join(_q(c.name) for c in schema)
        sql = (f"SELECT {cols} FROM "
               f"{_q(table.namespace)}.{_q(table.name)}{self._as_of()}")
        if where:
            sql += f" WHERE {where}"
        if order:
            sql += f" ORDER BY {order}"
        if limit:
            sql += f" FETCH NEXT {limit} ROWS ONLY"
        return sql

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        # dedicated connection per part: parts load from parallel worker
        # threads, and the shared self.conn socket is not thread-safe —
        # catalog queries for this load must ride the same private socket
        conn = _conn(self.params)
        try:
            schema = self._table_schema_on(conn, table.id)
            keys = schema.key_columns()
            if len(keys) == 1:
                self._load_keyset(conn, table, schema, keys[0], pusher)
            else:
                self._load_plain(conn, table, schema, pusher)
        finally:
            conn.close()

    def _load_keyset(self, conn: OracleConnection, table: TableDescription,
                     schema: TableSchema, key: ColSchema,
                     pusher: Pusher) -> None:
        """Keyset pagination over the PK with FETCH NEXT (12c+), stable
        under concurrent writes and index-driven server-side."""
        last = None
        bs = self.params.batch_rows
        while True:
            conds = []
            if table.filter:
                conds.append(f"({table.filter})")
            if last is not None:
                conds.append(f"{_q(key.name)} > {_ora_literal(last)}")
            sql = self._select(table.id, schema,
                               where=" AND ".join(conds),
                               order=_q(key.name), limit=bs)
            _, rows = conn.execute(sql)
            if not rows:
                return
            self._push_rows(rows, schema, table.id, pusher)
            last = rows[-1][schema.names().index(key.name)]
            if len(rows) < bs:
                return

    def _load_plain(self, conn: OracleConnection, table: TableDescription,
                    schema: TableSchema, pusher: Pusher) -> None:
        sql = self._select(table.id, schema, where=table.filter)
        _, rows = conn.execute(sql)
        if rows:
            self._push_rows(rows, schema, table.id, pusher)

    @staticmethod
    def _coerce(cs: ColSchema, v):
        """Wire value -> columnar representation (epoch ints for temporals,
        per the canonical model in abstract/schema.py)."""
        if v is None:
            return None
        t = cs.data_type
        if t == CanonicalType.DATETIME and isinstance(v, dt.datetime):
            return int(v.replace(tzinfo=dt.timezone.utc).timestamp())
        if t == CanonicalType.TIMESTAMP and isinstance(v, dt.datetime):
            return (calendar.timegm(v.timetuple()) * 1_000_000
                    + v.microsecond)
        if t == CanonicalType.DATE and isinstance(v, (dt.date, dt.datetime)):
            d = v.date() if isinstance(v, dt.datetime) else v
            return (d - dt.date(1970, 1, 1)).days
        if t == CanonicalType.DECIMAL and not isinstance(v, str):
            return str(v)
        if t.is_integer and isinstance(v, float) and v.is_integer():
            return int(v)
        return v

    def _push_rows(self, rows, schema: TableSchema, tid: TableID,
                   pusher: Pusher) -> None:
        names = schema.names()
        cols = {c.name: c for c in schema}
        data = {
            n: [self._coerce(cols[n], r[i]) for r in rows]
            for i, n in enumerate(names)
        }
        pusher(ColumnBatch.from_pydict(tid, schema, data))

    # -- intra-table sharding (provider/sharding_storage.go) ----------------
    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        """ORA_HASH(ROWID) modulo split.  The reference walks dba_extents
        into disjoint ROWID ranges (sharding_storage.go:42 splitByROWID) to
        avoid per-row hashing; the hash split needs no DBA views and keeps
        parts balanced, at full-scan cost per part."""
        n = self.params.desired_shards
        if n <= 1 or table.filter:
            return [table]
        eta = table.eta_rows or self.estimate_table_rows_count(table.id)
        return [
            TableDescription(
                id=table.id,
                filter=f"MOD(ORA_HASH(ROWID), {n}) = {i}",
                eta_rows=eta // n,
            )
            for i in range(n)
        ]

    # -- checksum sampling --------------------------------------------------
    RANDOM_SAMPLE_LIMIT = 2000
    TOP_BOTTOM_LIMIT = 1000

    def _sample_parts(self, tid: TableID):
        schema = self.table_schema(tid)
        order = ", ".join(_q(c.name) for c in schema.key_columns())
        return schema, order

    def load_random_sample(self, table: TableDescription,
                           pusher: Pusher) -> None:
        schema, order = self._sample_parts(table.id)
        sql = self._select(
            table.id, schema, where="DBMS_RANDOM.VALUE <= 0.05",
            order=order, limit=self.RANDOM_SAMPLE_LIMIT)
        _, rows = self.conn.execute(sql)
        if rows:
            self._push_rows(rows, schema, table.id, pusher)

    def load_top_bottom_sample(self, table: TableDescription,
                               pusher: Pusher) -> None:
        schema, order = self._sample_parts(table.id)
        if not order:
            raise OracleError(
                f"no primary key on {table.id.fqtn()}; "
                "cannot take top/bottom sample")
        desc = ", ".join(f"{c} DESC" for c in order.split(", "))
        for by in (order, desc):
            sql = self._select(table.id, schema, order=by,
                               limit=self.TOP_BOTTOM_LIMIT)
            _, rows = self.conn.execute(sql)
            if rows:
                self._push_rows(rows, schema, table.id, pusher)

    # per-statement cap on OR-disjuncts: keeps every generated SELECT
    # well under the 64KB TNS packet limit (tns.pack_packet)
    SAMPLE_SET_CHUNK = 200

    def load_sample_by_set(self, table: TableDescription, key_set,
                           pusher: Pusher) -> None:
        schema, _ = self._sample_parts(table.id)
        key_set = list(key_set)
        if not key_set:
            return
        for i in range(0, len(key_set), self.SAMPLE_SET_CHUNK):
            conds = [
                "(" + " AND ".join(
                    f"{_q(name)} = {_ora_literal(val)}"
                    for name, val in key.items()) + ")"
                for key in key_set[i:i + self.SAMPLE_SET_CHUNK]
            ]
            sql = self._select(table.id, schema,
                               where=" OR ".join(conds))
            _, rows = self.conn.execute(sql)
            if rows:
                self._push_rows(rows, schema, table.id, pusher)


@register_provider
class OracleProvider(Provider):
    NAME = "oracle"

    def storage(self):
        if isinstance(self.transfer.src, OracleSourceParams):
            return OracleStorage(self.transfer.src)
        return None

    def source(self):
        """LogMiner CDC (reference replication/log_miner/)."""
        if isinstance(self.transfer.src, OracleSourceParams):
            from transferia_tpu.providers.oracle.logminer import (
                OracleLogMinerSource,
            )

            return OracleLogMinerSource(
                self.transfer.src, self.transfer.id, self.coordinator)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            storage = OracleStorage(self.transfer.src)
            storage.ping()
            result.add("connect")
            result.add(f"table_list ({len(storage.table_list())} tables)")
            storage.close()
        except (OracleError, OSError) as e:
            result.add("connect", e)
        return result
