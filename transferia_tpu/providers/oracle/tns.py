"""Oracle TNS (Transparent Network Substrate) framing and data codecs.

Reference parity: pkg/providers/oracle/ connects through godror/OCI; this
framework speaks the wire directly, like its PG/MySQL/Mongo/YDB clients.

Faithful parts (public, documented formats):
- TNS packet framing: 8-byte header (length, checksum, type, flags), packet
  types CONNECT/ACCEPT/REFUSE/DATA/MARKER, connect descriptors
  ``(DESCRIPTION=(CONNECT_DATA=(SERVICE_NAME=...)))``;
- Oracle NUMBER binary format: base-100 exponent/mantissa with the sign
  fold and the 102 terminator on negatives;
- Oracle DATE (7-byte excess-100 century/year) and TIMESTAMP (11-byte with
  big-endian nanoseconds);
- native column type codes (VARCHAR2=1, NUMBER=2, DATE=12, ...).

Simplified parts (documented here so nobody mistakes this for OCI parity):
the TTC session layer uses these frames and value codecs but a reduced
message vocabulary (see wire.py), and long values use a 0xFE marker
followed by one uint32 length — not Oracle's real repeated-chunk
continuation encoding.
"""

from __future__ import annotations

import datetime as dt
import socket
import struct
from decimal import Decimal
from typing import Optional, Union

# -- packet types ------------------------------------------------------------

PKT_CONNECT = 1
PKT_ACCEPT = 2
PKT_ACK = 3
PKT_REFUSE = 4
PKT_REDIRECT = 5
PKT_DATA = 6
PKT_NULL = 7
PKT_ABORT = 9
PKT_RESEND = 11
PKT_MARKER = 12

# protocol version: 314 = 1.3.14, the classic TNS version pre-big-SDU
TNS_VERSION = 314
TNS_VERSION_MIN = 300

SDU = 8192
TDU = 32767


class TNSError(Exception):
    pass


# -- framing -----------------------------------------------------------------


def pack_packet(ptype: int, payload: bytes, flags: int = 0) -> bytes:
    """8-byte TNS header + payload (length includes the header)."""
    total = len(payload) + 8
    if total > 0xFFFF:
        raise TNSError(f"packet too large for 2-byte length: {total}")
    return struct.pack(">HHBBH", total, 0, ptype, flags, 0) + payload


def read_packet(sock: socket.socket) -> tuple[int, bytes]:
    hdr = _read_exact(sock, 8)
    length, _cksum, ptype, _flags, _hck = struct.unpack(">HHBBH", hdr)
    if length < 8:
        raise TNSError(f"bad TNS length {length}")
    return ptype, _read_exact(sock, length - 8)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TNSError("connection closed mid-packet")
        buf += chunk
    return buf


# -- connect / accept / refuse ----------------------------------------------


def build_connect(descriptor: str) -> bytes:
    """CONNECT packet body: version block + offset/length of the connect
    descriptor, which trails the fixed header."""
    data = descriptor.encode()
    fixed = struct.pack(
        ">HHHHHHHHIHH2x",
        TNS_VERSION, TNS_VERSION_MIN,
        0,              # service options
        SDU, TDU,
        0x7F08,         # protocol characteristics (NT flags)
        0, 1,           # line turnaround, "our one" byte-order probe
        len(data),      # connect data length
        34,             # descriptor offset: 8 header + 26 fixed bytes
        0,              # max receivable connect data
    )
    return fixed + data


def parse_connect(payload: bytes) -> str:
    (_ver, _low, _opts, _sdu, _tdu, _prot, _turn, _probe, dlen, doff,
     _maxr) = struct.unpack(">HHHHHHHHIHH", payload[:24])
    start = doff - 8
    return payload[start:start + dlen].decode()


def build_accept() -> bytes:
    return struct.pack(">HHHHIBB8x", TNS_VERSION, 0, SDU, TDU, 0, 0, 0)


def parse_accept(payload: bytes) -> int:
    (version,) = struct.unpack(">H", payload[:2])
    return version


def build_refuse(message: str) -> bytes:
    data = message.encode()
    return struct.pack(">BBH", 1, 2, len(data)) + data


def parse_refuse(payload: bytes) -> str:
    (_ureason, _sreason, dlen) = struct.unpack(">BBH", payload[:4])
    return payload[4:4 + dlen].decode(errors="replace")


def connect_descriptor(host: str, port: int, service_name: str = "",
                       sid: str = "") -> str:
    if service_name:
        cd = f"(SERVICE_NAME={service_name})"
    elif sid:
        cd = f"(SID={sid})"
    else:
        raise TNSError("need service_name or sid")
    return (
        f"(DESCRIPTION=(ADDRESS=(PROTOCOL=TCP)(HOST={host})(PORT={port}))"
        f"(CONNECT_DATA={cd}(CID=(PROGRAM=transferia_tpu))))"
    )


def parse_connect_data(descriptor: str) -> dict:
    """Pull SERVICE_NAME / SID out of a connect descriptor."""
    out = {}
    for key in ("SERVICE_NAME", "SID"):
        marker = f"({key}="
        i = descriptor.upper().find(marker)
        if i >= 0:
            j = descriptor.index(")", i)
            out[key.lower()] = descriptor[i + len(marker):j]
    return out


# -- scalar marshaling (length-prefixed big-endian, UB* style) ---------------


def write_uint(n: int) -> bytes:
    if n < 0:
        raise TNSError("uint only")
    if n == 0:
        return b"\x00"
    raw = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([len(raw)]) + raw


def read_uint(buf: bytes, pos: int) -> tuple[int, int]:
    ln = buf[pos]
    pos += 1
    if ln == 0:
        return 0, pos
    return int.from_bytes(buf[pos:pos + ln], "big"), pos + ln


def write_bytes(b: Optional[bytes]) -> bytes:
    """Length-prefixed byte string; 0xFF = NULL (single-chunk subset)."""
    if b is None:
        return b"\xff"
    if len(b) >= 0xFE:
        return b"\xfe" + struct.pack(">I", len(b)) + b
    return bytes([len(b)]) + b


def read_bytes(buf: bytes, pos: int) -> tuple[Optional[bytes], int]:
    ln = buf[pos]
    pos += 1
    if ln == 0xFF:
        return None, pos
    if ln == 0xFE:
        (real,) = struct.unpack(">I", buf[pos:pos + 4])
        pos += 4
        return buf[pos:pos + real], pos + real
    return buf[pos:pos + ln], pos + ln


def write_str(s: Optional[str]) -> bytes:
    return write_bytes(None if s is None else s.encode())


def read_str(buf: bytes, pos: int) -> tuple[Optional[str], int]:
    b, pos = read_bytes(buf, pos)
    return (None if b is None else b.decode()), pos


# -- Oracle NUMBER (base-100, excess-65 exponent) ----------------------------


def encode_number(value: Union[int, float, Decimal]) -> bytes:
    """Encode into Oracle's NUMBER wire format.

    Positive: [0xC1 + exp] [d1+1] [d2+1] ...      (digits base 100)
    Negative: [0x3E - exp] [101-d1] ... [102]     (sign-folded, terminated)
    Zero: [0x80].
    """
    if isinstance(value, float):
        value = Decimal(repr(value))
    else:
        value = Decimal(value)
    if value == 0:
        return b"\x80"
    neg = value < 0
    if neg:
        value = -value
    # normalize to d1.d2d3... * 100^exp with 1 <= d1 <= 99
    digits: list[int] = []
    exp = 0
    intpart = int(value)
    frac = value - intpart
    if intpart:
        while intpart:
            digits.insert(0, intpart % 100)
            intpart //= 100
        exp = len(digits) - 1
    else:
        exp = -1
        while frac and int(frac * 100) == 0:
            frac *= 100
            exp -= 1
    f = frac
    while f and len(digits) < 20:
        f *= 100
        d = int(f)
        digits.append(d)
        f -= d
    while digits and digits[-1] == 0:
        digits.pop()
    if not digits:
        return b"\x80"
    if neg:
        out = bytes([0x3E - exp]) + bytes(101 - d for d in digits)
        if len(digits) < 20:
            out += b"\x66"  # 102 terminator
        return out
    return bytes([0xC1 + exp]) + bytes(d + 1 for d in digits)


def decode_number(b: bytes) -> Union[int, float, Decimal]:
    if b == b"\x80":
        return 0
    head = b[0]
    if head & 0x80:  # positive
        exp = head - 0xC1
        digits = [x - 1 for x in b[1:]]
        neg = False
    else:
        exp = 0x3E - head
        digits = [101 - x for x in b[1:]]
        if digits and digits[-1] == -1:  # 102 terminator
            digits.pop()
        neg = True
    value = Decimal(0)
    for i, d in enumerate(digits):
        value += Decimal(d) * (Decimal(100) ** (exp - i))
    if neg:
        value = -value
    if value == value.to_integral_value():
        iv = int(value)
        if -(2 ** 63) <= iv < 2 ** 63:
            return iv
    # keep the exact Decimal whenever float would lose precision (wide
    # NUMBER(>18) keys, high-scale decimals); float only when lossless
    f = float(value)
    if Decimal(f) == value:
        return f
    return value


# -- Oracle DATE / TIMESTAMP -------------------------------------------------


def encode_date(value: Union[dt.date, dt.datetime]) -> bytes:
    if not isinstance(value, dt.datetime):
        value = dt.datetime(value.year, value.month, value.day)
    return bytes([
        value.year // 100 + 100, value.year % 100 + 100,
        value.month, value.day,
        value.hour + 1, value.minute + 1, value.second + 1,
    ])


def decode_date(b: bytes) -> dt.datetime:
    return dt.datetime(
        (b[0] - 100) * 100 + (b[1] - 100), b[2], b[3],
        b[4] - 1, b[5] - 1, b[6] - 1,
    )


def encode_timestamp(value: dt.datetime) -> bytes:
    ns = value.microsecond * 1000
    return encode_date(value) + struct.pack(">I", ns)


def decode_timestamp(b: bytes) -> dt.datetime:
    base = decode_date(b[:7])
    (ns,) = struct.unpack(">I", b[7:11])
    return base.replace(microsecond=ns // 1000)


# -- native column type codes ------------------------------------------------

ORA_VARCHAR2 = 1
ORA_NUMBER = 2
ORA_LONG = 8
ORA_DATE = 12
ORA_RAW = 23
ORA_LONG_RAW = 24
ORA_CHAR = 96
ORA_BINARY_FLOAT = 100
ORA_BINARY_DOUBLE = 101
ORA_CLOB = 112
ORA_BLOB = 113
ORA_TIMESTAMP = 180
ORA_TIMESTAMP_TZ = 181
ORA_INTERVAL_DS = 183


def encode_value(type_code: int, value) -> bytes:
    """One column value in wire form (NULL-aware, type-directed)."""
    if value is None:
        return write_bytes(None)
    if type_code == ORA_NUMBER:
        return write_bytes(encode_number(value))
    if type_code in (ORA_BINARY_FLOAT, ORA_BINARY_DOUBLE):
        fmt = ">f" if type_code == ORA_BINARY_FLOAT else ">d"
        return write_bytes(struct.pack(fmt, float(value)))
    if type_code == ORA_DATE:
        if isinstance(value, str):
            value = dt.datetime.fromisoformat(value)
        return write_bytes(encode_date(value))
    if type_code in (ORA_TIMESTAMP, ORA_TIMESTAMP_TZ):
        if isinstance(value, str):
            value = dt.datetime.fromisoformat(value)
        return write_bytes(encode_timestamp(value))
    if type_code in (ORA_RAW, ORA_LONG_RAW, ORA_BLOB):
        if isinstance(value, str):
            value = value.encode()
        return write_bytes(bytes(value))
    return write_bytes(str(value).encode())


def decode_value(type_code: int, buf: bytes, pos: int):
    raw, pos = read_bytes(buf, pos)
    if raw is None:
        return None, pos
    if type_code == ORA_NUMBER:
        return decode_number(raw), pos
    if type_code == ORA_BINARY_FLOAT:
        return struct.unpack(">f", raw)[0], pos
    if type_code == ORA_BINARY_DOUBLE:
        return struct.unpack(">d", raw)[0], pos
    if type_code == ORA_DATE:
        return decode_date(raw), pos
    if type_code in (ORA_TIMESTAMP, ORA_TIMESTAMP_TZ):
        return decode_timestamp(raw), pos
    if type_code in (ORA_RAW, ORA_LONG_RAW, ORA_BLOB):
        return raw, pos
    return raw.decode(errors="replace"), pos
