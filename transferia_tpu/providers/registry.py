"""Provider base + registry (provider.go:15-94)."""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Type

from transferia_tpu.abstract.interfaces import (
    AsyncSink,
    Sinker,
    Source,
    Storage,
)
from transferia_tpu.stats.registry import Metrics


@dataclass
class TestResult:
    """Endpoint connectivity check result (provider Tester)."""

    ok: bool
    checks: dict[str, str] = field(default_factory=dict)  # name -> "ok"/err

    def add(self, name: str, err: Optional[BaseException] = None) -> None:
        self.checks[name] = "ok" if err is None else str(err)
        if err is not None:
            self.ok = False


class ActivateCallbacks:
    """Hooks handed to Provider.activate (provider_tasks.go Activator).

    `rollbacks` (a utils.rollbacks.Rollbacks, when the activate task
    provides one) lets a custom activate hook register undos for source
    resources it acquires — they run only if the activation fails.
    """

    def __init__(self, cleanup: Callable[[list], None],
                 upload: Callable[[list], None],
                 rollbacks=None):
        self.cleanup = cleanup
        self.upload = upload
        self.rollbacks = rollbacks


class _SniffDone(Exception):
    """Internal: stop a sniff load after enough rows."""


class Provider(abc.ABC):
    """One connector.  Subclasses override the capabilities they support;
    the default None/NotImplemented signals 'capability absent' the way the
    reference's interface assertions do (provider.go:15-88)."""

    NAME = ""

    def __init__(self, transfer, metrics: Optional[Metrics] = None,
                 coordinator=None):
        self.transfer = transfer
        self.metrics = metrics or Metrics()
        # control-plane handle for sources that checkpoint positions
        # (wal LSN, binlog pos, incremental cursors) — may be None for
        # pure snapshot flows
        self.coordinator = coordinator

    # -- capabilities (return None when unsupported) ------------------------
    def storage(self) -> Optional[Storage]:
        """Snapshot capability."""
        return None

    def destination_storage(self) -> Optional[Storage]:
        """Storage view of the *target* endpoint, for checksum validation
        (provider.go:84-88 Checksumable.DestinationChecksumableStorage)."""
        return None

    def snapshot_provider(self):
        """Event-model-v2 snapshot capability (abstract2/transfer.go:212
        SnapshotProvider); None = v1 Storage path only."""
        return None

    def event_target(self):
        """Native event-model-v2 target (abstract2/transfer.go:201
        EventTarget); None = v1 sink wrapped via EventTargetOverAsyncSink."""
        return None

    def source(self) -> Optional[Source]:
        """Replication capability."""
        return None

    def sinker(self) -> Optional[Sinker]:
        """Sync sink capability."""
        return None

    def snapshot_sinker(self) -> Optional[Sinker]:
        """Dedicated snapshot-stage sink (SnapshotSinker), else sinker()."""
        return None

    def async_sink(self) -> Optional[AsyncSink]:
        """Native AsyncSink (e.g. CH async insert path)."""
        return None

    def activate(self, callbacks: ActivateCallbacks) -> None:
        """Custom activation flow (Activator); default = cleanup + upload of
        all tables, implemented by the activate task itself."""
        raise NotImplementedError

    def supports_activate(self) -> bool:
        return type(self).activate is not Provider.activate

    def cleanup(self, tables: list) -> None:
        """Cleanuper: drop/truncate target tables per cleanup_policy."""

    def test(self) -> TestResult:
        """Tester: connectivity / permissions checks."""
        return TestResult(ok=True)

    def deactivate(self) -> None:
        """Deactivator: release source resources (slots etc.)."""

    SNIFF_TABLE_CAP = 20

    def sniff(self, max_rows: int = 10) -> dict:
        """Sniffer (provider.go Sniffer): preview a sample of rows from up
        to SNIFF_TABLE_CAP tables (a "_truncated" entry reports how many
        tables were skipped).  Default implementation samples through the
        storage capability.
        """
        storage = self.storage()
        if storage is None:
            raise NotImplementedError(
                f"provider {self.NAME!r} has no snapshot capability to "
                f"sniff"
            )
        from transferia_tpu.abstract.table import TableDescription

        out: dict[str, list] = {}
        try:
            all_tables = list(storage.table_list())
            if len(all_tables) > self.SNIFF_TABLE_CAP:
                out["_truncated"] = [
                    f"{len(all_tables) - self.SNIFF_TABLE_CAP} more "
                    f"tables not sampled"
                ]
            for tid in all_tables[:self.SNIFF_TABLE_CAP]:
                rows: list = []

                def pusher(batch, _rows=rows):
                    items = batch.to_rows() \
                        if hasattr(batch, "to_rows") else batch
                    for it in items:
                        if it.is_row_event():
                            _rows.append(it.as_dict())
                            if len(_rows) >= max_rows:
                                raise _SniffDone()

                try:
                    storage.load_table(TableDescription(id=tid), pusher)
                except _SniffDone:
                    pass
                out[str(tid)] = rows
        finally:
            storage.close()
        return out


_PROVIDERS: dict[str, Type[Provider]] = {}


def register_provider(cls: Type[Provider]) -> Type[Provider]:
    if not cls.NAME:
        raise ValueError("provider class must set NAME")
    _PROVIDERS[cls.NAME] = cls
    return cls


def get_provider(name: str, transfer, metrics: Optional[Metrics] = None,
                 coordinator=None) -> Provider:
    cls = _PROVIDERS.get(name)
    if cls is None:
        from transferia_tpu.providers import load_builtin_providers

        load_builtin_providers()
        cls = _PROVIDERS.get(name)
    if cls is None:
        raise KeyError(
            f"unknown provider {name!r}; registered: {sorted(_PROVIDERS)}"
        )
    return cls(transfer, metrics, coordinator)


def registered_providers() -> list[str]:
    from transferia_tpu.providers import load_builtin_providers

    load_builtin_providers()
    return sorted(_PROVIDERS)
