"""S3 object format readers (reference: pkg/providers/s3/reader/registry/
— csv/json/line/nginx/parquet/proto with schema inference,
reader/abstract.go:40-52).

A Reader turns one object into ColumnBatches.  Formats:
  parquet — arrow row groups straight to columnar (zero pivot)
  csv     — arrow CSV with inferred schema
  jsonl   — newline-delimited JSON, schema inferred from a sample
  line    — each line one row (utf8) + system columns
  nginx   — nginx log_format template parsing ($var tokens), typed fields
  proto   — varint length-prefixed protobuf frames through the protobuf
            parser plugin (descriptor config)

line/nginx add the reference's system columns __file_name/__row_index
(reader/abstract.go:16-17, AppendSystemColsTableSchema) as primary keys so
replicated rows stay addressable.  Parse failures follow unparsed_policy:
"route" sends them to the parsers' `_unparsed` system table
(pkg/parsers/utils.go:145), "skip" drops them counted, "fail" raises.
"""

from __future__ import annotations

import abc
import io
import json
import logging
import re
from typing import Callable, Iterable, Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.columnar.batch import ColumnBatch, arrow_to_table_schema
from transferia_tpu.parsers.base import Message, unparsed_batch

logger = logging.getLogger(__name__)

FILE_NAME_COL = "__file_name"
ROW_INDEX_COL = "__row_index"

Pusher = Callable[[ColumnBatch], None]


class ReaderError(CategorizedError):
    def __init__(self, message: str):
        super().__init__(CategorizedError.SOURCE, message)


class Reader(abc.ABC):
    """One-object reader; fs is an fsspec filesystem."""

    @abc.abstractmethod
    def infer_schema(self, fs, path: str) -> TableSchema:
        ...

    @abc.abstractmethod
    def read(self, fs, path: str, tid: TableID, schema: TableSchema,
             batch_rows: int, pusher: Pusher) -> None:
        ...

    def estimate_rows(self, fs, path: str) -> int:
        return 0


def _system_cols() -> list[ColSchema]:
    return [
        ColSchema(FILE_NAME_COL, CanonicalType.UTF8, primary_key=True),
        ColSchema(ROW_INDEX_COL, CanonicalType.INT64, primary_key=True),
    ]


class ParquetReader(Reader):
    def infer_schema(self, fs, path: str) -> TableSchema:
        import pyarrow.parquet as pq

        with fs.open(path, "rb") as fh:
            return arrow_to_table_schema(pq.read_schema(fh))

    def estimate_rows(self, fs, path: str) -> int:
        import pyarrow.parquet as pq

        with fs.open(path, "rb") as fh:
            return pq.ParquetFile(fh).metadata.num_rows

    def read(self, fs, path, tid, schema, batch_rows, pusher) -> None:
        import pyarrow.parquet as pq

        with fs.open(path, "rb") as fh:
            pf = pq.ParquetFile(fh)
            for rb in pf.iter_batches(batch_size=batch_rows):
                if rb.num_rows:
                    batch = ColumnBatch.from_arrow(rb, tid, schema)
                    batch.read_bytes = rb.nbytes
                    pusher(batch)


class CsvReader(Reader):
    def infer_schema(self, fs, path: str) -> TableSchema:
        import pyarrow.csv as pacsv

        with fs.open(path, "rb") as fh:
            head = fh.read(1 << 20)
        with pacsv.open_csv(io.BytesIO(head)) as reader:
            return arrow_to_table_schema(reader.schema)

    def read(self, fs, path, tid, schema, batch_rows, pusher) -> None:
        import pyarrow.csv as pacsv

        with fs.open(path, "rb") as fh:
            data = fh.read()
        with pacsv.open_csv(io.BytesIO(data)) as reader:
            for rb in reader:
                if rb.num_rows:
                    batch = ColumnBatch.from_arrow(rb, tid, schema)
                    batch.read_bytes = rb.nbytes
                    pusher(batch)


class JsonlReader(Reader):
    def infer_schema(self, fs, path: str) -> TableSchema:
        import pyarrow as pa

        rows = []
        with fs.open(path, "rb") as fh:
            for i, line in enumerate(fh):
                if i >= 100:
                    break
                if line.strip():
                    rows.append(json.loads(line))
        return arrow_to_table_schema(pa.Table.from_pylist(rows).schema)

    def read(self, fs, path, tid, schema, batch_rows, pusher) -> None:
        rows: list[dict] = []
        nbytes = 0
        with fs.open(path, "rb") as fh:
            for line in fh:
                if not line.strip():
                    continue
                rows.append(json.loads(line))
                nbytes += len(line)
                if len(rows) >= batch_rows:
                    self._push(rows, nbytes, tid, schema, pusher)
                    rows, nbytes = [], 0
        if rows:
            self._push(rows, nbytes, tid, schema, pusher)

    @staticmethod
    def _push(rows, nbytes, tid, schema, pusher):
        data = {c.name: [r.get(c.name) for r in rows] for c in schema}
        batch = ColumnBatch.from_pydict(tid, schema, data)
        batch.read_bytes = nbytes
        pusher(batch)


class LineReader(Reader):
    """Each line one row (registry/line): `line` utf8 + system columns."""

    SCHEMA = TableSchema([ColSchema("line", CanonicalType.UTF8)]
                         + _system_cols())

    def infer_schema(self, fs, path: str) -> TableSchema:
        return self.SCHEMA

    def read(self, fs, path, tid, schema, batch_rows, pusher) -> None:
        lines: list[str] = []
        idx0 = 0
        nbytes = 0
        row = 0
        with fs.open(path, "rb") as fh:
            for raw in fh:
                text = raw.decode("utf-8", errors="replace").rstrip("\r\n")
                if not text.strip():
                    row += 1
                    continue
                if not lines:
                    idx0 = row
                lines.append(text)
                nbytes += len(raw)
                row += 1
                if len(lines) >= batch_rows:
                    self._push(lines, idx0, nbytes, path, tid, pusher)
                    lines, nbytes = [], 0
        if lines:
            self._push(lines, idx0, nbytes, path, tid, pusher)

    def _push(self, lines, idx0, nbytes, path, tid, pusher):
        # row indices are per-pushed-row dense from the first line of the
        # buffer; blank lines advance the file row counter but aren't rows
        batch = ColumnBatch.from_pydict(tid, self.SCHEMA, {
            "line": lines,
            FILE_NAME_COL: [path] * len(lines),
            ROW_INDEX_COL: list(range(idx0, idx0 + len(lines))),
        })
        batch.read_bytes = nbytes
        pusher(batch)


# default combined log format (nginx docs)
NGINX_COMBINED = (
    '$remote_addr - $remote_user [$time_local] "$request" '
    '$status $body_bytes_sent "$http_referer" "$http_user_agent"'
)

_NGINX_INT = {"status", "body_bytes_sent", "bytes_sent", "request_length",
              "connection", "connection_requests", "content_length"}
_NGINX_FLOAT = {"request_time", "upstream_response_time", "msec",
                "upstream_connect_time", "upstream_header_time"}
_VAR_RE = re.compile(r"\$([A-Za-z0-9_]+)")


class NginxReader(Reader):
    """nginx log_format template parser (registry/nginx): literals match
    exactly, variables capture up to the next literal."""

    def __init__(self, log_format: str = "",
                 unparsed_policy: str = "route"):
        fmt = (log_format or NGINX_COMBINED).strip()
        fmt = re.sub(r"[ \t]*\n[ \t]*", " ", fmt)
        self.tokens: list[tuple[bool, str]] = []
        last = 0
        for m in _VAR_RE.finditer(fmt):
            if m.start() > last:
                self.tokens.append((False, fmt[last:m.start()]))
            self.tokens.append((True, m.group(1)))
            last = m.end()
        if last < len(fmt):
            self.tokens.append((False, fmt[last:]))
        self.fields = [v for is_var, v in self.tokens if is_var]
        if not self.fields:
            raise ReaderError(f"nginx format has no variables: {fmt!r}")
        self.unparsed_policy = unparsed_policy
        cols = []
        for f in self.fields:
            if f in _NGINX_INT:
                t = CanonicalType.INT64
            elif f in _NGINX_FLOAT:
                t = CanonicalType.DOUBLE
            else:
                t = CanonicalType.UTF8
            cols.append(ColSchema(f, t))
        self.schema = TableSchema(cols + _system_cols())

    def infer_schema(self, fs, path: str) -> TableSchema:
        return self.schema

    def parse_line(self, line: str) -> Optional[list]:
        values: list = []
        pos = 0
        n = len(self.tokens)
        for i, (is_var, val) in enumerate(self.tokens):
            if not is_var:
                lit = val
                if line.startswith(lit, pos):
                    pos += len(lit)
                elif i == 0 and line.startswith(lit.lstrip(), pos):
                    pos += len(lit.lstrip())
                else:
                    return None
                continue
            # variable: capture up to the next literal (or line end)
            if i + 1 < n and not self.tokens[i + 1][0]:
                nxt = self.tokens[i + 1][1]
                end = line.find(nxt, pos)
                if end < 0:
                    return None
            else:
                end = len(line)
            values.append(line[pos:end])
            pos = end
        out: list = []
        for f, raw in zip(self.fields, values):
            if f in _NGINX_INT:
                try:
                    out.append(int(raw))
                except ValueError:
                    out.append(None)
            elif f in _NGINX_FLOAT:
                try:
                    out.append(float(raw))
                except ValueError:
                    out.append(None)  # e.g. '-' for upstream times
            else:
                out.append(raw)
        return out

    def read(self, fs, path, tid, schema, batch_rows, pusher) -> None:
        good: list[list] = []
        good_idx: list[int] = []
        bad: list[Message] = []
        reasons: list[str] = []
        nbytes = 0
        with fs.open(path, "rb") as fh:
            for row, raw in enumerate(fh):
                text = raw.decode("utf-8",
                                  errors="replace").rstrip("\r\n")
                if not text.strip():
                    continue
                nbytes += len(raw)
                vals = self.parse_line(text)
                if vals is None:
                    if self.unparsed_policy == "fail":
                        raise ReaderError(
                            f"nginx parse failed at {path}:{row}: "
                            f"{text[:200]!r}")
                    if self.unparsed_policy == "route":
                        bad.append(Message(value=raw, topic=path,
                                           offset=row))
                        reasons.append("nginx format mismatch")
                        if len(bad) >= batch_rows:
                            # flush: a fully-mismatched multi-GB log must
                            # not accumulate in memory
                            pusher(unparsed_batch(bad, reasons))
                            bad, reasons = [], []
                    continue
                good.append(vals)
                good_idx.append(row)
                if len(good) >= batch_rows:
                    self._push(good, good_idx, nbytes, path, tid, pusher)
                    good, good_idx, nbytes = [], [], 0
        if good:
            self._push(good, good_idx, nbytes, path, tid, pusher)
        if bad:
            pusher(unparsed_batch(bad, reasons))

    def _push(self, rows, idx, nbytes, path, tid, pusher):
        data = {f: [r[i] for r in rows]
                for i, f in enumerate(self.fields)}
        data[FILE_NAME_COL] = [path] * len(rows)
        data[ROW_INDEX_COL] = idx
        batch = ColumnBatch.from_pydict(tid, self.schema, data)
        batch.read_bytes = nbytes
        pusher(batch)


class ProtoReader(Reader):
    """Varint length-prefixed protobuf frames (registry/proto) decoded by
    the protobuf parser plugin (descriptor config in `parser`)."""

    def __init__(self, parser_config: dict):
        from transferia_tpu.parsers import make_parser

        if not parser_config or "protobuf" not in parser_config:
            raise ReaderError(
                "proto format needs a {'protobuf': {...}} parser config")
        self.parser = make_parser(parser_config)

    def infer_schema(self, fs, path: str) -> TableSchema:
        schema = self.parser.result_schema()
        if schema is not None:
            return schema
        # sample the first frames of the object (reader/abstract.go:40-52)
        with fs.open(path, "rb") as fh:
            data = fh.read(1 << 20)
        msgs: list[Message] = []
        try:
            for idx, frame in self._frames(data):
                msgs.append(Message(value=frame, topic=path, offset=idx))
                if len(msgs) >= 100:
                    break
        except ReaderError:
            pass  # truncated tail of the sample window
        result = self.parser.do_batch(msgs)
        if result.batches:
            return result.batches[0].schema
        raise ReaderError(f"could not infer proto schema from {path}")

    @staticmethod
    def _frames(data: bytes) -> Iterable[tuple[int, bytes]]:
        pos, n, idx = 0, len(data), 0
        while pos < n:
            shift, length = 0, 0
            while True:
                if pos >= n:
                    raise ReaderError("truncated varint length prefix")
                b = data[pos]
                pos += 1
                length |= (b & 0x7F) << shift
                if not (b & 0x80):
                    break
                shift += 7
                if shift > 63:
                    raise ReaderError("varint length prefix overflow")
            if pos + length > n:
                raise ReaderError("truncated protobuf frame")
            yield idx, data[pos:pos + length]
            pos += length
            idx += 1

    def read(self, fs, path, tid, schema, batch_rows, pusher) -> None:
        with fs.open(path, "rb") as fh:
            data = fh.read()
        msgs: list[Message] = []
        for idx, frame in self._frames(data):
            msgs.append(Message(value=frame, topic=path, offset=idx))
            if len(msgs) >= batch_rows:
                self._flush(msgs, tid, pusher)
                msgs = []
        if msgs:
            self._flush(msgs, tid, pusher)

    def _flush(self, msgs, tid, pusher):
        result = self.parser.do_batch(msgs)
        for b in result.batches:
            pusher(b.rename_table(tid))
        if result.unparsed is not None and result.unparsed.n_rows:
            pusher(result.unparsed)


def make_reader(fmt: str, *, nginx_format: str = "",
                unparsed_policy: str = "route",
                parser_config: Optional[dict] = None) -> Reader:
    if fmt == "parquet":
        return ParquetReader()
    if fmt == "csv":
        return CsvReader()
    if fmt == "jsonl":
        return JsonlReader()
    if fmt == "line":
        return LineReader()
    if fmt == "nginx":
        return NginxReader(nginx_format, unparsed_policy)
    if fmt == "proto":
        return ProtoReader(parser_config or {})
    raise ReaderError(f"unknown s3 format {fmt!r} (parquet/csv/jsonl/"
                      f"line/nginx/proto)")
