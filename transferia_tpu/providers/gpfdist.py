"""In-process gpfdist endpoint: Greenplum's segment-direct data path.

Reference: pkg/providers/greenplum/gpfdist/ + gpfdist_storage.go /
gpfdist_sink.go — the reference shells out to the actual gpfdist binary
and reads named pipes; here the worker IS the gpfdist endpoint: a small
HTTP server speaking the protocol subset Greenplum segments use for
external tables, so table data flows segment -> worker (unload) or
worker -> segment (load) WITHOUT passing through the master connection.
The master connection only runs the control statements (CREATE EXTERNAL
TABLE / INSERT ... SELECT).

Protocol subset (the simple gpfdist HTTP exchange):
  - segments identify with X-GP-SEGMENT-ID / X-GP-SEGMENT-COUNT and a
    transfer id X-GP-XID
  - unload (WRITABLE EXTERNAL TABLE): each segment POSTs its rows as
    CSV chunks to gpfdist://host:port/<slot>; a final empty POST with
    X-GP-DONE: 1 closes that segment's stream
  - load (READABLE EXTERNAL TABLE): segments GET the same URL; every
    response hands out the next pending CSV chunk, an empty 200 body
    means end of data
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class GpfdistServer:
    """One slot per concurrent table transfer.

    Unload: register_sink(slot, on_chunk) routes segment POST bodies to
    the callback (called from server threads — callbacks synchronize);
    wait_done(slot, n_segments) blocks until every segment finished.
    Load: put_chunk(slot, data) queues CSV chunks; finish(slot) marks
    EOF (subsequent GETs drain the queue, then read empty)."""

    def __init__(self, host: str = "127.0.0.1"):
        self.host = host
        self._sinks: dict[str, Callable[[bytes], None]] = {}
        self._done: dict[str, set] = {}
        self._done_ev: dict[str, threading.Event] = {}
        self._expect: dict[str, int] = {}
        self._out: dict[str, queue.Queue] = {}
        self._finished: set[str] = set()
        self._lock = threading.Lock()
        self.port = 0
        self._srv: Optional[ThreadingHTTPServer] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "GpfdistServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _slot(self):
                return self.path.lstrip("/").split("?")[0]

            def do_POST(self):
                slot = self._slot()
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length)
                seg = self.headers.get("X-GP-SEGMENT-ID", "0")
                done = self.headers.get("X-GP-DONE")
                try:
                    server._on_post(slot, seg, body, bool(done))
                except Exception as e:  # surfaces via wait_done timeout
                    logger.error("gpfdist sink error: %s", e)
                    self.send_response(500)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_GET(self):
                slot = self._slot()
                data = server._next_chunk(slot)
                if data is None:
                    # unregistered slot (stray GET after release(), a
                    # typo'd location) or a wedged load past the drain
                    # deadline — 404 fails the segment scan instead of
                    # pinning a server thread or faking a clean EOF
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/csv")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                if data:
                    self.wfile.write(data)

            def log_message(self, *a):  # quiet
                pass

        self._srv = ThreadingHTTPServer((self.host, 0), Handler)
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._srv:
            self._srv.shutdown()
            self._srv.server_close()

    def location(self, slot: str) -> str:
        return f"gpfdist://{self.host}:{self.port}/{slot}"

    # -- unload (segments POST) ----------------------------------------------
    def register_sink(self, slot: str,
                      on_chunk: Callable[[str, bytes, bool], None],
                      n_segments: int) -> None:
        """on_chunk(segment_id, body, done): bodies arrive PER SEGMENT at
        arbitrary byte boundaries — reframing state must key on the
        segment id, never be shared across segments."""
        with self._lock:
            self._sinks[slot] = on_chunk
            self._done[slot] = set()
            self._done_ev[slot] = threading.Event()
            self._expect[slot] = n_segments

    def _on_post(self, slot: str, seg: str, body: bytes,
                 done: bool) -> None:
        sink = self._sinks.get(slot)
        if sink is None:
            raise KeyError(f"unknown gpfdist slot {slot!r}")
        if body or done:
            sink(seg, body, done)
        if done:
            with self._lock:
                self._done[slot].add(seg)
                if len(self._done[slot]) >= self._expect[slot]:
                    self._done_ev[slot].set()

    def wait_done(self, slot: str, timeout: float = 600.0) -> None:
        ev = self._done_ev[slot]
        if not ev.wait(timeout):
            with self._lock:
                got = len(self._done.get(slot, ()))
                want = self._expect.get(slot, 0)
            raise TimeoutError(
                f"gpfdist unload {slot}: {got}/{want} segments "
                f"finished within {timeout}s")

    def release(self, slot: str) -> None:
        with self._lock:
            self._sinks.pop(slot, None)
            self._done.pop(slot, None)
            self._done_ev.pop(slot, None)
            self._expect.pop(slot, None)
            self._out.pop(slot, None)
            self._finished.discard(slot)

    # -- load (segments GET) --------------------------------------------------
    def put_chunk(self, slot: str, data: bytes) -> None:
        with self._lock:
            q = self._out.setdefault(slot, queue.Queue())
        q.put(data)

    def finish(self, slot: str) -> None:
        with self._lock:
            self._finished.add(slot)
            self._out.setdefault(slot, queue.Queue())

    # a load that stalls longer than this between chunks is wedged; the
    # deadline keeps stray segment GETs from pinning server threads
    DRAIN_TIMEOUT = 600.0

    def _next_chunk(self, slot: str) -> Optional[bytes]:
        """Next pending CSV chunk for a segment GET.

        Returns None for slots never registered via put_chunk/finish
        (the handler answers 404); b"" signals end-of-data.  Never
        recreates a released slot's queue — before the deadline was
        added, a GET arriving after release() would setdefault a fresh
        queue and spin forever."""
        with self._lock:
            q = self._out.get(slot)
        if q is None:
            return None
        deadline = time.monotonic() + self.DRAIN_TIMEOUT
        while True:
            try:
                return q.get(timeout=0.2)
            except queue.Empty:
                with self._lock:
                    if slot in self._finished and q.empty():
                        return b""
                    if slot not in self._out:
                        return None  # released mid-drain
                if time.monotonic() > deadline:
                    # 404, NOT the b"" end-of-data sentinel: a clean
                    # EOF here would let the INSERT..SELECT commit a
                    # partial table; erroring the segment GET fails the
                    # load loudly instead
                    logger.warning(
                        "gpfdist load %s: no chunk within %.0fs; "
                        "failing the stream", slot, self.DRAIN_TIMEOUT)
                    return None
