"""Azure Event Hubs replication source (reference: pkg/providers/eventhub/).

Event Hubs exposes an official Kafka-compatible endpoint
(<namespace>.servicebus.windows.net:9093, TLS + SASL PLAIN with user
"$ConnectionString" and the connection string as the password).  This
provider rides the framework's Kafka wire client over that surface —
the reference's AMQP client and this implementation consume the same
hubs; the Kafka surface is the one a dependency-free client can speak.

Partitions, offset checkpoints, parser plumbing and the at-least-once
ack discipline are exactly the Kafka source's (providers/kafka/).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)


@register_endpoint
@dataclass
class EventHubSourceParams(EndpointParams):
    PROVIDER = "eventhub"
    IS_SOURCE = True
    # queue sources cannot be re-read from scratch: reupload
    # is forbidden (model/endpoint.go AppendOnlySource)
    is_append_only = True

    namespace: str = ""          # <name>.servicebus.windows.net (or host)
    hub: str = ""                # the event hub (Kafka topic)
    connection_string: str = ""  # Endpoint=sb://...;SharedAccessKey=...
    port: int = 9093
    tls: bool = True             # the public endpoint requires TLS
    tls_ca: str = ""
    consumer_group: str = "$Default"
    parser: Optional[dict] = None
    parallelism: int = 4
    start_from: str = "earliest"

    def parser_config(self):
        return self.parser

    def to_kafka_params(self):
        from transferia_tpu.providers.kafka.provider import (
            KafkaSourceParams,
        )

        host = self.namespace
        if host and "." not in host:
            host = f"{host}.servicebus.windows.net"
        return KafkaSourceParams(
            brokers=[f"{host}:{self.port}"],
            topic=self.hub,
            parser=self.parser,
            parallelism=self.parallelism,
            start_from=self.start_from,
            tls=self.tls,
            tls_ca=self.tls_ca,
            sasl_mechanism="PLAIN",
            sasl_username="$ConnectionString",
            sasl_password=self.connection_string,
        )


@register_provider
class EventHubProvider(Provider):
    NAME = "eventhub"

    def source(self):
        if not isinstance(self.transfer.src, EventHubSourceParams):
            return None
        from transferia_tpu.providers.kafka.provider import (
            _KafkaQueueClient,
        )
        from transferia_tpu.providers.queue_common import QueueSource

        params = self.transfer.src.to_kafka_params()
        client = _KafkaQueueClient(params, self.transfer.id,
                                   self.coordinator)
        return QueueSource(client, self.transfer.src.parser_config(),
                           parallelism=self.transfer.src.parallelism,
                           metrics=self.metrics,
                           transfer_id=self.transfer.id)

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            if isinstance(self.transfer.src, EventHubSourceParams):
                from transferia_tpu.providers.kafka.client import (
                    KafkaClient,
                )

                p = self.transfer.src.to_kafka_params()
                client = KafkaClient(
                    p.brokers, tls=p.tls, tls_ca=p.tls_ca,
                    sasl_mechanism=p.sasl_mechanism,
                    sasl_username=p.sasl_username,
                    sasl_password=p.sasl_password,
                )
                client.metadata([self.transfer.src.hub])
                client.close()
            result.add("connect")
        except Exception as e:
            result.add("connect", e)
        return result
