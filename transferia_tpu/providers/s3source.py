"""S3 replication source: new-object events -> reader -> async sink.

Reference parity: pkg/providers/s3/source/ (S3Source over an
ObjectFetcher) + s3util/object_fetcher/ — two fetch strategies:

  sqs  — S3 bucket notifications through an SQS queue (JSON protocol,
         SigV4 via utils/awssign); creation events are unwrapped (plain or
         SNS-enveloped), filtered by path pattern, and their messages are
         deleted only AFTER the object's rows are durably pushed
         (at-least-once, object_fetcher_sqs.go).
  poll — periodic listing with a (mtime, name) watermark persisted in the
         coordinator transfer-state KV (object_fetcher_poller.go).

Objects decode through the same reader registry as snapshots
(providers/s3readers.py) so every format — including line/nginx/proto —
replicates.
"""

from __future__ import annotations

import fnmatch
import json
import logging
import re
import threading
import time
import urllib.parse
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import AsyncSink, Source
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.coordinator.interface import Coordinator

logger = logging.getLogger(__name__)


class S3SourceError(CategorizedError):
    def __init__(self, message: str):
        super().__init__(CategorizedError.SOURCE, message)


class SQSClient:
    """Minimal SQS client (JSON protocol, AmazonSQS.* targets)."""

    def __init__(self, queue_url: str, region: str = "us-east-1",
                 access_key: str = "", secret_key: str = "",
                 endpoint: str = "", timeout: float = 70.0):
        import http.client  # noqa: F401 - used in call()

        self.queue_url = queue_url
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.timeout = timeout
        base = endpoint or queue_url
        parsed = urllib.parse.urlparse(base)
        self.host = parsed.hostname or ""
        self.port = parsed.port or (443 if parsed.scheme == "https" else 80)
        self.secure = parsed.scheme == "https"

    def call(self, action: str, payload: dict) -> dict:
        import http.client

        from transferia_tpu.utils.awssign import sign_request

        body = json.dumps(payload).encode()
        default = 443 if self.secure else 80
        host = self.host if self.port == default \
            else f"{self.host}:{self.port}"
        headers = sign_request(
            "POST", host, "/", {}, {
                "content-type": "application/x-amz-json-1.0",
                "x-amz-target": f"AmazonSQS.{action}",
            }, body, self.region, "sqs", self.access_key, self.secret_key,
        )
        cls = (http.client.HTTPSConnection if self.secure
               else http.client.HTTPConnection)
        conn = cls(self.host, self.port, timeout=self.timeout)
        try:
            conn.request("POST", "/", body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            try:
                obj = json.loads(data) if data else {}
            except ValueError:
                obj = {"message": data[:200].decode("utf-8", "replace")}
            if resp.status != 200:
                raise S3SourceError(
                    f"sqs {action}: {obj.get('message', resp.status)}")
            return obj
        finally:
            conn.close()

    def receive(self, max_messages: int = 10,
                wait_seconds: int = 10) -> list[dict]:
        out = self.call("ReceiveMessage", {
            "QueueUrl": self.queue_url,
            "MaxNumberOfMessages": max_messages,
            "WaitTimeSeconds": wait_seconds,
            "VisibilityTimeout": 600,
        })
        return out.get("Messages", []) or []

    def delete(self, receipt_handle: str) -> None:
        self.call("DeleteMessage", {
            "QueueUrl": self.queue_url,
            "ReceiptHandle": receipt_handle,
        })


class SQSObjectFetcher:
    """S3 event notifications via SQS (object_fetcher_sqs.go)."""

    def __init__(self, params, prefix: str = ""):
        self.client = SQSClient(
            params.sqs_queue_url, region=params.sqs_region,
            access_key=params.sqs_access_key,
            secret_key=params.sqs_secret_key,
            endpoint=params.sqs_endpoint,
        )
        self.wait_seconds = params.sqs_wait_seconds
        self.pattern = params.path_pattern
        self.inflight: dict[str, str] = {}       # key -> receipt handle
        self._pending: dict[str, set] = {}       # receipt -> pending keys

    def fetch_objects(self) -> list[str]:
        msgs = self.client.receive(wait_seconds=self.wait_seconds)
        keys: list[str] = []
        for m in msgs:
            receipt = m.get("ReceiptHandle", "")
            body = m.get("Body", "")
            if "s3:TestEvent" in body:
                self.client.delete(receipt)
                continue
            try:
                doc = json.loads(body)
            except ValueError:
                self.client.delete(receipt)
                continue
            if not doc.get("Records") and doc.get("Message"):
                # SNS-enveloped notification: records are one level down
                try:
                    doc = json.loads(doc["Message"])
                except ValueError:
                    self.client.delete(receipt)
                    continue
            matched: set[str] = set()
            for rec in doc.get("Records", []):
                if "ObjectCreated" not in rec.get("eventName", ""):
                    continue
                key = urllib.parse.unquote_plus(
                    rec.get("s3", {}).get("object", {}).get("key", ""))
                if not key or (self.pattern and not fnmatch.fnmatch(
                        key, self.pattern)):
                    continue
                self.inflight[key] = receipt
                matched.add(key)
                keys.append(key)
            if not matched:
                # folder creation / non-matching events: drop the message
                self.client.delete(receipt)
            else:
                # one message may carry several records: delete it only
                # when EVERY key's object has been durably pushed
                self._pending[receipt] = matched
        return keys

    def commit(self, key: str) -> None:
        receipt = self.inflight.pop(key, None)
        if receipt is None:
            return
        pending = self._pending.get(receipt)
        if pending is not None:
            pending.discard(key)
            if pending:
                return  # other records on this message still replicating
            self._pending.pop(receipt, None)
        self.client.delete(receipt)

    def close(self) -> None:
        pass


class PollingObjectFetcher:
    """Bucket listing with an (mtime, name) watermark persisted in the
    coordinator state (object_fetcher_poller.go)."""

    STATE_KEY = "s3_poll_watermark"

    def __init__(self, fs, root: str, transfer_id: str,
                 coordinator: Optional[Coordinator],
                 pattern: str = ""):
        self.fs = fs
        # a glob URL (s3://bucket/prefix/*.jsonl) lists from the first
        # wildcard-free parent and filters with the glob itself
        if ("*" in root or "?" in root) and not pattern:
            pattern = root
        if "*" in root or "?" in root:
            head = re.split(r"[*?\[]", root, 1)[0]
            root = head.rsplit("/", 1)[0] if "/" in head else head
        self.root = root
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.pattern = pattern
        saved = {}
        if self.cp is not None:
            saved = self.cp.get_transfer_state(transfer_id).get(
                self.STATE_KEY, {})
        # watermark: highest committed mtime + every name committed AT
        # that mtime.  (mtime, name) lexicographic alone would skip an
        # object whose name sorts before an already-committed same-second
        # name (S3 LastModified has 1s granularity).
        self.wm_mtime: float = saved.get("mtime", -1.0)
        self.wm_names: set[str] = set(saved.get("names", []))
        self._pending: dict[str, float] = {}

    def _mtime(self, info: dict) -> float:
        m = info.get("mtime") or info.get("LastModified") or 0
        if hasattr(m, "timestamp"):
            return m.timestamp()
        return float(m or 0)

    def fetch_objects(self) -> list[str]:
        if hasattr(self.fs, "invalidate_cache"):
            # s3fs/gcsfs cache directory listings; without this, objects
            # uploaded after the first poll are never seen
            self.fs.invalidate_cache(self.root)
        found = []
        for path, info in sorted(self.fs.find(
                self.root, detail=True).items()):
            if info.get("type") == "directory":
                continue
            if self.pattern and not fnmatch.fnmatch(path, self.pattern):
                continue
            mtime = self._mtime(info)
            if mtime < self.wm_mtime:
                continue
            if mtime == self.wm_mtime and path in self.wm_names:
                continue
            if path in self._pending:
                continue
            found.append((mtime, path))
        found.sort()
        out = []
        for mtime, path in found:
            self._pending[path] = mtime
            out.append(path)
        return out

    def commit(self, key: str) -> None:
        mtime = self._pending.pop(key, None)
        if mtime is None:
            return
        if mtime > self.wm_mtime:
            self.wm_mtime = mtime
            self.wm_names = {key}
        elif mtime == self.wm_mtime:
            self.wm_names.add(key)
        else:
            return  # older than the watermark; nothing to persist
        if self.cp is not None:
            self.cp.set_transfer_state(self.transfer_id, {
                self.STATE_KEY: {"mtime": self.wm_mtime,
                                 "names": sorted(self.wm_names)},
            })

    def close(self) -> None:
        pass


class S3ReplicationSource(Source):
    """Replicates newly created objects through the format reader."""

    def __init__(self, params, transfer_id: str,
                 coordinator: Optional[Coordinator] = None):
        from transferia_tpu.providers.s3 import _fs_for

        self.params = params
        self.table = TableID(params.namespace, params.table)
        self.fs, self.root = _fs_for(params.url, params)
        self.reader = params.make_reader()
        self._schema: Optional[TableSchema] = None
        self._stop = threading.Event()
        if params.event_source == "sqs":
            if not params.sqs_queue_url:
                raise S3SourceError("event_source=sqs needs sqs_queue_url")
            self.fetcher = SQSObjectFetcher(params)
        elif params.event_source == "poll":
            self.fetcher = PollingObjectFetcher(
                self.fs, self.root, transfer_id, coordinator,
                params.path_pattern)
        else:
            raise S3SourceError(
                f"unknown event_source {params.event_source!r} (sqs|poll)")

    def _full_path(self, key: str) -> str:
        # key shape is determined by the fetcher: SQS events carry
        # bucket-relative keys, the poller lists full paths — never probe
        # fs.exists (a relative key could resolve against the WRONG
        # bucket that happens to exist)
        if isinstance(self.fetcher, SQSObjectFetcher):
            bucket = self.root.split("/", 1)[0]
            return f"{bucket}/{key}"
        return key

    def run(self, sink: AsyncSink) -> None:
        while not self._stop.is_set():
            keys = self.fetcher.fetch_objects()
            if not keys:
                if isinstance(self.fetcher, PollingObjectFetcher):
                    self._stop.wait(self.params.poll_interval)
                continue
            for key in keys:
                if self._stop.is_set():
                    break
                self._replicate_object(key, sink)
        self.fetcher.close()

    def _replicate_object(self, key: str, sink: AsyncSink) -> None:
        path = self._full_path(key)
        try:
            if self._schema is None:
                self._schema = self.reader.infer_schema(self.fs, path)
        except FileNotFoundError:
            # deleted between notification and processing (lifecycle
            # rules): skip+commit — a poison message must not crash the
            # worker on every redelivery (the reference fetcher skips
            # missing objects too)
            logger.warning("s3 object %s vanished before replication; "
                           "skipping", path)
            self.fetcher.commit(key)
            return
        futures = []

        def pusher(batch):
            futures.append(sink.async_push(batch))

        t0 = time.monotonic()
        try:
            self.reader.read(self.fs, path, self.table, self._schema,
                             self.params.batch_rows, pusher)
        except FileNotFoundError:
            logger.warning("s3 object %s vanished mid-read; skipping",
                           path)
            for f in futures:
                f.result()  # partial rows already pushed stay pushed
            self.fetcher.commit(key)
            return
        for f in futures:
            f.result()  # at-least-once: commit only after durable push
        self.fetcher.commit(key)
        logger.info("s3 replicated %s in %.2fs (%d pushes)",
                    path, time.monotonic() - t0, len(futures))

    def stop(self) -> None:
        self._stop.set()
