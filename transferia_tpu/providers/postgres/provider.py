"""Postgres storage/sink over the wire client.

Reference parity: providers/postgres/storage.go (snapshot via reads),
splitter/ (ctid-range intra-table sharding), typesystem.go (pg type rules),
provider.go capability surface.  Snapshot loads use COPY TO STDOUT (csv)
into pyarrow's block CSV reader — vectorized straight into ColumnBatch.
"""

from __future__ import annotations

import io
import logging
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.errors import StaleEpochPublishError
from transferia_tpu.abstract.interfaces import (
    Batch,
    IncrementalStorage,
    PositionalStorage,
    Pusher,
    SampleableStorage,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import (
    CleanupPolicy,
    EndpointParams,
    register_endpoint,
)
from transferia_tpu.providers.postgres.wire import PGConnection, PGError
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)
from transferia_tpu.typesystem.rules import (
    register_source_rules,
    register_target_rules,
)

logger = logging.getLogger(__name__)

register_source_rules("pg", {
    "smallint": CanonicalType.INT16, "int2": CanonicalType.INT16,
    "integer": CanonicalType.INT32, "int4": CanonicalType.INT32,
    "bigint": CanonicalType.INT64, "int8": CanonicalType.INT64,
    "real": CanonicalType.FLOAT, "float4": CanonicalType.FLOAT,
    "double precision": CanonicalType.DOUBLE, "float8": CanonicalType.DOUBLE,
    "boolean": CanonicalType.BOOLEAN, "bool": CanonicalType.BOOLEAN,
    "text": CanonicalType.UTF8, "varchar": CanonicalType.UTF8,
    "character varying": CanonicalType.UTF8,
    "character": CanonicalType.UTF8, "bpchar": CanonicalType.UTF8,
    "bytea": CanonicalType.STRING,
    "date": CanonicalType.DATE,
    "timestamp without time zone": CanonicalType.TIMESTAMP,
    "timestamp with time zone": CanonicalType.TIMESTAMP,
    "timestamp": CanonicalType.TIMESTAMP,
    "timestamptz": CanonicalType.TIMESTAMP,
    "interval": CanonicalType.INTERVAL,
    "numeric": CanonicalType.DECIMAL, "decimal": CanonicalType.DECIMAL,
    "json": CanonicalType.ANY, "jsonb": CanonicalType.ANY,
    "uuid": CanonicalType.UTF8,
    "*": CanonicalType.ANY,
})

register_target_rules("pg", {
    CanonicalType.INT8: "smallint", CanonicalType.INT16: "smallint",
    CanonicalType.INT32: "integer", CanonicalType.INT64: "bigint",
    CanonicalType.UINT8: "smallint", CanonicalType.UINT16: "integer",
    CanonicalType.UINT32: "bigint", CanonicalType.UINT64: "numeric",
    CanonicalType.FLOAT: "real", CanonicalType.DOUBLE: "double precision",
    CanonicalType.BOOLEAN: "boolean", CanonicalType.STRING: "bytea",
    CanonicalType.UTF8: "text", CanonicalType.DATE: "date",
    CanonicalType.DATETIME: "timestamp",
    CanonicalType.TIMESTAMP: "timestamp",
    CanonicalType.INTERVAL: "interval", CanonicalType.DECIMAL: "numeric",
    CanonicalType.ANY: "jsonb",
})


@register_endpoint
@dataclass
class PGSourceParams(EndpointParams):
    PROVIDER = "pg"
    IS_SOURCE = True

    host: str = "localhost"
    port: int = 5432
    database: str = "postgres"
    user: str = "postgres"
    password: str = ""
    # failover host list (pkg/pgha): tried in order before `host`; the
    # first host that accepts a connection wins
    hosts: list[str] = field(default_factory=list)
    schemas: list[str] = field(default_factory=lambda: ["public"])
    transfer_ddl: bool = False    # move indexes/views/sequences to a PG
    #                               target post-load (pg_dump.go parity)
    batch_rows: int = 131_072
    desired_part_size_bytes: int = 256 << 20  # ctid split target
    slot_name: str = ""                        # replication slot (CDC)
    # DBLog incremental snapshot (provider.go:443 DBLogUpload): chunked
    # watermark-fenced snapshot interleaved with live replication.
    # Tables need a single-column primary key; empty list = all tables.
    dblog_snapshot: bool = False
    dblog_chunk_rows: int = 10_000
    dblog_tables: list[str] = field(default_factory=list)


@register_endpoint
@dataclass
class PGTargetParams(EndpointParams):
    PROVIDER = "pg"
    IS_TARGET = True

    host: str = "localhost"
    port: int = 5432
    database: str = "postgres"
    user: str = "postgres"
    password: str = ""


def _conn(params) -> PGConnection:
    """Connect with pgha-style failover across the configured host list."""
    candidates = []
    for h in getattr(params, "hosts", None) or []:
        if h.startswith("["):  # [v6]:port or [v6]
            v6, _, rest = h[1:].partition("]")
            port = rest.lstrip(":")
            candidates.append((v6, int(port) if port.isdigit()
                               else params.port))
        elif h.count(":") == 1 and h.rpartition(":")[2].isdigit():
            host, _, port = h.rpartition(":")
            candidates.append((host, int(port)))
        else:
            # bare hostname, unbracketed IPv6 literal, or junk port:
            # default port — a malformed entry must never abort failover
            candidates.append((h, params.port))
    candidates.append((params.host, params.port))
    last: Optional[Exception] = None
    for host, port in candidates:
        try:
            return PGConnection(
                host=host, port=port, database=params.database,
                user=params.user, password=params.password,
            ).connect()
        except (OSError, PGError) as e:
            last = e
            logger.warning("pg host %s:%s unavailable: %s", host, port, e)
    raise PGError(f"no postgres host reachable: {last}")


def _pg_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, bytes):
        return f"'\\x{v.hex()}'::bytea"
    s = str(v).replace("'", "''")
    return f"'{s}'"


class PGStorage(Storage, ShardingStorage, PositionalStorage,
                IncrementalStorage, SampleableStorage):
    def __init__(self, params: PGSourceParams):
        self.params = params
        self._c: Optional[PGConnection] = None

    @property
    def conn(self) -> PGConnection:
        if self._c is None:
            self._c = _conn(self.params)
        return self._c

    def close(self) -> None:
        if self._c is not None:
            self._c.close()
            self._c = None

    def ping(self) -> None:
        self.conn.scalar("SELECT 1")

    # -- catalog ------------------------------------------------------------
    def table_list(self, include=None):
        from transferia_tpu.providers.staging import is_meta_name

        schemas = ", ".join(f"'{s}'" for s in self.params.schemas)
        rows = self.conn.query(
            "SELECT n.nspname AS ns, c.relname AS name, "
            "c.reltuples::bigint AS eta "
            "FROM pg_class c JOIN pg_namespace n ON n.oid = c.relnamespace "
            f"WHERE c.relkind IN ('r', 'p') AND n.nspname IN ({schemas})"
        )
        out = {}
        for r in rows:
            if is_meta_name(r["name"]):
                continue  # staging/fence tables are not user data
            tid = TableID(r["ns"], r["name"])
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(eta_rows=max(0, int(r["eta"] or 0)))
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        from transferia_tpu.typesystem.rules import map_source_type

        rows = self.conn.query(
            "SELECT a.attname AS name, "
            "format_type(a.atttypid, a.atttypmod) AS typ, "
            "a.attnotnull AS notnull, "
            "COALESCE(( SELECT TRUE FROM pg_index i "
            "  WHERE i.indrelid = a.attrelid AND i.indisprimary "
            "  AND a.attnum = ANY(i.indkey)), FALSE) AS is_pk "
            f"FROM pg_attribute a WHERE a.attrelid = "
            f"'{table.fqtn()}'::regclass "
            "AND a.attnum > 0 AND NOT a.attisdropped ORDER BY a.attnum"
        )
        from transferia_tpu.providers.staging import is_meta_name

        cols = []
        for r in rows:
            if is_meta_name(r["name"]):
                continue  # hidden staged-commit part column
            cols.append(ColSchema(
                name=r["name"],
                data_type=map_source_type("pg", r["typ"].lower()),
                primary_key=r["is_pk"] in ("t", True, "true"),
                required=r["notnull"] in ("t", True, "true"),
                original_type=f"pg:{r['typ']}",
            ))
        return TableSchema(cols)

    def exact_table_rows_count(self, table: TableID) -> int:
        return int(self.conn.scalar(
            f"SELECT count(*) FROM {table.fqtn()}"
        ) or 0)

    def estimate_table_rows_count(self, table: TableID) -> int:
        info = self.table_list([table]).get(table)
        return info.eta_rows if info else 0

    def position(self) -> dict:
        try:
            lsn = self.conn.scalar("SELECT pg_current_wal_lsn()")
            return {"wal_lsn": lsn}
        except PGError:
            return {}

    # -- IncrementalStorage (storage_incremental.go) ------------------------
    @staticmethod
    def _cursor_literal(v) -> str:
        if isinstance(v, (int, float)):
            return str(v)
        s = str(v).replace("'", "''")
        return f"'{s}'"

    def get_increment_state(self, tables, state):
        out = []
        for t in tables:
            cursor = state.get(str(t.table), t.initial_state or None)
            if cursor in (None, ""):
                out.append(TableDescription(id=t.table))
            else:
                out.append(TableDescription(
                    id=t.table,
                    filter=f'"{t.cursor_field}" > '
                           f"{self._cursor_literal(cursor)}",
                ))
        return out

    def next_increment_state(self, tables):
        out = {}
        for t in tables:
            v = self.conn.scalar(
                f'SELECT max("{t.cursor_field}") FROM {t.table.fqtn()}'
            )
            if v is not None:
                out[str(t.table)] = v
        return out

    # -- intra-table sharding (postgres/splitter: ctid block ranges) --------
    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        try:
            size = int(self.conn.scalar(
                f"SELECT pg_relation_size('{table.id.fqtn()}')"
            ) or 0)
            blocks = int(self.conn.scalar(
                f"SELECT relpages FROM pg_class "
                f"WHERE oid = '{table.id.fqtn()}'::regclass"
            ) or 0)
        except PGError:
            return [table]
        target = self.params.desired_part_size_bytes
        if size <= target or blocks <= 1 or table.filter:
            return [table]
        n_parts = min((size + target - 1) // target, 64)
        per = (blocks + n_parts - 1) // n_parts
        eta_per = 0
        out = []
        for i in range(int(n_parts)):
            lo, hi = i * per, min(blocks + 1, (i + 1) * per)
            out.append(TableDescription(
                id=table.id,
                filter=(
                    f"ctid >= '({lo},0)'::tid AND ctid < '({hi},0)'::tid"
                ),
                eta_rows=table.eta_rows // int(n_parts),
            ))
        return out

    # -- snapshot load ------------------------------------------------------
    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        schema = self.table_schema(table.id)
        cols = ", ".join(f'"{c.name}"' for c in schema)
        where = f" WHERE {table.filter}" if table.filter else ""
        self._copy_select(
            f"SELECT {cols} FROM {table.id.fqtn()}{where}",
            table.id, schema, pusher,
        )

    def _copy_select(self, select_sql: str, tid: TableID,
                     schema: TableSchema, pusher: Pusher) -> None:
        sql = (
            f"COPY ({select_sql}) "
            f"TO STDOUT WITH (FORMAT csv, HEADER false)"
        )
        # dedicated connection: parts stream in parallel threads
        conn = _conn(self.params)
        try:
            buf = io.BytesIO()
            nbytes = 0
            for chunk in conn.copy_out(sql):
                buf.write(chunk)
                nbytes += len(chunk)
                if nbytes >= 32 << 20:
                    self._flush_csv(buf, tid, schema, pusher)
                    buf = io.BytesIO()
                    nbytes = 0
            if buf.tell():
                self._flush_csv(buf, tid, schema, pusher)
        finally:
            conn.close()

    # -- checksum sampling (storage.go:984 LoadTopBottomSample etc.) --------
    RANDOM_SAMPLE_LIMIT = 2000   # reference: "random()<=0.05 … limit 2000"
    TOP_BOTTOM_LIMIT = 1000

    def table_size_in_bytes(self, table: TableID) -> int:
        try:
            return int(self.conn.scalar(
                f"SELECT pg_relation_size('{table.fqtn()}')"
            ) or 0)
        except PGError:
            return 0

    def _sample_parts(self, tid: TableID):
        schema = self.table_schema(tid)
        cols = ", ".join(f'"{c.name}"' for c in schema)
        order = ", ".join(f'"{c.name}"' for c in schema.key_columns())
        return schema, cols, order

    def load_random_sample(self, table: TableDescription,
                           pusher: Pusher) -> None:
        schema, cols, order = self._sample_parts(table.id)
        by = f" ORDER BY {order}" if order else ""
        self._copy_select(
            f"SELECT {cols} FROM {table.id.fqtn()} "
            f"WHERE random() <= 0.05{by} LIMIT {self.RANDOM_SAMPLE_LIMIT}",
            table.id, schema, pusher,
        )

    def load_top_bottom_sample(self, table: TableDescription,
                               pusher: Pusher) -> None:
        schema, cols, order = self._sample_parts(table.id)
        if not order:
            raise PGError(f"no primary key on {table.id.fqtn()}; "
                          "cannot take top/bottom sample")
        desc = ", ".join(f"{c} DESC" for c in order.split(", "))
        n = self.TOP_BOTTOM_LIMIT
        self._copy_select(
            f"(SELECT {cols} FROM {table.id.fqtn()} "
            f"ORDER BY {order} LIMIT {n}) UNION ALL "
            f"(SELECT {cols} FROM {table.id.fqtn()} "
            f"ORDER BY {desc} LIMIT {n})",
            table.id, schema, pusher,
        )

    def load_sample_by_set(self, table: TableDescription, key_set,
                           pusher: Pusher) -> None:
        schema, cols, order = self._sample_parts(table.id)
        conds = []
        for key in key_set:
            conds.append("(" + " AND ".join(
                f'"{name}" = {_pg_literal(val)}'
                for name, val in key.items()) + ")")
        where = " OR ".join(conds) if conds else "FALSE"
        self._copy_select(
            f"SELECT {cols} FROM {table.id.fqtn()} WHERE {where}",
            table.id, schema, pusher,
        )

    def _flush_csv(self, buf: io.BytesIO, tid: TableID,
                   schema: TableSchema, pusher: Pusher) -> None:
        """CSV chunk -> arrow (vectorized) -> ColumnBatch.

        Chunks split on CopyData boundaries which always align to row ends
        (each CopyData message is one row for csv format).
        """
        import pyarrow as pa
        import pyarrow.csv as pacsv

        from transferia_tpu.columnar.batch import arrow_to_table_schema

        buf.seek(0)
        convert = pacsv.ConvertOptions(
            column_types={
                c.name: _arrow_read_type(c.data_type) for c in schema
            },
            null_values=[""],
            strings_can_be_null=True,
        )
        read = pacsv.ReadOptions(column_names=schema.names())
        tbl = pacsv.read_csv(buf, read_options=read,
                             convert_options=convert)
        for rb in tbl.to_batches(max_chunksize=self.params.batch_rows):
            batch = ColumnBatch.from_arrow(rb, tid, schema)
            batch.read_bytes = rb.nbytes
            pusher(batch)


def _arrow_read_type(ctype: CanonicalType):
    import pyarrow as pa

    table = {
        CanonicalType.INT8: pa.int8(), CanonicalType.INT16: pa.int16(),
        CanonicalType.INT32: pa.int32(), CanonicalType.INT64: pa.int64(),
        CanonicalType.UINT8: pa.uint8(), CanonicalType.UINT16: pa.uint16(),
        CanonicalType.UINT32: pa.uint32(), CanonicalType.UINT64: pa.uint64(),
        CanonicalType.FLOAT: pa.float32(), CanonicalType.DOUBLE: pa.float64(),
        CanonicalType.BOOLEAN: pa.bool_(),
        CanonicalType.DATE: pa.date32(),
        CanonicalType.TIMESTAMP: pa.timestamp("us"),
        CanonicalType.DATETIME: pa.timestamp("s"),
    }
    return table.get(ctype, pa.string())


class PGSinker(Sinker, StagedSinker):
    """COPY-based insert sink with DDL creation; updates/deletes via
    simple-query statements (CDC slow path).

    Staged-commit capable (abstract/commit.py): with an open part stage
    batches COPY into a per-(part, epoch) staging table
    (`public.__trtpu_stg_<hash>`), and publish is postgres's own atomic
    primitive — ONE transaction doing DELETE-part-rows + `INSERT ...
    SELECT` from staging + an append-only epoch row into the
    `__trtpu_commits` fence table (PK (part_key, epoch): the fence
    value is max(epoch), monotone by construction — a zombie's row can
    never regress it), so the target flips from "nothing of this part"
    to "exactly this part" with no torn middle state.  The final table
    carries a hidden `__trtpu_part` column (filtered out of every
    PGStorage read) so a republish can address its own rows.

    Fence bound: the epoch check reads before the publish transaction,
    so two publishers racing the SAME instant can interleave their
    data flips (last txn wins) — the coordinator's fenced commit_part
    is the primary gate that keeps two live owners from publishing one
    part concurrently; this sink fence is the zombie-PROCESS backstop
    (a stale publisher arriving after the survivor always raises)."""

    def __init__(self, params: PGTargetParams):
        self.params = params
        self._c: Optional[PGConnection] = None
        self._created: set[TableID] = set()
        self._stage = None  # staging.WireStage when open
        self._fence_ready = False

    @property
    def conn(self) -> PGConnection:
        if self._c is None:
            self._c = _conn(self.params)
        return self._c

    def close(self) -> None:
        if self._c is not None:
            self._c.close()
            self._c = None

    def _ensure_table(self, tid: TableID, schema: TableSchema,
                      with_part_column: bool = False) -> None:
        if tid in self._created:
            return
        from transferia_tpu.providers.staging import META_COLUMN
        from transferia_tpu.typesystem.rules import map_target_type

        cols = []
        for c in schema:
            pg_type = map_target_type("pg", c.data_type)
            nn = " NOT NULL" if (c.required or c.primary_key) else ""
            cols.append(f'"{c.name}" {pg_type}{nn}')
        if with_part_column:
            cols.append(f'"{META_COLUMN}" text')
        keys = ", ".join(f'"{c.name}"' for c in schema.key_columns())
        pk = f", PRIMARY KEY ({keys})" if keys else ""
        if tid.namespace:
            self.conn.query(
                f'CREATE SCHEMA IF NOT EXISTS "{tid.namespace}"'
            )
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {tid.fqtn()} "
            f"({', '.join(cols)}{pk})"
        )
        self._created.add(tid)

    @staticmethod
    def _csv_cell(v) -> str:
        if v is None:
            return ""
        if isinstance(v, bytes):
            return "\\x" + v.hex()
        if isinstance(v, bool):
            return "t" if v else "f"
        s = str(v)
        if any(ch in s for ch in ',"\n\r'):
            s = '"' + s.replace('"', '""') + '"'
        return s

    def push(self, batch: Batch) -> None:
        if not is_columnar(batch):
            rows = [it for it in batch if it.is_row_event()]
            if not rows:
                return
            batch = ColumnBatch.from_rows(rows)
        if self._stage is not None:
            self._stage_push(batch)
            return
        self._ensure_table(batch.table_id, batch.schema)
        if batch.kinds is None:
            self._copy_insert(batch)
        else:
            for it in batch.to_rows():
                self._apply_row(it)

    def _copy_insert(self, batch: ColumnBatch,
                     target: Optional[str] = None) -> None:
        cols = ", ".join(f'"{n}"' for n in batch.columns)
        data = batch.to_pydict()
        names = list(batch.columns)
        lines = []
        for i in range(batch.n_rows):
            lines.append(",".join(
                self._csv_cell(data[n][i]) for n in names
            ))
        payload = ("\n".join(lines) + "\n").encode()
        self.conn.copy_in(
            f"COPY {target or batch.table_id.fqtn()} ({cols}) "
            f"FROM STDIN WITH (FORMAT csv)",
            [payload],
        )

    # -- StagedSinker (exactly-once publish via one SQL transaction) --------
    @staticmethod
    def _stage_fqtn(stage) -> str:
        return f'"public"."{stage.table}"'

    def _commits_fqtn(self) -> str:
        from transferia_tpu.providers.staging import COMMITS_TABLE

        return f'"public"."{COMMITS_TABLE}"'

    def _ensure_fence_table(self) -> None:
        if self._fence_ready:
            return
        # APPEND-ONLY fence: one row per accepted (part, epoch), fence
        # value = max(epoch) per part.  A zombie's publish can add its
        # own (older) row but can never REGRESS the fence the way a
        # keyed-by-part upsert could — monotone by construction
        self.conn.query(
            f"CREATE TABLE IF NOT EXISTS {self._commits_fqtn()} "
            f"(\"part_key\" text, \"epoch\" bigint, "
            f"PRIMARY KEY (\"part_key\", \"epoch\"))"
        )
        self._fence_ready = True

    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import (
            WireStage,
            stage_ident_prefix,
        )

        stage = WireStage(key, epoch)
        # begin replaces — for EVERY epoch of this key: a crashed
        # earlier owner's staging table (different epoch, so a
        # different name) would otherwise leak in the target forever
        pfx = stage_ident_prefix(key)
        rows = self.conn.query(
            "SELECT n.nspname AS ns, c.relname AS name, "
            "c.reltuples::bigint AS eta "
            "FROM pg_class c JOIN pg_namespace n ON n.oid = "
            "c.relnamespace "
            "WHERE c.relkind IN ('r', 'p') AND n.nspname IN ('public')")
        for r in rows:
            if r["name"].startswith(pfx):
                self.conn.query(
                    f"DROP TABLE IF EXISTS \"public\".\"{r['name']}\"")
        self._ensure_fence_table()
        self._stage = stage

    def _stage_push(self, batch: ColumnBatch) -> None:
        from transferia_tpu.typesystem.rules import map_target_type

        stage = self._stage
        staged = stage.state.stage(batch)
        if stage.schema is None:
            stage.tid = batch.table_id
            stage.schema = batch.schema
            cols = ", ".join(
                f'"{c.name}" {map_target_type("pg", c.data_type)}'
                for c in batch.schema)
            self.conn.query(
                f"CREATE TABLE IF NOT EXISTS "
                f"{self._stage_fqtn(stage)} ({cols})")
        if staged.n_rows == 0:
            return
        try:
            self._copy_insert(staged, target=self._stage_fqtn(stage))
        except BaseException:
            # the staging write died after the dedup window recorded
            # this batch: only a full part restage is safe
            stage.state.mark_failed()
            raise

    def _fence_epoch(self, slug: str):
        rows = self.conn.query(
            f"SELECT \"epoch\" FROM {self._commits_fqtn()} "
            f"WHERE (\"part_key\" = '{slug}')")
        epochs = [int(r["epoch"]) for r in rows
                  if r.get("epoch") is not None]
        return max(epochs) if epochs else None

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.providers.staging import (
            META_COLUMN,
            publish_guard,
        )
        from transferia_tpu.stats import trace

        stage = self._stage
        if stage is None or stage.key != key:
            raise RuntimeError(f"pg sink: no open stage for {key!r}")
        with publish_guard(key, epoch):
            prev = self._fence_epoch(stage.slug)
            if prev is not None and epoch < prev:
                raise StaleEpochPublishError(key, epoch, prev)
            trace.instant("pg_publish_txn", part=key, epoch=epoch,
                          rows=stage.state.rows)
            failpoint("sink.pg.publish")
            stmts = ["BEGIN"]
            if stage.schema is not None:
                self._ensure_table(stage.tid, stage.schema,
                                   with_part_column=True)
                # a final table created by the at-least-once path (or
                # a pre-staged-commit run) lacks the part column; the
                # retrofit is idempotent and outside the publish txn
                self.conn.query(
                    f"ALTER TABLE {stage.tid.fqtn()} ADD COLUMN IF "
                    f"NOT EXISTS \"{META_COLUMN}\" text")
                cols = ", ".join(f'"{c.name}"' for c in stage.schema)
                stmts.append(
                    f"DELETE FROM {stage.tid.fqtn()} "
                    f"WHERE \"{META_COLUMN}\" = '{stage.slug}'")
                stmts.append(
                    f"INSERT INTO {stage.tid.fqtn()} "
                    f"({cols}, \"{META_COLUMN}\") "
                    f"SELECT {cols}, '{stage.slug}' "
                    f"FROM {self._stage_fqtn(stage)}")
            stmts.append(
                f"INSERT INTO {self._commits_fqtn()} "
                f"(\"part_key\", \"epoch\") "
                f"VALUES ('{stage.slug}', {epoch}) "
                f"ON CONFLICT (\"part_key\", \"epoch\") DO NOTHING")
            stmts.append("COMMIT")
            # one Q message = one implicit transaction block: postgres
            # applies all statements atomically or rolls back together
            self.conn.query("; ".join(stmts))
            self.conn.query(
                f"DROP TABLE IF EXISTS {self._stage_fqtn(stage)}")
            self.last_dedup_dropped = stage.state.dedup_dropped
            rows = stage.state.rows
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        stage = self._stage
        if stage is None or stage.key != key:
            return
        self._stage = None
        try:
            self.conn.query(
                f"DROP TABLE IF EXISTS {self._stage_fqtn(stage)}")
        except PGError as e:
            logger.warning("pg staged abort of %s: %s", key, e)

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.state.note_push_retry()

    _sql_literal = staticmethod(lambda v: _pg_literal(v))

    def _apply_row(self, it) -> None:
        tid = it.table_id
        if it.kind == Kind.INSERT:
            cols = ", ".join(f'"{n}"' for n in it.column_names)
            vals = ", ".join(self._sql_literal(v) for v in it.column_values)
            keys = [c.name for c in it.table_schema.key_columns()] \
                if it.table_schema else []
            conflict = ""
            if keys:
                sets = ", ".join(
                    f'"{n}" = EXCLUDED."{n}"' for n in it.column_names
                    if n not in keys
                )
                kcols = ", ".join(f'"{k}"' for k in keys)
                conflict = f" ON CONFLICT ({kcols}) DO UPDATE SET {sets}" \
                    if sets else f" ON CONFLICT ({kcols}) DO NOTHING"
            self.conn.query(
                f"INSERT INTO {tid.fqtn()} ({cols}) VALUES ({vals})"
                f"{conflict}"
            )
        elif it.kind == Kind.UPDATE:
            sets = ", ".join(
                f'"{n}" = {self._sql_literal(v)}'
                for n, v in zip(it.column_names, it.column_values)
            )
            where = self._key_where(it)
            self.conn.query(f"UPDATE {tid.fqtn()} SET {sets} WHERE {where}")
        elif it.kind == Kind.DELETE:
            self.conn.query(
                f"DELETE FROM {tid.fqtn()} WHERE {self._key_where(it)}"
            )

    def _key_where(self, it) -> str:
        key = it.effective_key()
        names = [c.name for c in it.table_schema.key_columns()]
        return " AND ".join(
            f'"{n}" = {self._sql_literal(v)}' for n, v in zip(names, key)
        )


@register_provider
class PostgresProvider(Provider):
    NAME = "pg"

    def storage(self):
        if isinstance(self.transfer.src, PGSourceParams):
            return PGStorage(self.transfer.src)
        return None

    def destination_storage(self):
        dst = self.transfer.dst
        if isinstance(dst, PGTargetParams):
            return PGStorage(PGSourceParams(
                host=dst.host, port=dst.port, database=dst.database,
                user=dst.user, password=dst.password,
            ))
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, PGTargetParams):
            return PGSinker(self.transfer.dst)
        return None

    def source(self):
        """Logical-replication CDC (publisher.go)."""
        if isinstance(self.transfer.src, PGSourceParams):
            from transferia_tpu.providers.postgres.replication import (
                PGReplicationSource,
            )

            return PGReplicationSource(
                self.transfer.src, self.transfer.id,
                coordinator=self.coordinator,
            )
        return None

    def transfer_ddl_objects(self, dst_params) -> int:
        """Post-upload hook (activation task): apply the source's
        indexes/views/sequences on a PG target (pg_dump.go)."""
        src = self.transfer.src
        if not isinstance(src, PGSourceParams) or not src.transfer_ddl:
            return 0
        if not isinstance(dst_params, PGTargetParams):
            logger.warning(
                "transfer_ddl is PG->PG only; destination is %s",
                getattr(dst_params, "PROVIDER", "?"))
            return 0
        from transferia_tpu.providers.postgres.pg_dump import (
            apply_ddl_objects,
            dump_ddl_objects,
        )

        src_conn = _conn(src)
        try:
            statements = dump_ddl_objects(src_conn, src.schemas)
        finally:
            src_conn.close()
        if not statements:
            return 0
        dst_conn = _conn(dst_params)
        try:
            applied = apply_ddl_objects(dst_conn, statements)
        finally:
            dst_conn.close()
        logger.info("transferred %d/%d ddl objects to the target",
                    applied, len(statements))
        return applied

    def deactivate(self) -> None:
        """Drop the replication slot (postgres Deactivator)."""
        from transferia_tpu.providers.postgres.replication import (
            PGReplicationSource,
            ReplicationConnection,
        )

        src = self.transfer.src
        if not isinstance(src, PGSourceParams):
            return
        slot = src.slot_name or \
            f"transferia_{self.transfer.id}".replace("-", "_")
        conn = ReplicationConnection(
            host=src.host, port=src.port, database=src.database,
            user=src.user, password=src.password, replication=True,
        ).connect()
        try:
            conn.drop_slot(slot)
        except PGError as e:
            logger.warning("drop slot %s: %s", slot, e)
        finally:
            conn.close()

    def cleanup(self, tables: list) -> None:
        params = self.transfer.dst
        conn = _conn(params)
        try:
            stmt = "DROP TABLE IF EXISTS" \
                if params.cleanup_policy == CleanupPolicy.DROP \
                else "TRUNCATE TABLE"
            for td in tables or []:
                tid = td.id if hasattr(td, "id") else td
                try:
                    conn.query(f"{stmt} {tid.fqtn()}")
                except PGError as e:
                    if params.cleanup_policy == CleanupPolicy.TRUNCATE \
                            and e.sqlstate == "42P01":
                        continue  # truncate of missing table is fine
                    raise
        finally:
            conn.close()

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        params = self.transfer.src if isinstance(
            self.transfer.src, PGSourceParams
        ) else self.transfer.dst
        try:
            conn = _conn(params)
            conn.scalar("SELECT 1")
            conn.close()
            result.add("connect")
        except Exception as e:
            result.add("connect", e)
        return result
