"""PostgreSQL logical replication source (CDC).

Reference parity: providers/postgres/ — publisher.go (slot-based
replication), wal2json_parser.go (decode), create_replication_slot.go /
lsn_slot.go (slot lifecycle), pkg/abstract/slot_monitor.go:9-26 (runaway
slot protection).

Protocol: a `replication=database` connection runs CREATE_REPLICATION_SLOT
/ START_REPLICATION; the server switches to CopyBoth and streams XLogData
('w') and keepalive ('k') CopyData messages; the client answers with
standby status updates ('r') advancing the flushed LSN only after the sink
confirms delivery — the at-least-once checkpoint contract
(transfer_state.go wal position).

Decode: wal2json format-version 2 (one JSON object per message:
action I/U/D/B/C/T with columns/identity arrays).
"""

from __future__ import annotations

import json
import logging
import struct
import threading
import time
from typing import Iterator, Optional

from transferia_tpu.abstract.change_item import ChangeItem, OldKeys
from transferia_tpu.abstract.errors import FatalError
from transferia_tpu.abstract.interfaces import AsyncSink, Source
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.providers.postgres.wire import PGConnection, PGError
from transferia_tpu.typesystem.rules import map_source_type

logger = logging.getLogger(__name__)


def lsn_to_int(lsn: str) -> int:
    hi, lo = lsn.split("/")
    return (int(hi, 16) << 32) | int(lo, 16)


def int_to_lsn(v: int) -> str:
    return f"{v >> 32:X}/{v & 0xFFFFFFFF:X}"


class ReplicationConnection(PGConnection):
    """PGConnection extension for the streaming-replication sub-protocol."""

    def identify_system(self) -> dict:
        rows = self.query("IDENTIFY_SYSTEM")
        return rows[0] if rows else {}

    def create_slot(self, slot: str, plugin: str = "wal2json") -> dict:
        rows = self.query(
            f"CREATE_REPLICATION_SLOT {slot} LOGICAL {plugin}"
        )
        return rows[0] if rows else {}

    def drop_slot(self, slot: str) -> None:
        self.query(f"DROP_REPLICATION_SLOT {slot}")

    def start_replication(self, slot: str, lsn: str,
                          options: Optional[dict] = None) -> None:
        opts = options or {"format-version": "2",
                           "include-transaction": "true"}
        opt_s = ", ".join(f'"{k}" \'{v}\'' for k, v in opts.items())
        sql = f"START_REPLICATION SLOT {slot} LOGICAL {lsn} ({opt_s})"
        self._send(b"Q", sql.encode() + b"\x00")
        t, payload = self._recv_message()
        if t != b"W":
            raise PGError(
                f"expected CopyBothResponse for START_REPLICATION, got {t!r}"
            )

    def stream(self, timeout: float = 1.0
               ) -> Iterator[tuple[str, int, bytes]]:
        """Yield ('xlog', wal_end, payload) / ('keepalive', wal_end,
        reply_requested) until no data is readable for `timeout` (caller
        loops).

        Framing safety: readability is probed with select BEFORE touching
        the socket; once a header byte exists the full message is read
        under the connection's long timeout — a short-timeout abort
        mid-frame would desync the protocol permanently.  Connection errors
        propagate (the replication retry loop restarts the worker); they
        are never swallowed.
        """
        import select

        while True:
            # BufferedSock may have whole messages already buffered in
            # userspace (a 256KiB refill can pull several replication
            # frames at once); select on the raw fd would block past them
            # and stall CDC delivery / keepalive replies until fresh wire
            # bytes arrive.  Drain the buffer before probing the kernel.
            if self.sock.pending() == 0:
                readable, _, _ = select.select([self.sock], [], [], timeout)
                if not readable:
                    return
            t, payload = self._recv_message()
            if t != b"d":
                if t == b"Z":
                    return
                continue
            kind = payload[:1]
            if kind == b"w":
                start, end, ts = struct.unpack("!QQQ", payload[1:25])
                yield ("xlog", end, payload[25:])
            elif kind == b"k":
                end, ts, reply = struct.unpack("!QQB", payload[1:18])
                yield ("keepalive", end, bytes([reply]))

    def send_standby_status(self, flushed_lsn: int,
                            reply_requested: bool = False) -> None:
        # PG epoch (2000-01-01) microseconds
        ts = int((time.time() - 946_684_800) * 1_000_000)
        msg = b"r" + struct.pack(
            "!QQQQB", flushed_lsn + 1, flushed_lsn + 1, flushed_lsn + 1,
            ts, 1 if reply_requested else 0,
        )
        self._send(b"d", msg)


class Wal2JsonDecoder:
    """wal2json v2 messages -> ChangeItems (wal2json_parser.go)."""

    def __init__(self):
        self._schemas: dict[str, TableSchema] = {}

    def _schema_for(self, obj: dict) -> TableSchema:
        cols = obj.get("columns") or obj.get("identity") or []
        key = json.dumps(
            [obj.get("schema"), obj.get("table"),
             [(c.get("name"), c.get("type")) for c in cols],
             [c.get("name") for c in (obj.get("pk") or [])]],
            sort_keys=True,
        )
        cached = self._schemas.get(key)
        if cached is not None:
            return cached
        pk_names = {c.get("name") for c in (obj.get("pk") or [])}
        if not pk_names and obj.get("identity"):
            pk_names = {c.get("name") for c in obj["identity"]}
        schema = TableSchema([
            ColSchema(
                name=c["name"],
                data_type=map_source_type("pg", (c.get("type") or "")
                                          .lower()),
                primary_key=c["name"] in pk_names,
                original_type=f"pg:{c.get('type', '')}",
            )
            for c in cols
        ])
        self._schemas[key] = schema
        return schema

    @staticmethod
    def _coerce(cs: ColSchema, v):
        if v is None:
            return None
        t = cs.data_type
        if t.is_integer:
            try:
                return int(v)
            except (TypeError, ValueError):
                return v
        if t.is_float:
            try:
                return float(v)
            except (TypeError, ValueError):
                return v
        return v

    def decode(self, payload: bytes, lsn: int,
               txn_id: str = "") -> Optional[ChangeItem]:
        obj = json.loads(payload)
        action = obj.get("action")
        if action in ("B", "C"):  # txn begin/commit markers
            return None
        if action == "M":  # logical message
            return None
        kind = {"I": Kind.INSERT, "U": Kind.UPDATE,
                "D": Kind.DELETE, "T": Kind.TRUNCATE}.get(action)
        if kind is None:
            raise ValueError(f"wal2json: unknown action {action!r}")
        tid = TableID(obj.get("schema", ""), obj.get("table", ""))
        if kind == Kind.TRUNCATE:
            return ChangeItem(kind=kind, schema=tid.namespace,
                              table=tid.name, lsn=lsn, txn_id=txn_id)
        schema = self._schema_for(obj)
        names, values = (), ()
        if kind != Kind.DELETE:
            cols = obj.get("columns") or []
            names = tuple(c["name"] for c in cols)
            values = tuple(
                self._coerce(schema.find(c["name"]), c.get("value"))
                for c in cols
            )
        old_keys = OldKeys()
        identity = obj.get("identity") or []
        if identity:
            old_keys = OldKeys(
                tuple(c["name"] for c in identity),
                tuple(
                    self._coerce(schema.find(c["name"]), c.get("value"))
                    for c in identity
                ),
            )
        return ChangeItem(
            kind=kind, schema=tid.namespace, table=tid.name,
            column_names=names, column_values=values,
            table_schema=schema, old_keys=old_keys,
            lsn=lsn, txn_id=txn_id,
            commit_time_ns=time.time_ns(),
        )


class PGReplicationSource(Source):
    """Slot-based CDC source with post-push LSN checkpointing."""

    STATE_KEY = "pg_wal_lsn"

    def __init__(self, params, transfer_id: str,
                 coordinator: Optional[Coordinator] = None,
                 batch_rows: int = 1024,
                 flush_interval: float = 1.0):
        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.batch_rows = batch_rows
        self.flush_interval = flush_interval
        self.decoder = Wal2JsonDecoder()
        self._stop = threading.Event()
        self.slot = params.slot_name or f"transferia_{transfer_id}" \
            .replace("-", "_")

    def _connect(self) -> ReplicationConnection:
        return ReplicationConnection(
            host=self.params.host, port=self.params.port,
            database=self.params.database, user=self.params.user,
            password=self.params.password, replication=True,
        ).connect()

    def ensure_slot(self, conn: ReplicationConnection) -> str:
        """Create the slot if missing; returns the start LSN."""
        try:
            info = conn.create_slot(self.slot)
            lsn = info.get("consistent_point") or "0/0"
            logger.info("created replication slot %s at %s", self.slot, lsn)
            return lsn
        except PGError as e:
            if e.sqlstate == "42710":  # duplicate_object: slot exists
                return "0/0"
            raise

    def run(self, sink: AsyncSink) -> None:
        conn = self._connect()
        dblog = None
        try:
            start_lsn = "0/0"
            if self.cp is not None:
                state = self.cp.get_transfer_state(self.transfer_id)
                if state.get(self.STATE_KEY):
                    start_lsn = state[self.STATE_KEY]
            if start_lsn == "0/0":
                start_lsn = self.ensure_slot(conn) or "0/0"
            conn.start_replication(self.slot, start_lsn)
            if getattr(self.params, "dblog_snapshot", False):
                from transferia_tpu.providers.postgres.dblog import (
                    PGDBLogRunner,
                )

                # the runner's filter stays wired even after completion:
                # residual signal-table echoes replayed from the slot
                # must never reach the target
                dblog = PGDBLogRunner(
                    self.params, self.transfer_id, self.cp,
                    chunk_rows=self.params.dblog_chunk_rows,
                    tables=self.params.dblog_tables or None,
                )
                if not dblog.already_done():
                    dblog.start()
                else:
                    dblog.done.set()
            items: list[ChangeItem] = []
            futures: list = []
            flushed = lsn_to_int(start_lsn) if start_lsn != "0/0" else 0
            pending_lsn = flushed
            last_flush = time.monotonic()

            def flush_items():
                nonlocal items
                if not items:
                    return
                for run in _split_homogeneous(items):
                    batch: object
                    if run[0].is_row_event() and run[0].table_schema:
                        batch = ColumnBatch.from_rows(run)
                    else:
                        batch = run
                    if dblog is not None:
                        # watermark fencing: signal rows are consumed,
                        # pending chunks emit inline at this position
                        batch = dblog.filter(batch)
                        if dblog.error is not None:
                            raise dblog.error
                        if isinstance(batch, list) and not batch:
                            continue
                        if (isinstance(batch, ColumnBatch)
                                and batch.n_rows == 0):
                            continue
                    futures.append(sink.async_push(batch))
                items = []

            def confirm():
                nonlocal flushed
                for f in futures:
                    f.result()
                futures.clear()
                if pending_lsn > flushed:
                    flushed = pending_lsn
                    if self.cp is not None:
                        self.cp.set_transfer_state(
                            self.transfer_id,
                            {self.STATE_KEY: int_to_lsn(flushed)},
                        )
                    conn.send_standby_status(flushed)

            while not self._stop.is_set():
                for kind, wal_end, payload in conn.stream(timeout=0.2):
                    if kind == "keepalive":
                        flush_items()
                        confirm()
                        if payload == b"\x01":
                            conn.send_standby_status(flushed, True)
                        continue
                    item = self.decoder.decode(payload, wal_end)
                    pending_lsn = max(pending_lsn, wal_end)
                    if item is not None:
                        items.append(item)
                    if len(items) >= self.batch_rows:
                        flush_items()
                    if self._stop.is_set():
                        break
                if time.monotonic() - last_flush >= self.flush_interval:
                    flush_items()
                    confirm()
                    last_flush = time.monotonic()
            flush_items()
            confirm()
        finally:
            conn.close()

    def stop(self) -> None:
        self._stop.set()


def _split_homogeneous(items: list[ChangeItem]) -> list[list[ChangeItem]]:
    out: list[list[ChangeItem]] = []
    key = None
    for it in items:
        k = (it.table_id, it.table_schema.fingerprint()
             if it.table_schema else None, it.is_row_event())
        if not out or k != key:
            out.append([])
            key = k
        out[-1].append(it)
    return out


class SlotMonitor:
    """Watches slot lag and kills runaway slots
    (pkg/abstract/slot_monitor.go:9-26)."""

    def __init__(self, params, slot: str,
                 max_lag_bytes: int = 50 << 30,
                 interval: float = 60.0):
        self.params = params
        self.slot = slot
        self.max_lag = max_lag_bytes
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def check_once(self) -> int:
        """Returns current slot lag in bytes; raises FatalError past limit."""
        conn = PGConnection(
            host=self.params.host, port=self.params.port,
            database=self.params.database, user=self.params.user,
            password=self.params.password,
        ).connect()
        try:
            lag = conn.scalar(
                "SELECT pg_wal_lsn_diff(pg_current_wal_lsn(), "
                f"restart_lsn) FROM pg_replication_slots "
                f"WHERE slot_name = '{self.slot}'"
            )
            lag = int(lag or 0)
            if lag > self.max_lag:
                raise FatalError(
                    f"replication slot {self.slot} lag {lag} bytes exceeds "
                    f"limit {self.max_lag}; dropping to protect the source"
                )
            return lag
        finally:
            conn.close()

    def start(self, on_fatal) -> None:
        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.check_once()
                except FatalError as e:
                    on_fatal(e)
                    return
                except PGError as e:
                    logger.warning("slot monitor check failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slot-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
