"""PostgreSQL frontend/backend protocol v3 client (pure stdlib).

Implements what the provider needs: startup, auth (trust / cleartext / md5 /
SCRAM-SHA-256), the simple query protocol, and COPY OUT/IN streaming.
Message framing per the PostgreSQL protocol docs: 1-byte type + int32
length (inclusive) + payload.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import socket
import struct
from base64 import b64decode, b64encode
from typing import Iterator, Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.utils.net import recv_exact


class PGError(CategorizedError):
    def __init__(self, message: str, fields: Optional[dict] = None):
        super().__init__(CategorizedError.SOURCE, message)
        self.fields = fields or {}

    @property
    def sqlstate(self) -> str:
        return self.fields.get("C", "")


class PGConnection:
    def __init__(self, host: str = "localhost", port: int = 5432,
                 database: str = "postgres", user: str = "postgres",
                 password: str = "", timeout: float = 60.0,
                 replication: bool = False):
        self.host = host
        self.port = port
        self.database = database
        self.user = user
        self.password = password
        self.timeout = timeout
        self.replication = replication
        self.sock: Optional[socket.socket] = None
        self.parameters: dict[str, str] = {}
        self.backend_pid = 0

    # -- framing ------------------------------------------------------------
    def _send(self, type_byte: bytes, payload: bytes) -> None:
        msg = type_byte + struct.pack("!I", len(payload) + 4) + payload
        self.sock.sendall(msg)

    def _recv_exact(self, n: int) -> bytes:
        try:
            return recv_exact(self.sock, n)
        except ConnectionError as e:
            raise PGError(str(e)) from e

    def _recv_message(self) -> tuple[bytes, bytes]:
        header = self._recv_exact(5)
        type_byte = header[:1]
        length = struct.unpack("!I", header[1:5])[0]
        payload = self._recv_exact(length - 4) if length > 4 else b""
        if type_byte == b"E":
            raise PGError(self._error_text(payload),
                          self._error_fields(payload))
        return type_byte, payload

    @staticmethod
    def _error_fields(payload: bytes) -> dict:
        fields = {}
        for part in payload.split(b"\x00"):
            if part:
                fields[chr(part[0])] = part[1:].decode("utf-8", "replace")
        return fields

    @classmethod
    def _error_text(cls, payload: bytes) -> str:
        f = cls._error_fields(payload)
        return f"{f.get('S', 'ERROR')}: {f.get('M', 'unknown')} " \
               f"(sqlstate {f.get('C', '?')})"

    # -- connection ---------------------------------------------------------
    def connect(self) -> "PGConnection":
        from transferia_tpu.utils.net import BufferedSock

        raw = socket.create_connection((self.host, self.port),
                                       timeout=self.timeout)
        raw.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # buffered reads: COPY streams arrive as one small frame per row
        self.sock = BufferedSock(raw)
        params = {
            "user": self.user,
            "database": self.database,
            "client_encoding": "UTF8",
            "application_name": "transferia-tpu",
        }
        if self.replication:
            params["replication"] = "database"
        body = b"".join(
            k.encode() + b"\x00" + v.encode() + b"\x00"
            for k, v in params.items()
        ) + b"\x00"
        startup = struct.pack("!II", len(body) + 8, 196608) + body
        self.sock.sendall(startup)
        self._auth_loop()
        return self

    def _auth_loop(self) -> None:
        while True:
            t, payload = self._recv_message()
            if t == b"R":
                code = struct.unpack("!I", payload[:4])[0]
                if code == 0:
                    continue  # AuthenticationOk
                if code == 3:  # cleartext
                    self._send(b"p", self.password.encode() + b"\x00")
                elif code == 5:  # md5
                    salt = payload[4:8]
                    inner = hashlib.md5(
                        (self.password + self.user).encode()
                    ).hexdigest()
                    digest = "md5" + hashlib.md5(
                        inner.encode() + salt
                    ).hexdigest()
                    self._send(b"p", digest.encode() + b"\x00")
                elif code == 10:  # SASL
                    self._scram(payload[4:])
                elif code in (11, 12):
                    continue  # SASL continue handled in _scram
                else:
                    raise PGError(f"unsupported auth method {code}")
            elif t == b"S":
                k, v, _ = payload.split(b"\x00", 2)
                self.parameters[k.decode()] = v.decode()
            elif t == b"K":
                self.backend_pid = struct.unpack("!I", payload[:4])[0]
            elif t == b"Z":
                return
            # ignore N (notice) and others

    def _scram(self, mechanisms: bytes) -> None:
        """SCRAM-SHA-256 (RFC 5802/7677)."""
        if b"SCRAM-SHA-256" not in mechanisms:
            raise PGError(f"no supported SASL mechanism in {mechanisms!r}")
        nonce = b64encode(os.urandom(18)).decode()
        first_bare = f"n=,r={nonce}"
        init = b"SCRAM-SHA-256\x00" + struct.pack(
            "!I", len(first_bare) + 3
        ) + b"n,," + first_bare.encode()
        self._send(b"p", init)
        t, payload = self._recv_message()
        code = struct.unpack("!I", payload[:4])[0]
        if code != 11:
            raise PGError(f"expected SASLContinue, got {code}")
        server_first = payload[4:].decode()
        parts = dict(p.split("=", 1) for p in server_first.split(","))
        r, s, i = parts["r"], parts["s"], int(parts["i"])
        if not r.startswith(nonce):
            raise PGError("SCRAM server nonce mismatch")
        salted = hashlib.pbkdf2_hmac(
            "sha256", self.password.encode(), b64decode(s), i
        )
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c={b64encode(b'n,,').decode()},r={r}"
        auth_message = ",".join([first_bare, server_first, without_proof])
        client_sig = hmac.new(stored_key, auth_message.encode(),
                              hashlib.sha256).digest()
        proof = b64encode(
            bytes(a ^ b for a, b in zip(client_key, client_sig))
        ).decode()
        self._send(b"p", f"{without_proof},p={proof}".encode())
        t, payload = self._recv_message()
        code = struct.unpack("!I", payload[:4])[0]
        if code != 12:
            raise PGError(f"expected SASLFinal, got {code}")
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        expect = hmac.new(server_key, auth_message.encode(),
                          hashlib.sha256).digest()
        final = dict(p.split("=", 1)
                     for p in payload[4:].decode().split(","))
        if b64decode(final.get("v", "")) != expect:
            raise PGError("SCRAM server signature mismatch")

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._send(b"X", b"")
            except OSError:
                pass
            self.sock.close()
            self.sock = None

    # -- simple query protocol ---------------------------------------------
    def query(self, sql: str) -> list[dict]:
        """Run a query; text-format rows as dicts (None for NULL)."""
        self._send(b"Q", sql.encode() + b"\x00")
        columns: list[str] = []
        rows: list[dict] = []
        error: Optional[PGError] = None
        while True:
            try:
                t, payload = self._recv_message()
            except PGError as e:
                error = e
                continue  # drain until ReadyForQuery
            if t == b"T":
                columns = self._parse_row_description(payload)
            elif t == b"D":
                rows.append(dict(zip(
                    columns, self._parse_data_row(payload)
                )))
            elif t == b"Z":
                if error is not None:
                    raise error
                return rows
            # C (complete), N (notice), I (empty) ignored

    @staticmethod
    def _parse_row_description(payload: bytes) -> list[str]:
        n = struct.unpack("!H", payload[:2])[0]
        pos = 2
        cols = []
        for _ in range(n):
            end = payload.index(b"\x00", pos)
            cols.append(payload[pos:end].decode())
            pos = end + 1 + 18  # skip fixed field metadata
        return cols

    @staticmethod
    def _parse_data_row(payload: bytes) -> list[Optional[str]]:
        n = struct.unpack("!H", payload[:2])[0]
        pos = 2
        out = []
        for _ in range(n):
            ln = struct.unpack("!i", payload[pos:pos + 4])[0]
            pos += 4
            if ln < 0:
                out.append(None)
            else:
                out.append(payload[pos:pos + ln].decode("utf-8", "replace"))
                pos += ln
        return out

    def scalar(self, sql: str):
        rows = self.query(sql)
        if not rows:
            return None
        return next(iter(rows[0].values()))

    # -- COPY ---------------------------------------------------------------
    def copy_out(self, sql: str) -> Iterator[bytes]:
        """COPY ... TO STDOUT: yields raw CopyData chunks."""
        self._send(b"Q", sql.encode() + b"\x00")
        error: Optional[PGError] = None
        while True:
            try:
                t, payload = self._recv_message()
            except PGError as e:
                error = e
                continue
            if t == b"d":
                yield payload
            elif t == b"Z":
                if error is not None:
                    raise error
                return
            # H (CopyOutResponse), c (CopyDone), C ignored

    def _drain_until_ready(self, first_error: "PGError") -> None:
        """Consume messages through ReadyForQuery so the connection stays
        usable, then raise — an early raise leaves replies buffered and
        every later query would read the previous query's responses."""
        while True:
            try:
                t, _ = self._recv_message()
            except PGError:
                continue
            if t == b"Z":
                raise first_error

    def copy_in(self, sql: str, chunks) -> None:
        """COPY ... FROM STDIN: send chunks, finish, wait for commit."""
        self._send(b"Q", sql.encode() + b"\x00")
        try:
            t, payload = self._recv_message()
        except PGError as e:
            self._drain_until_ready(e)
        if t != b"G":
            self._drain_until_ready(
                PGError(f"expected CopyInResponse, got {t!r}")
            )
        for chunk in chunks:
            if chunk:
                self._send(b"d", chunk)
        self._send(b"c", b"")
        error: Optional[PGError] = None
        while True:
            try:
                t, payload = self._recv_message()
            except PGError as e:
                error = e
                continue
            if t == b"Z":
                if error is not None:
                    raise error
                return
