"""Schema-object transfer: indexes, views, sequences
(reference: pkg/providers/postgres/pg_dump.go — pg_dump-style DDL moved
from source to target around the snapshot).

Post-data objects (indexes, views) apply AFTER rows land — building
indexes on loaded tables is much faster and views depend on the tables.
Sequences apply post-data too, with setval() so serial columns continue
from the source's last value.  Primary-key indexes are skipped: the sink
already creates them with the table DDL.
"""

from __future__ import annotations

import logging
import re
from typing import Sequence

logger = logging.getLogger(__name__)


def _q(ident: str) -> str:
    return '"' + ident.replace('"', '""') + '"'


def _lit(s: str) -> str:
    """SQL string literal with quote escaping."""
    return "'" + str(s).replace("'", "''") + "'"


def dump_ddl_objects(conn, schemas: Sequence[str]) -> list[str]:
    """Read post-data DDL statements from the source connection."""
    in_list = ", ".join(_lit(s) for s in schemas)
    out: list[str] = []
    for row in conn.query(
            f"SELECT schemaname, sequencename, start_value, increment_by, "
            f"last_value FROM pg_sequences "
            f"WHERE schemaname IN ({in_list})"):
        seq = f"{_q(row['schemaname'])}.{_q(row['sequencename'])}"
        out.append(
            f"CREATE SEQUENCE IF NOT EXISTS {seq} "
            f"START WITH {row['start_value'] or 1} "
            f"INCREMENT BY {row['increment_by'] or 1}")
        if row.get("last_value"):
            # regclass literal with QUOTED idents: unquoted names would
            # case-fold and miss mixed-case sequences
            out.append(f"SELECT setval({_lit(seq)}, "
                       f"{row['last_value']})")
    for row in conn.query(
            f"SELECT schemaname, tablename, indexname, indexdef "
            f"FROM pg_indexes WHERE schemaname IN ({in_list})"):
        if row["indexname"].endswith("_pkey"):
            continue  # the table DDL already created the pk index
        ddl = row["indexdef"]
        # idempotent re-activation: CREATE [UNIQUE] INDEX IF NOT EXISTS
        ddl = re.sub(r"^CREATE (UNIQUE )?INDEX (?!IF NOT EXISTS)",
                     lambda m: f"CREATE {m.group(1) or ''}INDEX "
                               f"IF NOT EXISTS ",
                     ddl, count=1)
        out.append(ddl)
    for row in conn.query(
            f"SELECT schemaname, viewname, definition FROM pg_views "
            f"WHERE schemaname IN ({in_list})"):
        view = f"{_q(row['schemaname'])}.{_q(row['viewname'])}"
        out.append(f"CREATE OR REPLACE VIEW {view} AS "
                   f"{row['definition'].rstrip(';')}")
    return out


def apply_ddl_objects(conn, statements: list[str]) -> int:
    """Apply on the target; per-statement failures log and continue (a
    view referencing an excluded table must not fail the transfer —
    pg_dump.go applies best-effort the same way)."""
    applied = 0
    for stmt in statements:
        try:
            conn.query(stmt)
            applied += 1
        except Exception as e:
            logger.warning("ddl object skipped (%s): %.120s", e, stmt)
    return applied
