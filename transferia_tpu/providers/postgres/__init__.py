"""PostgreSQL provider.

Reference parity: pkg/providers/postgres/ (storage.go snapshot reads,
splitter/ ctid sharding, typesystem.go).  The client is a dependency-free
implementation of the v3 wire protocol (this image ships no libpq binding);
snapshot loads stream through COPY ... TO STDOUT (FORMAT csv) into
pyarrow's vectorized CSV reader — rows land columnar without a per-row
decode loop.  Logical-replication CDC (slot management, wal2json decode,
slot monitor) builds on the same protocol layer (replication.py).
"""

from transferia_tpu.providers.postgres.provider import (
    PGSourceParams,
    PGTargetParams,
    PostgresProvider,
)

__all__ = ["PGSourceParams", "PGTargetParams", "PostgresProvider"]
