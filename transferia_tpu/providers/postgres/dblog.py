"""DBLog incremental snapshot wired into the PG provider.

Reference: pkg/providers/postgres/dblog/ (signal table + pg chunk
iterator) and pkg/providers/postgres/provider.go:443 DBLogUpload — a
chunked, watermark-fenced snapshot interleaving with the LIVE wal2json
stream, so huge tables snapshot without a long-held consistent read
while replication keeps flowing.  The engine (watermark window, touched-
key dedup, inline chunk emission at the HIGH watermark's stream
position) lives in transferia_tpu/dblog/core.py; this module supplies
the PG pieces:

  - signal table: ``public.__transferia_signal`` on the source; every
    watermark is an INSERT whose wal2json echo fences the chunk window
  - chunk iterator: keyset-paged ``SELECT ... WHERE pk > cursor ORDER BY
    pk LIMIT n`` through the COPY path (PGStorage._copy_select)
  - runner: drives one table at a time, exposes filter() for the
    replication source to pass every CDC batch through, and marks
    completion in transfer state so resume never re-snapshots
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from transferia_tpu.abstract.schema import TableID
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.dblog.core import (
    DBLogSnapshot,
    PagedChunkIterator,
    StorageSignalTable,
)

logger = logging.getLogger(__name__)

SIGNAL_TID = TableID("public", "__transferia_signal")


class PGDBLogRunner:
    """Drives DBLog snapshots for a PG source next to live replication.

    The replication source calls ``filter(batch)`` on every batch it is
    about to push (watermark rows are consumed there; pending chunks are
    emitted inline), and ``start()`` once streaming is up.  Completion is
    recorded under STATE_KEY in the transfer state."""

    STATE_KEY = "pg_dblog_done"

    def __init__(self, params, transfer_id: str, coordinator,
                 chunk_rows: int = 10_000,
                 tables: Optional[list[str]] = None,
                 chunk_timeout: float = 30.0):
        from transferia_tpu.providers.postgres.provider import PGStorage

        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.chunk_rows = chunk_rows
        self.tables = tables
        self.chunk_timeout = chunk_timeout
        self.storage = PGStorage(params)
        self._active: Optional[DBLogSnapshot] = None
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    # -- replication-side hook ----------------------------------------------
    def filter(self, batch):
        """Pass a CDC batch through the active snapshot's watermark
        filter.  Signal-table rows NEVER pass — watermark echoes landing
        between snapshots (the SUCCESS marker, residual slot replays
        after completion) must not reach the target."""
        snap = self._active
        if snap is not None:
            batch = snap.filter_cdc(batch)
        if isinstance(batch, ColumnBatch):
            if batch.table_id == SIGNAL_TID:
                return []
            return batch
        return [it for it in batch
                if getattr(it, "table_id", None) != SIGNAL_TID]

    # -- lifecycle -----------------------------------------------------------
    def already_done(self) -> bool:
        if self.cp is None:
            return False
        state = self.cp.get_transfer_state(self.transfer_id)
        return bool(state.get(self.STATE_KEY))

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run_all, name="pg-dblog", daemon=True)
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    # -- internals ------------------------------------------------------------
    def _write_conn(self):
        from transferia_tpu.providers.postgres.provider import _conn

        return _conn(self.params)

    def _ensure_signal_table(self, conn) -> None:
        conn.query(
            f'CREATE TABLE IF NOT EXISTS '
            f'"{SIGNAL_TID.namespace}"."{SIGNAL_TID.name}" '
            f'(mark_id text, kind text, PRIMARY KEY (mark_id))'
        )

    def _table_ids(self) -> list[TableID]:
        if self.tables:
            return [TableID.parse(t) for t in self.tables]
        return [tid for tid in self.storage.table_list()
                if tid != SIGNAL_TID]

    def _chunk_loader(self, tid: TableID, schema, key: str):
        from transferia_tpu.providers.postgres.provider import _pg_literal

        cols = ", ".join(f'"{c.name}"' for c in schema)

        def load(cursor, limit: int) -> Optional[ColumnBatch]:
            where = (f' WHERE "{key}" > {_pg_literal(cursor)}'
                     if cursor is not None else "")
            sql = (f'SELECT {cols} FROM {tid.fqtn()}{where} '
                   f'ORDER BY "{key}" LIMIT {int(limit)}')
            got: list[ColumnBatch] = []
            self.storage._copy_select(sql, tid, schema, got.append)
            if not got:
                return None
            if len(got) == 1:
                return got[0]
            rows = []
            for b in got:
                rows.extend(b.to_rows())
            return ColumnBatch.from_rows(rows)

        return load

    def _run_all(self) -> None:
        try:
            conn = self._write_conn()
            try:
                self._ensure_signal_table(conn)

                def write_watermark(mark_id: str, kind: str) -> None:
                    conn.query(
                        f'INSERT INTO "{SIGNAL_TID.namespace}".'
                        f'"{SIGNAL_TID.name}" (mark_id, kind) '
                        f"VALUES ('{mark_id}', '{kind}')"
                    )

                signal = StorageSignalTable(write_watermark,
                                            table=SIGNAL_TID)
                total = 0
                for tid in self._table_ids():
                    schema = self.storage.table_schema(tid)
                    keys = [c.name for c in schema.key_columns()]
                    if len(keys) != 1:
                        logger.warning(
                            "dblog: %s needs a single-column primary key "
                            "(has %d) — skipping (use the regular "
                            "snapshot path for it)", tid, len(keys))
                        continue
                    snap = DBLogSnapshot(
                        signal,
                        PagedChunkIterator(
                            self._chunk_loader(tid, schema, keys[0]),
                            keys[0], self.chunk_rows),
                        keys,
                    )
                    self._active = snap
                    try:
                        rows = snap.run(chunk_timeout=self.chunk_timeout)
                    finally:
                        self._active = None
                    logger.info("dblog snapshot of %s: %d rows", tid,
                                rows)
                    total += rows
                if self.cp is not None:
                    state = self.cp.get_transfer_state(self.transfer_id)
                    state[self.STATE_KEY] = True
                    self.cp.set_transfer_state(self.transfer_id, state)
                logger.info("dblog snapshot complete: %d rows total",
                            total)
            finally:
                conn.close()
        except BaseException as e:  # surfaced by the replication loop
            logger.exception("dblog snapshot failed")
            self.error = e
        finally:
            self.done.set()
