"""Arrow Flight provider: shard handoff endpoints as transfer sources
and sinks (`flight`).

The source reads parts published on a `ShardFlightServer`
(interchange/flight.py): every Flight on the server is one
`OperationTablePart`-shaped stream keyed `<namespace>.<table>/<part>`,
each listed part becomes a shardable `TableDescription`, and co-located
clients map the server's shared-memory segments instead of pulling the
gRPC stream (automatic — interchange/shm.py).  The sink DoPuts each
pushed batch as a part, which is how a decode-plane worker publishes
shards for the fleet instead of every worker re-decoding parquet.

pyarrow(+flight) is optional: the provider registers unconditionally
and raises an actionable install hint at use time (_pyarrow.py).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Optional

from transferia_tpu.abstract.commit import StagedSinker
from transferia_tpu.abstract.interfaces import (
    Batch,
    Pusher,
    ShardingStorage,
    Sinker,
    Storage,
    TableInfo,
    is_columnar,
)
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import Provider, register_provider

_PART_FILTER = "flight_part:"


@register_endpoint
@dataclass
class FlightSourceParams(EndpointParams):
    PROVIDER = "flight"
    IS_SOURCE = True

    uri: str = ""            # grpc://host:port
    # None = auto (shm negotiated when the server is co-located);
    # False forces the gRPC wire path
    allow_shm: Optional[bool] = None


@register_endpoint
@dataclass
class FlightTargetParams(EndpointParams):
    PROVIDER = "flight"
    IS_TARGET = True

    uri: str = ""


def part_key(table_id: TableID, part: str) -> str:
    return f"{table_id.namespace}.{table_id.name}/{part}"


def split_part_key(key: str) -> tuple[TableID, str]:
    path, _, part = key.rpartition("/")
    ns, _, name = path.rpartition(".")
    return TableID(ns, name), part


class FlightStorage(Storage, ShardingStorage):
    """Snapshot storage over a shard server's published parts."""

    def __init__(self, params: FlightSourceParams):
        from transferia_tpu.interchange.flight import FlightShardClient

        self.params = params
        self._client = FlightShardClient(params.uri,
                                         allow_shm=params.allow_shm)
        # the part list is immutable for the life of a snapshot shard
        # plan: list_flights once, not per capability call
        self._catalog_cache = None

    def _catalog(self) -> dict[TableID, tuple[TableSchema, list[str], int]]:
        from transferia_tpu.columnar.batch import arrow_to_table_schema
        from transferia_tpu.interchange import convert

        if self._catalog_cache is not None:
            return self._catalog_cache
        out: dict[TableID, tuple[TableSchema, list[str], int]] = {}
        for info in self._client.list_parts():
            key = info.descriptor.path[0].decode()
            tid, _part = split_part_key(key)
            md = info.schema.metadata or {}
            if convert.SCHEMA_KEY in md:
                schema = TableSchema.from_json(
                    json.loads(md[convert.SCHEMA_KEY]))
            else:
                schema = arrow_to_table_schema(info.schema)
            if tid in out:
                prev = out[tid]
                prev[1].append(key)
                out[tid] = (prev[0], prev[1],
                            prev[2] + max(0, info.total_records))
            else:
                out[tid] = (schema, [key], max(0, info.total_records))
        self._catalog_cache = out
        return out

    def table_list(self, include=None):
        out = {}
        for tid, (schema, _keys, rows) in self._catalog().items():
            if include and not any(tid.include_matches(p) for p in include):
                continue
            out[tid] = TableInfo(eta_rows=rows, schema=schema)
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        return self._catalog()[table][0]

    def estimate_table_rows_count(self, table: TableID) -> int:
        entry = self._catalog().get(table)
        return entry[2] if entry else 0

    def shard_table(self, table: TableDescription) -> list[TableDescription]:
        entry = self._catalog().get(table.id)
        if entry is None:
            return [table]
        return [TableDescription(id=table.id,
                                 filter=f"{_PART_FILTER}{key}")
                for key in entry[1]]

    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        if table.filter.startswith(_PART_FILTER):
            keys = [table.filter[len(_PART_FILTER):]]
        else:
            entry = self._catalog().get(table.id)
            if entry is None:
                raise KeyError(f"flight: no parts for table {table.id}")
            keys = entry[1]
        for key in keys:
            for batch in self._client.get_part(key):
                pusher(batch.rename_table(table.id))

    def close(self) -> None:
        self._client.close()


class FlightSinker(Sinker, StagedSinker):
    """Publishes pushed blocks as part streams: consecutive batches of
    one part flow through a single held-open DoPut stream (closed when
    the part changes or on close()).  Part identity is the batch's
    `part_id` when the snapshot engine stamped one, else a per-table
    sequence.  A RETRIED part re-puts its key, which REPLACES the
    server-side stream — duplicates never append.

    Staged-commit capable (abstract/commit.py): with an open part stage
    the blocks buffer client-side and `publish_part` DoPuts them with
    the assignment epoch in the descriptor — the shard server fences
    stale epochs, so a zombie's publish of a reclaimed part is rejected
    at the wire instead of replacing the survivor's stream.  Publish
    atomicity is per wire key (one DoPut stream): snapshot parts are
    single-table so one part = one stream = one atomic replace; a
    multi-table part (CDC-shaped row batches) publishes one stream per
    table sequentially, and a mid-publish wire failure can leave
    earlier tables' streams visible until the part's idempotent
    republish replaces them (each stream is still individually fenced,
    so a zombie can never clobber any of the survivor's streams)."""

    def __init__(self, params: FlightTargetParams):
        import uuid

        from transferia_tpu.interchange.flight import FlightShardClient

        self.params = params
        self._client = FlightShardClient(params.uri)
        self._seq: dict[TableID, int] = {}
        # table -> (part key, open flight writer); push is serialized
        # per sink instance (Sinker contract)
        self._open: dict[TableID, tuple] = {}
        self._lock = threading.Lock()
        # sequence-keyed fallback parts embed an instance token: the
        # loader runs one sink pipeline per part in parallel, and two
        # instances both starting at seq 0 must not replace each
        # other's streams (same contract as the fs sink's file token)
        self._token = uuid.uuid4().hex[:8]
        self._stage = None  # staging.PartStage when open

    @staticmethod
    def _blocks(batch: Batch) -> list[ColumnBatch]:
        if is_columnar(batch):
            return [batch]
        rows = [it for it in batch if it.is_row_event()]
        if not rows:
            return []
        by_table: dict[TableID, list] = {}
        for it in rows:
            by_table.setdefault(it.table_id, []).append(it)
        return [ColumnBatch.from_rows(its) for its in by_table.values()]

    # -- StagedSinker -------------------------------------------------------
    def begin_part(self, key: str, epoch: int) -> None:
        from transferia_tpu.providers.staging import PartStage

        self._stage = PartStage(key, epoch, hold=True)

    def publish_part(self, key: str, epoch: int) -> int:
        from transferia_tpu.providers.staging import (
            part_slug,
            publish_guard,
        )

        if self._stage is None:
            raise RuntimeError(f"flight sink: no open stage for {key!r}")
        # group the staged blocks per table: one epoch-fenced put per
        # `<ns>.<table>/<part>` wire key, replacing whatever an earlier
        # publish of this part streamed.  put_part owns the rest of the
        # wire contract: pool-once accounting, FOR planning, the
        # stream-count model's substream choice, all-or-nothing
        # multi-stream failure, and stale-epoch mapping — a non-stale
        # wire failure propagates so the part republishes idempotently.
        by_table: dict[TableID, list] = {}
        for batch in self._stage.batches:
            for b in self._blocks(batch):
                by_table.setdefault(b.table_id, []).append(b)
        rows = 0
        with publish_guard(key, epoch):
            for tid, blocks in by_table.items():
                wire_key = part_key(tid, f"part-{part_slug(key)}")
                rows += self._client.put_part(wire_key, blocks,
                                              epoch=epoch)
        self.last_dedup_dropped = self._stage.dedup_dropped
        self._stage = None
        return rows

    def abort_part(self, key: str) -> None:
        self._stage = None

    def note_push_retry(self) -> None:
        if self._stage is not None:
            self._stage.note_push_retry()

    def push(self, batch: Batch) -> None:
        if self._stage is not None:
            self._stage.stage(batch)
            return
        blocks = self._blocks(batch)
        if not blocks:
            return
        from transferia_tpu.interchange.convert import (
            EncodedWireState,
            batch_to_arrow,
        )

        for b in blocks:
            rb = batch_to_arrow(b)
            if b.part_id:
                key = part_key(b.table_id, b.part_id)
                cur = self._open.get(b.table_id)
                if cur is not None and cur[0] != key:
                    cur[1].close()
                    cur = None
                if cur is None:
                    cur = (key, self._client.begin_put(key, rb.schema),
                           EncodedWireState())
                    self._open[b.table_id] = cur
                cur[2].account(b)  # pool-once per held-open stream
                cur[1].write_batch(rb)
                cur[2].commit()  # tallies publish per landed batch
                continue
            # no engine part identity: each push is its own part stream,
            # and the sequence advances only AFTER the put succeeds — a
            # sink-retried push re-puts the SAME key, which the server
            # replaces (the dedup contract the class docstring promises)
            with self._lock:
                seq = self._seq.get(b.table_id, 0)
            key = part_key(b.table_id, f"{self._token}-{seq}")
            writer = self._client.begin_put(key, rb.schema)
            writer.write_batch(rb)
            writer.close()
            with self._lock:
                self._seq[b.table_id] = seq + 1

    def close(self) -> None:
        errs = []
        for _key, writer, _wire in self._open.values():
            try:
                writer.close()
            except Exception as e:
                errs.append(e)
        self._open.clear()
        self._client.close()
        if errs:
            raise errs[0]


@register_provider
class FlightProvider(Provider):
    NAME = "flight"

    def storage(self):
        if isinstance(self.transfer.src, FlightSourceParams):
            return FlightStorage(self.transfer.src)
        return None

    def destination_storage(self):
        if isinstance(self.transfer.dst, FlightTargetParams):
            return FlightStorage(FlightSourceParams(
                uri=self.transfer.dst.uri))
        return None

    def sinker(self):
        if isinstance(self.transfer.dst, FlightTargetParams):
            return FlightSinker(self.transfer.dst)
        return None
