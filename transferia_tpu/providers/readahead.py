"""Row-group decode readahead: overlap host decode with the downstream
pipeline.

The snapshot hot loop was strictly serial per part: decode row group g
to completion, push its batches through filter -> transform -> sink,
only then touch g+1 — so host decode never overlapped device dispatch
or sink I/O (r05 profile: `source_decode` 68% of wall).  The decode
calls release the GIL (ctypes into the C++ chunk decoder, arrow C++
reads), so a single background thread decoding g+1 while g's batches
flow downstream buys genuine overlap without processes.

`RowGroupReadahead` is that bounded prefetcher:

- one worker thread decodes groups IN ORDER; the consumer iterates
  `(group, item)` pairs in the same order (batch ordering downstream is
  unchanged);
- bounded in-flight: at most `max_groups` decoded groups exist at once
  (the one the consumer holds + the queue + the one being decoded
  counts toward the cap), and optionally at most `max_bytes` of decoded
  payload — a part never holds more than ~2 row groups of decoded
  columns by default;
- a worker exception is re-raised to the consumer on its next pull (so
  it propagates to the `upload_tables` caller exactly like a serial
  decode error would);
- a consumer/pusher error cancels outstanding prefetches: `close()`
  (the context-manager exit) stops the worker before its next decode
  and drops queued groups;
- `max_groups <= 0` (or a single group) degrades to inline decode on
  the caller's thread — zero new threads, exactly the serial behavior.

Observability: each prefetch decode runs inside a `decode_readahead`
trace span on the worker thread (stage timers taken inside the decode
callable fold into the global stagetimer totals — per-thread accounting
is already how overlap_factor is defined); consumer stalls are
accounted as a `decode_wait` stage; queue depth and in-flight decoded
bytes feed optional gauges (stats/registry.py DeviceStats) plus the
module-level aggregate `snapshot_stats()` that `bench.py` appends to
its stages line.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterable, Optional

# process-wide aggregates (bench/diagnostic visibility, like
# parquet_native.fallback_stats): how deep the queue ran and how much
# decoded payload was in flight, across every prefetcher in the run
_agg_lock = threading.Lock()
_agg = {
    "prefetched_groups": 0,
    "prefetched_bytes": 0,
    "depth_sum": 0,
    "depth_samples": 0,
    "max_depth": 0,
    "max_inflight_bytes": 0,
    "cancelled_groups": 0,
}


def snapshot_stats() -> dict:
    with _agg_lock:
        out = dict(_agg)
    n = out.pop("depth_sum"), out.pop("depth_samples")
    out["avg_depth"] = round(n[0] / n[1], 2) if n[1] else 0.0
    return out


def reset_stats() -> None:
    with _agg_lock:
        for k in _agg:
            _agg[k] = 0


class RowGroupReadahead:
    """Bounded background decode of an ordered group list.

    with RowGroupReadahead(groups, decode, max_groups=2) as ra:
        for g, item in ra:
            ...push item's batches downstream...

    `decode(g)` runs on the worker thread (it must release the GIL to
    be useful — both the native parquet decoder and arrow reads do);
    `nbytes(item)` sizes an item for the byte cap and the gauges.
    `gauges` is an optional (depth_gauge, bytes_gauge) pair with
    prometheus inc/dec semantics (inc/dec compose across concurrent
    prefetchers where set() would fight).
    """

    def __init__(self, groups: Iterable, decode: Callable,
                 *, max_groups: int = 2,
                 max_bytes: Optional[int] = None,
                 nbytes: Optional[Callable] = None,
                 gauges: Optional[tuple] = None,
                 name: str = "decode-readahead"):
        self._groups = list(groups)
        self._decode = decode
        self._max_groups = max_groups
        self._max_bytes = max_bytes
        self._nbytes = nbytes
        self._gauges = gauges
        self._cond = threading.Condition()
        self._queue: deque = deque()  # (group, item, nbytes)
        self._inflight_bytes = 0
        self._handed: Optional[tuple] = None  # (group, nbytes) at consumer
        self._error: Optional[BaseException] = None
        self._closed = False
        self._done = False
        self._pos = 0  # inline-mode cursor
        self._thread: Optional[threading.Thread] = None
        # causal hop: the worker thread's decode spans must parent to
        # the span that SUBMITTED the prefetch (the part span), and its
        # resource events must bill the same (transfer, tenant, part)
        # — capture both here, adopt them in _run
        from transferia_tpu.stats import trace as _trace
        from transferia_tpu.stats.ledger import LEDGER as _ledger

        self._trace_ctx = _trace.current_context()
        self._ledger_key = _ledger.current_key()
        # max_groups=1 can never overlap (the cap counts the group the
        # consumer holds, stalling the worker whenever the consumer is
        # busy) — inline serial decode is strictly better there too
        if max_groups > 1 and len(self._groups) > 1:
            self._thread = threading.Thread(target=self._run, name=name,
                                            daemon=True)
            self._thread.start()

    # -- worker ------------------------------------------------------------
    def _stalled_locked(self) -> bool:
        """Caller holds self._cond.  True while decoding one more group
        would bust a cap.  A lone group always proceeds (a single group
        larger than max_bytes must still decode, or nothing ever
        flows)."""
        inflight = len(self._queue) + (1 if self._handed is not None else 0)
        if inflight == 0:
            return False
        if inflight + 1 > self._max_groups:
            return True
        return (self._max_bytes is not None
                and self._inflight_bytes >= self._max_bytes)

    def _run(self) -> None:
        from transferia_tpu.chaos.failpoints import failpoint
        from transferia_tpu.stats import trace
        from transferia_tpu.stats.ledger import LEDGER

        with trace.adopted(self._trace_ctx), \
                LEDGER.adopted(self._ledger_key):
            self._run_adopted(failpoint, trace)

    def _run_adopted(self, failpoint, trace) -> None:
        try:
            for g in self._groups:
                with self._cond:
                    while not self._closed and self._stalled_locked():
                        self._cond.wait()
                    if self._closed:
                        return
                failpoint("decode.readahead.worker")
                sp = trace.span("decode_readahead")
                if sp:
                    sp.add(group=g)
                with sp:
                    item = self._decode(g)
                nb = int(self._nbytes(item)) if self._nbytes else 0
                with self._cond:
                    if self._closed:
                        return  # consumer bailed mid-decode: drop
                    self._queue.append((g, item, nb))
                    self._inflight_bytes += nb
                    self._account_enqueue_locked(nb)
                    self._cond.notify_all()
        except BaseException as e:  # re-raised on the consumer thread
            with self._cond:
                self._error = e
                self._cond.notify_all()
        finally:
            with self._cond:
                self._done = True
                self._cond.notify_all()

    def _account_enqueue_locked(self, nb: int) -> None:
        depth = len(self._queue)
        if self._gauges is not None:
            self._gauges[0].inc()
            if nb:
                self._gauges[1].inc(nb)
        with _agg_lock:
            _agg["prefetched_groups"] += 1
            _agg["prefetched_bytes"] += nb
            _agg["depth_sum"] += depth
            _agg["depth_samples"] += 1
            _agg["max_depth"] = max(_agg["max_depth"], depth)
            _agg["max_inflight_bytes"] = max(_agg["max_inflight_bytes"],
                                             self._inflight_bytes)

    # -- consumer ----------------------------------------------------------
    def _release_handed_locked(self) -> None:
        if self._handed is None:
            return
        _, nb = self._handed
        self._handed = None
        self._inflight_bytes -= nb
        if self._gauges is not None and nb:
            self._gauges[1].dec(nb)

    def __iter__(self) -> "RowGroupReadahead":
        return self

    def __next__(self) -> tuple:
        if self._thread is None:
            return self._next_inline()
        waited = 0.0
        try:
            with self._cond:
                self._release_handed_locked()
                self._cond.notify_all()
                while True:
                    if self._queue:
                        g, item, nb = self._queue.popleft()
                        self._handed = (g, nb)
                        if self._gauges is not None:
                            self._gauges[0].dec()
                        break
                    if self._error is not None:
                        raise self._error
                    if self._done:
                        raise StopIteration
                    t0 = time.perf_counter()
                    self._cond.wait()
                    waited += time.perf_counter() - t0
        finally:
            if waited:
                from transferia_tpu.stats import stagetimer
                from transferia_tpu.stats.ledger import LEDGER

                stagetimer.add("decode_wait", waited)
                LEDGER.add(decode_wait_seconds=waited)
        return g, item

    def _next_inline(self) -> tuple:
        # serial fallback: no worker, no queue — decode on demand.  The
        # error/cancel semantics hold trivially (decode raises in place;
        # close() just ends iteration).
        if self._closed or self._pos >= len(self._groups):
            raise StopIteration
        g = self._groups[self._pos]
        self._pos += 1
        return g, self._decode(g)

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Cancel outstanding prefetches and join the worker.  Called by
        the context-manager exit — a pusher error inside the consumer
        loop lands here, so the worker stops before its next decode."""
        t = self._thread
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if t is not None:
            t.join()
        with self._cond:
            self._release_handed_locked()
            dropped = 0
            while self._queue:
                _g, _item, nb = self._queue.popleft()
                self._inflight_bytes -= nb
                dropped += 1
                if self._gauges is not None:
                    self._gauges[0].dec()
                    if nb:
                        self._gauges[1].dec(nb)
        if dropped:
            with _agg_lock:
                _agg["cancelled_groups"] += dropped

    def __enter__(self) -> "RowGroupReadahead":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
