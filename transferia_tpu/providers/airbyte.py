"""Airbyte connector provider: any Airbyte source image as a snapshot
source (reference: pkg/providers/airbyte/ + pkg/container/,
docs/architecture-overview.md:232-255).

The connector speaks the Airbyte protocol over stdout:
  spec / check / discover / read, line-framed JSON AirbyteMessages.
discover yields the stream catalog (-> table list + schemas); read with a
ConfiguredAirbyteCatalog streams RECORD/STATE/LOG messages.  The final
STATE message checkpoints into the coordinator KV (airbyte_state) and is
passed back via --state on the next run (incremental syncs).

Execution goes through the container runner (docker/podman, or
runtime "exec" to run a connector binary/script directly — also how the
tests drive the full protocol without a container runtime).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional

from transferia_tpu.abstract.errors import CategorizedError
from transferia_tpu.abstract.interfaces import (
    Pusher,
    Storage,
    TableInfo,
)
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.columnar.batch import ColumnBatch
from transferia_tpu.container import ContainerRunner, ContainerSpec
from transferia_tpu.models.endpoint import EndpointParams, register_endpoint
from transferia_tpu.providers.registry import (
    Provider,
    TestResult,
    register_provider,
)

logger = logging.getLogger(__name__)

STATE_KEY = "airbyte_state"


class AirbyteError(CategorizedError):
    def __init__(self, message: str):
        super().__init__(CategorizedError.SOURCE, message)


@register_endpoint
@dataclass
class AirbyteSourceParams(EndpointParams):
    PROVIDER = "airbyte"
    IS_SOURCE = True

    image: str = ""                  # connector image (docker/podman)
    config: dict = field(default_factory=dict)
    namespace: str = "airbyte"
    streams: list = field(default_factory=list)  # include list ([]=all)
    batch_rows: int = 10_000
    runtime: str = ""                # "" autodetect | docker | podman | exec
    exec_argv: list = field(default_factory=list)  # runtime=exec connector
    sync_mode: str = "full_refresh"  # full_refresh | incremental


_JSON_TO_CANONICAL = {
    "string": CanonicalType.UTF8,
    "integer": CanonicalType.INT64,
    "number": CanonicalType.DOUBLE,
    "boolean": CanonicalType.BOOLEAN,
    "object": CanonicalType.ANY,
    "array": CanonicalType.ANY,
}


def _json_type(prop: dict) -> CanonicalType:
    t = prop.get("type")
    if isinstance(t, list):  # ["null", "string"]
        t = next((x for x in t if x != "null"), "string")
    return _JSON_TO_CANONICAL.get(t, CanonicalType.ANY)


class AirbyteStorage(Storage):
    def __init__(self, params: AirbyteSourceParams, transfer_id: str = "",
                 coordinator=None):
        import threading

        self.params = params
        self.transfer_id = transfer_id
        self.cp = coordinator
        self.runner = ContainerRunner(params.runtime)
        self._catalog: Optional[dict] = None
        # one instance serves all upload worker threads: the catalog must
        # resolve once (discover is a full container run) and the state
        # blob read-modify-write must not lose concurrent streams' updates
        self._lock = threading.Lock()

    # -- protocol plumbing --------------------------------------------------
    def _spec(self, mode_args: list[str],
              files: dict[str, dict]) -> tuple[ContainerSpec, object]:
        """Build the run spec; files land in a temp dir that docker mounts
        at /data (exec connectors get host paths)."""
        tmp = tempfile.TemporaryDirectory(prefix="airbyte_")
        args = list(mode_args)
        for name, content in files.items():
            host = os.path.join(tmp.name, name)
            with open(host, "w") as fh:
                json.dump(content, fh)
            ctr = host if self.runner.runtime == "exec" \
                else f"/data/{name}"
            args += [f"--{name.split('.')[0]}", ctr]
        if self.runner.runtime == "exec":
            argv = list(self.params.exec_argv) + args
            return ContainerSpec(args=argv), tmp
        return ContainerSpec(
            image=self.params.image, args=args,
            mounts=[(tmp.name, "/data")], network="host",
        ), tmp

    def _messages(self, mode_args: list[str], files: dict[str, dict]):
        spec, tmp = self._spec(mode_args, files)
        try:
            for line in self.runner.stream(
                    spec, on_stderr=lambda ln: logger.debug(
                        "airbyte: %s", ln)):
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    logger.debug("non-json connector line: %.200s", line)
        finally:
            tmp.cleanup()

    # -- catalog ------------------------------------------------------------
    def catalog(self) -> dict:
        with self._lock:
            return self._catalog_locked()

    def _catalog_locked(self) -> dict:
        if self._catalog is None:
            for msg in self._messages(["discover"],
                                      {"config.json": self.params.config}):
                if msg.get("type") == "CATALOG":
                    self._catalog = msg["catalog"]
                    break
                if msg.get("type") == "TRACE" and \
                        msg.get("trace", {}).get("type") == "ERROR":
                    raise AirbyteError(
                        msg["trace"].get("error", {}).get("message",
                                                          "discover failed"))
            if self._catalog is None:
                raise AirbyteError("connector emitted no CATALOG message")
        return self._catalog

    def _streams(self) -> list[dict]:
        streams = self.catalog().get("streams", [])
        if self.params.streams:
            include = set(self.params.streams)
            streams = [s for s in streams if s["name"] in include]
        return streams

    def _schema_of(self, stream: dict) -> TableSchema:
        props = (stream.get("json_schema") or {}).get("properties", {})
        pkeys = {k[0] if isinstance(k, list) else k
                 for k in (stream.get("source_defined_primary_key") or [])}
        cols = [
            ColSchema(name, _json_type(prop), primary_key=name in pkeys,
                      original_type=f"airbyte:{prop.get('type')}")
            for name, prop in props.items()
        ]
        return TableSchema(cols)

    def table_list(self, include=None):
        out = {}
        for s in self._streams():
            tid = TableID(self.params.namespace, s["name"])
            if include and not any(tid.include_matches(p)
                                   for p in include):
                continue
            out[tid] = TableInfo(eta_rows=0, schema=self._schema_of(s))
        return out

    def table_schema(self, table: TableID) -> TableSchema:
        for s in self._streams():
            if s["name"] == table.name:
                return self._schema_of(s)
        raise AirbyteError(f"stream {table.name!r} not in catalog")

    def estimate_table_rows_count(self, table: TableID) -> int:
        return 0

    # -- read ---------------------------------------------------------------
    def load_table(self, table: TableDescription, pusher: Pusher) -> None:
        stream = next((s for s in self._streams()
                       if s["name"] == table.id.name), None)
        if stream is None:
            raise AirbyteError(f"stream {table.id.name!r} not in catalog")
        schema = self._schema_of(stream)
        configured = {"streams": [{
            "stream": stream,
            "sync_mode": self.params.sync_mode,
            "destination_sync_mode": "overwrite",
        }]}
        files = {"config.json": self.params.config,
                 "catalog.json": configured}
        # state is per-stream: a single transfer-wide blob would hand
        # stream B's cursor to stream A on the next run
        saved_state = None
        if self.cp is not None and \
                self.params.sync_mode == "incremental":
            all_state = self.cp.get_transfer_state(
                self.transfer_id).get(STATE_KEY) or {}
            saved_state = all_state.get(table.id.name)
        if saved_state is not None:
            files["state.json"] = saved_state

        rows: list[dict] = []
        nbytes = 0
        last_state = None

        def flush():
            nonlocal rows, nbytes
            if not rows:
                return
            data = {c.name: [r.get(c.name) for r in rows]
                    for c in schema}
            batch = ColumnBatch.from_pydict(table.id, schema, data)
            batch.read_bytes = nbytes
            pusher(batch)
            rows, nbytes = [], 0

        for msg in self._messages(["read"], files):
            mtype = msg.get("type")
            if mtype == "RECORD":
                rec = msg.get("record", {})
                if rec.get("stream") != table.id.name:
                    continue
                rows.append(rec.get("data", {}))
                nbytes += len(json.dumps(rec)) if rec else 0
                if len(rows) >= self.params.batch_rows:
                    flush()
            elif mtype == "STATE":
                last_state = msg.get("state")
            elif mtype == "TRACE" and \
                    msg.get("trace", {}).get("type") == "ERROR":
                raise AirbyteError(
                    msg["trace"].get("error", {}).get(
                        "message", "read failed"))
        flush()
        if last_state is not None and self.cp is not None:
            with self._lock:  # RMW of the whole blob: no lost updates
                all_state = self.cp.get_transfer_state(
                    self.transfer_id).get(STATE_KEY) or {}
                all_state[table.id.name] = last_state
                self.cp.set_transfer_state(self.transfer_id,
                                           {STATE_KEY: all_state})

    def ping(self) -> None:
        self.runner.require()
        for msg in self._messages(["check"],
                                  {"config.json": self.params.config}):
            if msg.get("type") == "CONNECTION_STATUS":
                if msg["connectionStatus"].get("status") != "SUCCEEDED":
                    raise AirbyteError(
                        msg["connectionStatus"].get("message",
                                                    "check failed"))
                return
        raise AirbyteError("connector emitted no CONNECTION_STATUS")


@register_provider
class AirbyteProvider(Provider):
    NAME = "airbyte"

    def storage(self):
        if isinstance(self.transfer.src, AirbyteSourceParams):
            return AirbyteStorage(self.transfer.src, self.transfer.id,
                                  self.coordinator)
        return None

    def test(self) -> TestResult:
        result = TestResult(ok=True)
        try:
            if isinstance(self.transfer.src, AirbyteSourceParams):
                AirbyteStorage(self.transfer.src).ping()
            result.add("check")
        except Exception as e:
            result.add("check", e)
        return result
