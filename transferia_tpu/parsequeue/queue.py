"""Parse->push->ack pipeline."""

from __future__ import annotations

import concurrent.futures
import logging
import threading
from typing import Any, Callable, Generic, Optional, Sequence, TypeVar

from transferia_tpu.abstract.interfaces import AsyncSink, Batch
from transferia_tpu.stats import trace

logger = logging.getLogger(__name__)

T = TypeVar("T")

# parse_fn(raw) -> Batch|list[Batch]; ack_fn(raw, error: Exception|None)
ParseFn = Callable[[Any], Any]
AckFn = Callable[[Any, Optional[BaseException]], None]


class ParseQueue(Generic[T]):
    """N-worker parse stage feeding an AsyncSink with ordered pushes.

    Pipeline parallelism (SURVEY §2.4 axis 4): parsing overlaps pushing and
    acking, but the sink sees batches in exactly Add() order and acks fire
    only after the corresponding push resolves — the at-least-once ordering
    contract queue sources rely on to commit offsets.
    """

    def __init__(self, parallelism: int, sink: AsyncSink,
                 parse_fn: ParseFn, ack_fn: AckFn,
                 max_inflight: int = 64):
        self.sink = sink
        self.parse_fn = parse_fn
        self.ack_fn = ack_fn
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, parallelism), thread_name_prefix="parse"
        )
        self._pusher = threading.Thread(
            target=self._push_loop, name="parsequeue-push", daemon=True
        )
        self._cv = threading.Condition()
        self._queue: list[tuple] = []
        self._closed = False
        self._failure: Optional[BaseException] = None
        self._inflight = threading.Semaphore(max_inflight)
        self._outstanding = 0  # added but not yet acked (guarded by _cv)
        self._pusher.start()

    # -- public -------------------------------------------------------------
    def add(self, raw: T) -> None:
        """Enqueue one unit; raises immediately if the queue has failed."""
        if self._failure is not None:
            raise self._failure
        if self._closed:
            raise RuntimeError("parsequeue closed")
        self._inflight.acquire()
        parse_fut = self._pool.submit(self._safe_parse, raw)
        with self._cv:
            self._queue.append((raw, parse_fut))
            self._outstanding += 1
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify()
        self._pusher.join(timeout=60)
        self._pool.shutdown(wait=False, cancel_futures=True)

    @property
    def failure(self) -> Optional[BaseException]:
        return self._failure

    # -- internals ----------------------------------------------------------
    def _safe_parse(self, raw: T):
        # the parser layer runs here (parse workers): decode raw broker
        # messages into batches — the source_decode stage of the timeline
        from transferia_tpu.chaos.failpoints import failpoint

        failpoint("parsequeue.parse")
        with trace.span("source_decode"):
            return self.parse_fn(raw)

    def _push_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(timeout=0.5)
                if not self._queue:
                    if self._closed:
                        return
                    continue
                raw, parse_fut = self._queue.pop(0)
            err: Optional[BaseException] = self._failure
            if err is None:
                # once failed, drain without pushing — pushing N+1 after N
                # failed would break the in-order delivery contract
                try:
                    parsed = parse_fut.result()
                    batches = parsed if isinstance(parsed, list) \
                        else [parsed]
                    # "sink_wait", not "sink_push": the actual push
                    # executes (and is spanned) inside the async sink's
                    # own worker — this span is the ordered-delivery
                    # wait, and naming them apart keeps the stage
                    # summary from double-counting the push
                    with trace.span("sink_wait"):
                        futs = []
                        for b in batches:
                            if b is not None and _batch_len(b):
                                futs.append(self.sink.async_push(b))
                        for f in futs:
                            f.result()
                except BaseException as e:
                    err = e
            try:
                self.ack_fn(raw, err)
            except BaseException as ack_err:
                err = err or ack_err
            if err is not None and self._failure is None:
                self._failure = err
                logger.error("parsequeue failed: %s", err)
            with self._cv:
                self._outstanding -= 1
                self._cv.notify_all()
            self._inflight.release()

    def wait(self) -> None:
        """Block until everything added so far is pushed+acked
        (WaitableParseQueue.Wait for rebalances)."""
        with self._cv:
            while self._outstanding > 0:
                self._cv.wait(timeout=0.5)
        if self._failure is not None:
            raise self._failure


def _batch_len(b) -> int:
    try:
        return b.n_rows if hasattr(b, "n_rows") else len(b)
    except TypeError:
        return 1


WaitableParseQueue = ParseQueue
