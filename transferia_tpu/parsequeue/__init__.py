"""ParseQueue: parallel parse, ordered push, serialized ack.

Reference parity: pkg/parsequeue/parsequeue.go:17-90 + README guarantees:
N parse workers run concurrently, pushes happen strictly in Add() order,
acks run serialized after their push resolves, and the first error latches
(fail-fast; subsequent Adds fail immediately).  WaitableParseQueue adds
Wait() for partition rebalances.
"""

from transferia_tpu.parsequeue.queue import ParseQueue, WaitableParseQueue

__all__ = ["ParseQueue", "WaitableParseQueue"]
