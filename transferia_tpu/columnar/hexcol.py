"""Numpy-only helpers shared by the host and device mask paths (no jax)."""

from __future__ import annotations

from typing import Optional

import numpy as np


def hex_to_varwidth(hexes: np.ndarray, validity: Optional[np.ndarray]
                    ) -> tuple[np.ndarray, np.ndarray]:
    """(N, 64) hex digest matrix -> flat var-width column bytes+offsets.

    Invalid rows become empty strings (validity is preserved separately by
    the caller's output Column).  Caller contract: hexes is freshly owned
    (device transfer / kernel output) — the all-valid fast path returns a
    reshape VIEW instead of copying 64 bytes/row again.
    """
    n = hexes.shape[0]
    if validity is None:
        out_offsets = np.arange(n + 1, dtype=np.int64) * 64
        if out_offsets[-1] > 2**31 - 1:
            raise ValueError("hashed column exceeds 2GiB")
        flat = np.ascontiguousarray(hexes).reshape(-1)
        return flat, out_offsets.astype(np.int32)
    lens = np.where(validity, 64, 0).astype(np.int64)
    out_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=out_offsets[1:])
    if out_offsets[-1] > 2**31 - 1:
        raise ValueError("hashed column exceeds 2GiB")
    out = np.zeros(int(out_offsets[-1]), dtype=np.uint8)
    valid_rows = np.nonzero(validity)[0]
    if len(valid_rows):
        starts = out_offsets[:-1][valid_rows]
        idx = starts[:, None] + np.arange(64)
        out[idx.reshape(-1)] = hexes[valid_rows].reshape(-1)
    return out, out_offsets.astype(np.int32)
