"""Numpy-only helpers shared by the host and device mask paths (no jax)."""

from __future__ import annotations

from typing import Optional

import numpy as np


_HEX_LUT = np.frombuffer(b"0123456789abcdef", dtype=np.uint8)


def digests_to_hex(words: np.ndarray) -> np.ndarray:
    """(N, 8) uint32 big-endian digest words -> (N, 64) ascii-hex uint8.

    Host-side hex encoding for the device mask path: the device returns raw
    digest words (32 bytes/row) instead of hex (64 bytes/row), halving the
    D2H volume; this LUT expansion is a table lookup over 32 bytes/row —
    microseconds per 131k-row batch, nothing vs the transfer it saves.
    """
    n = words.shape[0]
    b = np.ascontiguousarray(words.astype(">u4")).view(np.uint8)
    b = b.reshape(n, 32)
    out = np.empty((n, 64), dtype=np.uint8)
    out[:, 0::2] = _HEX_LUT[b >> 4]
    out[:, 1::2] = _HEX_LUT[b & 0xF]
    return out


def hex_to_varwidth(hexes: np.ndarray, validity: Optional[np.ndarray]
                    ) -> tuple[np.ndarray, np.ndarray]:
    """(N, 64) hex digest matrix -> flat var-width column bytes+offsets.

    Invalid rows become empty strings (validity is preserved separately by
    the caller's output Column).  Caller contract: hexes is freshly owned
    (device transfer / kernel output) — the all-valid fast path returns a
    reshape VIEW instead of copying 64 bytes/row again.
    """
    n = hexes.shape[0]
    if validity is None:
        out_offsets = np.arange(n + 1, dtype=np.int64) * 64
        if out_offsets[-1] > 2**31 - 1:
            raise ValueError("hashed column exceeds 2GiB")
        flat = np.ascontiguousarray(hexes).reshape(-1)
        return flat, out_offsets.astype(np.int32)
    if validity.all():
        return hex_to_varwidth(hexes, None)
    lens = np.where(validity, 64, 0).astype(np.int64)
    out_offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=out_offsets[1:])
    if out_offsets[-1] > 2**31 - 1:
        raise ValueError("hashed column exceeds 2GiB")
    # invalid rows are zero-length, so the flat output is exactly the
    # valid rows' digests in row order — one contiguous gather, no
    # per-byte scatter
    valid_rows = np.nonzero(validity)[0]
    if len(valid_rows):
        out = np.ascontiguousarray(hexes[valid_rows]).reshape(-1)
    else:
        out = np.zeros(0, dtype=np.uint8)
    return out, out_offsets.astype(np.int32)
