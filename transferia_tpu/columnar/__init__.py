"""Columnar data plane: the TPU currency.

The reference engine moves row-oriented ChangeItems everywhere; this
framework's equivalent of its hand-optimized Go hot loops (generic parser,
serializer batch loops, CH marshaller — SURVEY.md §3.5 "hot loops") is the
`ColumnBatch`: an Arrow-style columnar block whose fixed-width columns are
device-ready numpy/jax arrays and whose variable-width columns are
(uint8 bytes, int32 offsets) pairs.  Pivot/unpivot at the row-oriented edges
only.
"""

from transferia_tpu.columnar.batch import Column, ColumnBatch, bucket_rows

__all__ = ["Column", "ColumnBatch", "bucket_rows"]
