"""ColumnBatch: Arrow-style columnar block shared by host and device.

Design notes (TPU-first):
- Fixed-width canonical types map 1:1 to numpy dtypes (`CanonicalType.np_dtype`)
  so a column ships to the device with zero copies beyond the HBM transfer.
- Variable-width types (string/utf8/any/decimal) are a flat uint8 byte buffer
  plus (n_rows+1) int32 offsets — the layout Pallas string kernels consume.
- NULLs are a boolean validity array (True = valid), matching Arrow semantics.
- Row-count bucketing (`bucket_rows`) pads batches to power-of-two-ish sizes
  so XLA compiles once per (schema fingerprint, bucket) instead of once per
  batch — the shape-static analogue of the reference's schema-hash keyed
  transformer plan cache (pkg/transformer/transformation.go:47-60).

Reference parity: this replaces the []ChangeItem batch of
pkg/abstract/changeitem as the bulk currency; ChangeItems remain the row view.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from transferia_tpu.abstract.change_item import ChangeItem, OldKeys
from transferia_tpu.abstract.kinds import CODE_KINDS, KIND_CODES, Kind
from transferia_tpu.abstract.schema import (
    CanonicalType,
    ColSchema,
    TableID,
    TableSchema,
)
from transferia_tpu.runtime import lockwatch

_BUCKETS = (256, 1024, 4096, 16384, 65536, 262144, 1048576)
_INT32_MAX = 2**31 - 1


def _offsets_from_lengths(lengths) -> np.ndarray:
    """Build int32 offsets from per-row byte lengths, guarding overflow.

    Device kernels index with int32; a single batch's var-width column must
    stay under 2 GiB (the bufferer flushes far earlier) — fail loudly rather
    than let numpy wrap the cumsum.
    """
    off64 = np.zeros(len(lengths) + 1, dtype=np.int64)
    if len(lengths):
        np.cumsum(lengths, dtype=np.int64, out=off64[1:])
    if off64[-1] > _INT32_MAX:
        raise ValueError(
            f"variable-width column exceeds 2GiB in one batch "
            f"({int(off64[-1])} bytes); split the batch"
        )
    return off64.astype(np.int32)


def _gather_varwidth(data: np.ndarray, offsets: np.ndarray,
                     indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Gather var-width rows by index: C++ fast path, numpy fallback.

    Shared by Column.take and DictEnc.materialize (a dict materialization
    IS a gather of the pool by the code array).  The native form is two
    passes — lengths fold into offsets (gather_var_offsets), then one
    memcpy loop (gather_var_bytes) — replacing the numpy lens gather +
    int64 cumsum that profiled as ~5.5% of the snapshot wall."""
    from transferia_tpu.native import lib as _native_lib

    n = len(indices)
    cdll = _native_lib()
    if cdll is not None and n:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        # the C loops are unchecked: out-of-range / negative indices
        # must keep numpy's semantics (raise / wrap) instead of
        # reading stray memory — same guard as gather_fixed
        if int(idx.min()) < 0 or int(idx.max()) >= len(offsets) - 1:
            cdll = None
    if cdll is not None and hasattr(cdll, "gather_var_offsets") and n:
        src_off = np.ascontiguousarray(offsets, dtype=np.int32)
        out_offsets = np.empty(n + 1, dtype=np.int32)
        total = cdll.gather_var_offsets(src_off, idx, n, out_offsets)
        if total > _INT32_MAX:
            raise ValueError(
                f"variable-width column exceeds 2GiB in one batch "
                f"({int(total)} bytes); split the batch"
            )
        out = np.empty(int(total), dtype=np.uint8)
        if total:
            cdll.gather_var_bytes(np.ascontiguousarray(data), src_off,
                                  idx, n, out_offsets, out)
        return out, out_offsets
    lens = (offsets[1:] - offsets[:-1])[indices].astype(np.int64)
    new_offsets = _offsets_from_lengths(lens)  # guards the 2GiB limit
    total = int(new_offsets[-1])
    if cdll is not None and total:
        # prebuilt .so without the two-pass symbols: the one-pass gather
        # still beats the numpy scatter chain (indices validated above)
        out = np.empty(total, dtype=np.uint8)
        out_offsets = np.empty(n + 1, dtype=np.int32)
        cdll.gather_varwidth(
            np.ascontiguousarray(data),
            np.ascontiguousarray(offsets, dtype=np.int32),
            idx, n, out, out_offsets,
        )
        return out, out_offsets
    starts = offsets[:-1][indices].astype(np.int64)
    intra = np.arange(total, dtype=np.int64) - np.repeat(
        new_offsets[:-1].astype(np.int64), lens)
    src = np.repeat(starts, lens) + intra
    out = data[src] if total else np.zeros(0, dtype=np.uint8)
    return out, new_offsets


def _contiguous_span(indices) -> Optional[tuple[int, int]]:
    """[lo, hi) when indices is exactly lo, lo+1, ..., hi-1; else None.

    The O(n) monotonicity check only runs after the O(1) endpoints test
    matches, so random gathers pay two scalar reads."""
    n = len(indices)
    if n == 0 or not isinstance(indices, np.ndarray) \
            or indices.dtype.kind not in "iu":
        return None
    lo = int(indices[0])
    hi = int(indices[-1]) + 1
    if hi - lo != n or lo < 0:
        return None
    if n > 1 and not bool((np.diff(indices) == 1).all()):
        return None
    return lo, hi


def _gather_fixed(data: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """Fixed-width gather: native width-specialized loop, numpy fallback."""
    from transferia_tpu.native import lib as _native_lib

    n = len(indices)
    cdll = _native_lib()
    width = data.dtype.itemsize
    if (cdll is None or not hasattr(cdll, "gather_fixed") or n == 0
            or not data.flags.c_contiguous
            or not isinstance(indices, np.ndarray)
            or indices.dtype.kind not in "iu"):
        return data[indices]
    # the C loop is unchecked: out-of-range / negative indices must keep
    # numpy's semantics (raise / wrap) instead of reading stray memory
    if int(indices.min()) < 0 or int(indices.max()) >= len(data):
        return data[indices]
    out = np.empty(n, dtype=data.dtype)
    cdll.gather_fixed(
        data.view(np.uint8), np.ascontiguousarray(indices, dtype=np.int64),
        n, width, out.view(np.uint8))
    return out


def bucket_rows(n: int) -> int:
    """Smallest standard bucket >= n (caps XLA recompiles)."""
    for b in _BUCKETS:
        if n <= b:
            return b
    # beyond the largest bucket: round up to a multiple of it
    top = _BUCKETS[-1]
    return ((n + top - 1) // top) * top


class DictPool:
    """The value pool of a dictionary encoding, shareable across batches.

    values_data/values_offsets: the pool as flat uint8 bytes + (k+1) int32.
    null_code: index of the designated empty-bytes sentinel entry, if one
    was appended at adoption (nulls materialize as empty bytes — the
    canonical null representation of the flat path).
    memos: per-pool computation cache, e.g. the HMAC'd hex pool keyed by
    mask key — a pool shared by many batches is hashed once.
    """

    __slots__ = ("values_data", "values_offsets", "null_code", "_memos",
                 "_keepalive")

    def __init__(self, values_data: np.ndarray, values_offsets: np.ndarray,
                 null_code: Optional[int] = None, keepalive=None):
        self.values_data = values_data
        self.values_offsets = values_offsets
        self.null_code = null_code
        self._memos: dict = {}
        self._keepalive = keepalive  # pins adopted arrow buffers

    @property
    def n_values(self) -> int:
        return len(self.values_offsets) - 1

    def nbytes(self) -> int:
        return self.values_data.nbytes + self.values_offsets.nbytes

    def value_bytes(self, code: int) -> bytes:
        return bytes(self.values_data[
            self.values_offsets[code]:self.values_offsets[code + 1]])

    def memo_get(self, key):
        return self._memos.get(key)

    def memo_set(self, key, value) -> None:
        self._memos[key] = value


# Adopted arrow dictionaries keyed by buffer identity: batch slices of one
# row group share the same dict buffers, so they share one DictPool (and
# its memos).  Entries pin the arrow pool array, which is what makes the
# address a valid identity.  Bounded FIFO; lock guards the loader's
# concurrent part threads.
_POOL_CACHE: dict = {}
_POOL_CACHE_MAX = 64
_POOL_CACHE_LOCK = lockwatch.named_lock("pool.intern")

# Content-interned pools (intern_pool): every producer that re-creates a
# value pool with identical bytes — the native parquet reader decoding
# one file's per-row-group dict pages, the sample source re-emitting its
# preset pools per batch — converges on ONE DictPool object, so the
# pool-keyed memos (hexed HMAC pool, rowhash accumulators, device digest
# matrices, arrow wrapping) amortize across row groups AND parts.
_INTERN_CACHE: dict = {}   # key -> (content digest, DictPool)
_INTERN_CACHE_MAX = 128


def pool_sharing_enabled() -> bool:
    """TRANSFERIA_TPU_POOL_SHARING=0 disables content interning (each
    producer keeps private pools — the pre-sharing wire)."""
    from transferia_tpu.runtime import knobs

    return knobs.env_str("TRANSFERIA_TPU_POOL_SHARING", "1") != "0"


def _pool_digest(values_data: np.ndarray, values_offsets: np.ndarray,
                 null_code: Optional[int]) -> bytes:
    import hashlib

    h = hashlib.sha1()
    h.update(np.ascontiguousarray(values_data).tobytes())
    h.update(np.ascontiguousarray(values_offsets).tobytes())
    h.update(str(null_code).encode())
    return h.digest()


def intern_pool(key, values_data: np.ndarray, values_offsets: np.ndarray,
                null_code: Optional[int] = None, keepalive=None,
                finalize=None) -> "DictPool":
    """A canonical DictPool per (key, content).

    `key` scopes the cache entry (e.g. ``(path, column)`` for a parquet
    file, ``("sample", preset, column)`` for the generator); a candidate
    whose content digest matches the cached entry returns the CACHED
    pool — object identity is what downstream memo/fast paths key on.
    A changed digest under the same key replaces the entry (rewritten
    file, new dictionary page).

    `finalize(values_data, values_offsets)` runs only when the candidate
    is actually kept (a hit discards the candidate buffers), letting the
    caller defer a pin-avoiding copy of a decode-buffer view until it is
    known the buffers will live on.  Returns the buffers to store.
    """
    from transferia_tpu.stats.trace import TELEMETRY

    if not pool_sharing_enabled():
        if finalize is not None:
            values_data, values_offsets = finalize(values_data,
                                                   values_offsets)
        return DictPool(values_data, values_offsets, null_code, keepalive)
    digest = _pool_digest(values_data, values_offsets, null_code)
    if key is None:
        key = digest  # pure content identity (no producer scope)
    with _POOL_CACHE_LOCK:
        hit = _INTERN_CACHE.get(key)
        if hit is not None and hit[0] == digest:
            TELEMETRY.record_pool_share_hit()
            return hit[1]
    if finalize is not None:
        values_data, values_offsets = finalize(values_data, values_offsets)
    pool = DictPool(values_data, values_offsets, null_code, keepalive)
    with _POOL_CACHE_LOCK:
        hit = _INTERN_CACHE.get(key)
        if hit is not None and hit[0] == digest:
            TELEMETRY.record_pool_share_hit()
            return hit[1]
        while len(_INTERN_CACHE) >= _INTERN_CACHE_MAX:
            _INTERN_CACHE.pop(next(iter(_INTERN_CACHE)), None)
        _INTERN_CACHE[key] = (digest, pool)
    return pool


def intern_peek(key) -> Optional["DictPool"]:
    """The currently-interned pool under `key` (None when absent) —
    lets a producer try an order-insensitive CODE REMAP onto the
    canonical pool before falling back to exact-content interning."""
    with _POOL_CACHE_LOCK:
        hit = _INTERN_CACHE.get(key)
    return hit[1] if hit is not None else None


def reset_intern_cache() -> None:
    with _POOL_CACHE_LOCK:
        _INTERN_CACHE.clear()


def remap_codes_onto(canon: "DictPool", data: np.ndarray,
                     offsets: np.ndarray,
                     n_pool: int) -> Optional[np.ndarray]:
    """Remap table from a candidate pool's codes onto the canonical
    pool's, or None when the candidate carries a value outside the
    canonical pool (a genuinely new dictionary — the caller re-interns
    instead).  Shared by every order-insensitive pool-sharing consumer
    (parquet dict pages across row groups, arrow dictionaries across
    IPC/Flight streams) so the verification discipline can't fork.

    The canonical pool's bytes→code index memoizes on the pool; the
    null SENTINEL slot is excluded from it so a real empty-bytes value
    can never alias onto the sentinel (the mask plane empties the
    sentinel's hex slot — aliasing would silently unmask '' rows).
    The returned table has n_pool+1 entries: the candidate's own
    sentinel (code n_pool) maps to the canonical sentinel."""
    if canon.null_code is None:
        return None
    if n_pool == 0:
        return np.array([canon.null_code], dtype=np.int32)
    from transferia_tpu.ops.rowhash import pool_accumulators

    memo = canon.memo_get(("remap_keys",))
    if memo is None:
        a1, a2 = pool_accumulators(canon)
        ckeys = (a1.astype(np.uint64) << np.uint64(32)) \
            | a2.astype(np.uint64)
        # poison the sentinel's key: a real empty-bytes value must
        # never alias onto the null sentinel; the exact verification
        # below backstops any residual collision with the poison value
        ckeys = ckeys.copy()
        ckeys[canon.null_code] = np.uint64(0xFFFFFFFFFFFFFFFF)
        sorter = np.argsort(ckeys, kind="stable")
        memo = (ckeys[sorter], sorter)
        canon.memo_set(("remap_keys",), memo)
    sorted_keys, sorter = memo
    pool_bytes = int(offsets[n_pool])
    cand_pool = DictPool(data[:pool_bytes],
                         np.ascontiguousarray(offsets[:n_pool + 1],
                                              dtype=np.int32))
    p1, p2 = pool_accumulators(cand_pool)
    pkeys = (p1.astype(np.uint64) << np.uint64(32)) \
        | p2.astype(np.uint64)
    pos = np.searchsorted(sorted_keys, pkeys)
    cand = sorter[np.minimum(pos, canon.n_values - 1)]
    # the keys are 64-bit content hashes — verify the implied mapping
    # byte-EXACTLY (one native gather + two memcmps); any miss (value
    # outside the pool, or a hash collision) rejects the remap and the
    # caller re-interns, so a wrong code can never reach a consumer
    g_data, g_off = _gather_varwidth(
        canon.values_data,
        np.ascontiguousarray(canon.values_offsets, dtype=np.int32),
        cand.astype(np.int64))
    if not (np.array_equal(g_off, offsets[:n_pool + 1])
            and np.array_equal(g_data, data[:pool_bytes])):
        return None
    return np.append(cand.astype(np.int32),
                     np.int32(canon.null_code))


class DictEnc:
    """Dictionary encoding of a variable-width column (ClickHouse
    LowCardinality / Arrow DictionaryArray analogue).

    indices: (n,) int32 codes into the shared value pool.

    Columns carrying a DictEnc stay dictionary-encoded end-to-end: parquet
    dict pages adopt zero-copy on read (from_arrow), filters gather int32
    codes instead of strings, the HMAC mask hashes the pool once instead
    of every row, and to_arrow re-emits a DictionaryArray so parquet sinks
    write dict pages back.  Flat (data, offsets) materialize lazily the
    first time a consumer asks — correctness never depends on a consumer
    knowing about the encoding.
    """

    __slots__ = ("indices", "pool")

    def __init__(self, indices: np.ndarray,
                 values_data: Optional[np.ndarray] = None,
                 values_offsets: Optional[np.ndarray] = None,
                 pool: Optional[DictPool] = None):
        self.indices = indices
        self.pool = pool if pool is not None else DictPool(
            values_data, values_offsets)

    # -- pool passthroughs ---------------------------------------------------
    @property
    def values_data(self) -> np.ndarray:
        return self.pool.values_data

    @property
    def values_offsets(self) -> np.ndarray:
        return self.pool.values_offsets

    @property
    def n_values(self) -> int:
        return self.pool.n_values

    def value_bytes(self, code: int) -> bytes:
        return self.pool.value_bytes(code)

    def nbytes(self) -> int:
        return self.indices.nbytes + self.pool.nbytes()

    def materialize(self) -> tuple[np.ndarray, np.ndarray]:
        """Flatten to (data, offsets): a gather of the pool by codes."""
        return _gather_varwidth(self.pool.values_data,
                                self.pool.values_offsets,
                                self.indices.astype(np.int64))


class Column:
    """One column of a batch.

    data: fixed-width -> (n,) array of ctype.np_dtype
          variable-width -> (total_bytes,) uint8 buffer
    offsets: (n+1,) int32 — only for variable-width columns
    validity: (n,) bool (True = present) or None meaning all-valid
    dict_enc: optional dictionary encoding (var-width only); when set with
          data=None the flat buffers materialize lazily on first access
    """

    __slots__ = ("name", "ctype", "_data", "_offsets", "validity",
                 "dict_enc")

    def __init__(self, name: str, ctype: CanonicalType,
                 data: Optional[np.ndarray] = None,
                 offsets: Optional[np.ndarray] = None,
                 validity: Optional[np.ndarray] = None,
                 dict_enc: Optional[DictEnc] = None):
        self.name = name
        self.ctype = ctype
        self._data = data
        self._offsets = offsets
        self.validity = validity
        self.dict_enc = dict_enc
        if ctype.is_variable_width:
            if offsets is None and dict_enc is None:
                raise ValueError(
                    f"column {name}: var-width requires offsets")
        elif data is None:
            raise ValueError(f"column {name}: fixed-width requires data")

    def _materialize(self) -> None:
        if self._data is None:
            # counted: every flatten of a dict column is a defeat of the
            # code-native pipeline — the dict_flat_materializations /
            # lazy_dict_preserved pair makes regressions visible
            from transferia_tpu.stats.trace import TELEMETRY

            TELEMETRY.record_dict_materialize()
            self._data, self._offsets = self.dict_enc.materialize()

    @property
    def data(self) -> np.ndarray:
        if self._data is None:
            self._materialize()
        return self._data

    @data.setter
    def data(self, v: np.ndarray) -> None:
        self._data = v

    @property
    def offsets(self) -> Optional[np.ndarray]:
        if self._offsets is None and self.dict_enc is not None:
            self._materialize()
        return self._offsets

    @offsets.setter
    def offsets(self, v: Optional[np.ndarray]) -> None:
        self._offsets = v

    @property
    def is_lazy_dict(self) -> bool:
        """True while dictionary-encoded with no flat copy materialized —
        the state dict-aware fast paths (mask, to_arrow, take) look for."""
        return self.dict_enc is not None and self._data is None

    @property
    def n_rows(self) -> int:
        if self.dict_enc is not None and self._offsets is None:
            return len(self.dict_enc.indices)
        if self._offsets is not None:
            return len(self._offsets) - 1
        return len(self._data)

    def nbytes(self) -> int:
        if self.is_lazy_dict:
            n = self.dict_enc.nbytes()
        else:
            n = self._data.nbytes
            if self._offsets is not None:
                n += self._offsets.nbytes
        if self.validity is not None:
            n += self.validity.nbytes
        return n

    def is_valid(self, i: int) -> bool:
        return self.validity is None or bool(self.validity[i])

    # -- row access ---------------------------------------------------------
    def value(self, i: int) -> Any:
        """Python value at row i (None when invalid)."""
        if not self.is_valid(i):
            return None
        if self.is_lazy_dict:
            raw = self.dict_enc.value_bytes(int(self.dict_enc.indices[i]))
            return _decode_varwidth(self.ctype, raw)
        if self.offsets is not None:
            raw = bytes(self.data[self.offsets[i]:self.offsets[i + 1]])
            return _decode_varwidth(self.ctype, raw)
        v = self.data[i]
        if self.ctype == CanonicalType.BOOLEAN:
            return bool(v)
        if self.ctype.is_integer or self.ctype in (
            CanonicalType.DATE, CanonicalType.DATETIME,
            CanonicalType.TIMESTAMP, CanonicalType.INTERVAL,
        ):
            return int(v)
        return float(v)

    def to_pylist(self) -> list[Any]:
        return [self.value(i) for i in range(self.n_rows)]

    def renamed(self, name: str) -> "Column":
        """Copy under a new name (laziness and buffers preserved)."""
        return Column(name, self.ctype, self._data, self._offsets,
                      self.validity, self.dict_enc)

    # -- functional ops -----------------------------------------------------
    def take(self, indices: np.ndarray) -> "Column":
        """Gather rows (host-side; device path uses ops.strings.take_bytes).

        Fast paths: a contiguous ascending index range (what slice() and
        prefix/suffix filters produce) returns buffer VIEWS — no copy at
        all; non-contiguous fixed-width gathers route through the native
        width-specialized hostops loop when the library is present."""
        span = _contiguous_span(indices)
        if span is not None and span[1] <= self.n_rows:
            # (out-of-range spans fall through so the gather raises the
            # same IndexError numpy always did instead of clamping)
            return self._take_contiguous(*span)
        validity = (_gather_fixed(self.validity, indices)
                    if self.validity is not None else None)
        if self.is_lazy_dict:
            # dictionary stays shared; only the int32 codes gather —
            # through the native width-specialized loop, so a filter on
            # a DictEnc column never materializes the pool and never
            # pays numpy's generic fancy-indexing path
            enc = self.dict_enc
            return Column(
                self.name, self.ctype, validity=validity,
                dict_enc=DictEnc(_gather_fixed(enc.indices, indices),
                                 pool=enc.pool))
        if self.offsets is None:
            return Column(self.name, self.ctype,
                          _gather_fixed(self.data, indices), None, validity)
        out, new_offsets = _gather_varwidth(
            self.data, self.offsets,
            np.ascontiguousarray(indices, dtype=np.int64))
        return Column(self.name, self.ctype, out, new_offsets, validity)

    def _take_contiguous(self, lo: int, hi: int) -> "Column":
        """take() of [lo, hi) as views over the existing buffers."""
        validity = self.validity[lo:hi] if self.validity is not None else None
        if self.is_lazy_dict:
            enc = self.dict_enc
            return Column(
                self.name, self.ctype, validity=validity,
                dict_enc=DictEnc(enc.indices[lo:hi], pool=enc.pool))
        if self.offsets is None:
            return Column(self.name, self.ctype, self.data[lo:hi], None,
                          validity)
        off = self.offsets[lo:hi + 1]
        if off[0] == 0:
            # prefix range: offsets AND data are pure views
            return Column(self.name, self.ctype,
                          self.data[:off[-1]] if len(off) else self.data,
                          off, validity)
        # mid-range: data stays a view; only the small offsets rebase
        return Column(self.name, self.ctype,
                      self.data[off[0]:off[-1]],
                      off - off[0], validity)

    def filter(self, mask: np.ndarray) -> "Column":
        return self.take(np.nonzero(mask)[0])

    @staticmethod
    def from_pylist(name: str, ctype: CanonicalType,
                    values: Sequence[Any]) -> "Column":
        n = len(values)
        validity = np.fromiter(
            (v is not None for v in values), dtype=np.bool_, count=n
        )
        all_valid = bool(validity.all()) if n else True
        if ctype.is_variable_width:
            bufs = [
                _encode_varwidth(ctype, v) if v is not None else b""
                for v in values
            ]
            offsets = _offsets_from_lengths([len(b) for b in bufs])
            data = np.frombuffer(b"".join(bufs), dtype=np.uint8).copy() \
                if bufs else np.zeros(0, dtype=np.uint8)
            return Column(name, ctype, data, offsets,
                          None if all_valid else validity)
        dt = ctype.np_dtype
        data = np.zeros(n, dtype=dt)
        for i, v in enumerate(values):
            if v is not None:
                data[i] = v
        return Column(name, ctype, data, None, None if all_valid else validity)


def _encode_varwidth(ctype: CanonicalType, v: Any) -> bytes:
    if ctype == CanonicalType.STRING:
        if isinstance(v, bytes):
            return v
        return str(v).encode()
    if ctype in (CanonicalType.UTF8, CanonicalType.DECIMAL):
        return v.encode() if isinstance(v, str) else str(v).encode()
    # ANY: canonical JSON bytes
    if isinstance(v, bytes):
        return v
    return json.dumps(v, separators=(",", ":"), default=str).encode()


def _decode_varwidth(ctype: CanonicalType, raw: bytes) -> Any:
    if ctype == CanonicalType.STRING:
        return raw
    if ctype in (CanonicalType.UTF8, CanonicalType.DECIMAL):
        return raw.decode("utf-8", errors="replace")
    try:
        return json.loads(raw) if raw else None
    except (ValueError, UnicodeDecodeError):
        return raw


class ColumnBatch:
    """A columnar block of rows for one table.

    kinds is None for pure-insert (snapshot) blocks; otherwise an int8 array
    of KIND_CODES for mixed CDC blocks.  lsns/commit_times are optional
    per-row metadata carried through the pipeline for checkpointing.
    old_keys/txn_ids are host-side per-row sidecars (never shipped to the
    device) preserving CDC row identity for updates/deletes across the pivot.
    """

    __slots__ = ("table_id", "schema", "columns", "kinds", "lsns",
                 "commit_times", "part_id", "read_bytes", "old_keys",
                 "txn_ids")

    def __init__(self, table_id: TableID, schema: TableSchema,
                 columns: dict[str, Column],
                 kinds: Optional[np.ndarray] = None,
                 lsns: Optional[np.ndarray] = None,
                 commit_times: Optional[np.ndarray] = None,
                 part_id: str = "", read_bytes: int = 0,
                 old_keys: Optional[list[OldKeys]] = None,
                 txn_ids: Optional[list[str]] = None):
        self.table_id = table_id
        self.schema = schema
        self.columns = columns
        self.kinds = kinds
        self.lsns = lsns
        self.commit_times = commit_times
        self.part_id = part_id
        self.read_bytes = read_bytes
        self.old_keys = old_keys
        self.txn_ids = txn_ids
        self._check()

    def _check(self):
        n = self.n_rows
        for c in self.columns.values():
            if c.n_rows != n:
                raise ValueError(
                    f"ragged batch: column {c.name} has {c.n_rows} rows, "
                    f"expected {n}"
                )

    @property
    def n_rows(self) -> int:
        if not self.columns:
            return 0 if self.kinds is None else len(self.kinds)
        return next(iter(self.columns.values())).n_rows

    def __len__(self) -> int:
        return self.n_rows

    def nbytes(self) -> int:
        return sum(c.nbytes() for c in self.columns.values())

    def kind_at(self, i: int) -> Kind:
        if self.kinds is None:
            return Kind.INSERT
        return CODE_KINDS[int(self.kinds[i])]

    def column(self, name: str) -> Column:
        return self.columns[name]

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_pydict(table_id: TableID, schema: TableSchema,
                    data: dict[str, Sequence[Any]], **kw) -> "ColumnBatch":
        cols = {}
        for cs in schema:
            if cs.name in data:
                cols[cs.name] = Column.from_pylist(
                    cs.name, cs.data_type, data[cs.name]
                )
        return ColumnBatch(table_id, schema, cols, **kw)

    @staticmethod
    def from_rows(items: Sequence[ChangeItem]) -> "ColumnBatch":
        """Pivot a uniform-table row batch into a columnar block.

        All items must share table_id and table_schema; mixed kinds are
        captured in the kinds array.  This is the host-side pivot the
        BASELINE.json north star describes (ChangeItem rows -> column
        buffers).
        """
        from transferia_tpu.stats import trace

        sp = trace.span("pivot")
        if sp:
            sp.add(rows=len(items), direction="rows_to_columns")
        with sp:
            return ColumnBatch._from_rows_impl(items)

    @staticmethod
    def _from_rows_impl(items: Sequence[ChangeItem]) -> "ColumnBatch":
        if not items:
            raise ValueError("from_rows: empty batch")
        first = items[0]
        if first.table_schema is None:
            raise ValueError("from_rows: items must carry table_schema")
        schema = first.table_schema
        tid = first.table_id
        n = len(items)
        per_col: dict[str, list[Any]] = {c.name: [None] * n for c in schema}
        kinds = np.zeros(n, dtype=np.int8)
        lsns = np.zeros(n, dtype=np.int64)
        commit_times = np.zeros(n, dtype=np.int64)
        mixed = False
        old_keys: Optional[list[OldKeys]] = None
        txn_ids: Optional[list[str]] = None
        for i, it in enumerate(items):
            if it.table_id != tid:
                raise ValueError("from_rows: mixed tables in batch")
            if it.table_schema is not schema and it.table_schema != schema:
                raise ValueError(
                    "from_rows: mixed table schemas in batch (schema changed "
                    "mid-stream?) — split the batch on schema boundaries"
                )
            code = KIND_CODES.get(it.kind)
            if code is None:
                raise ValueError(f"from_rows: non-row kind {it.kind}")
            kinds[i] = code
            mixed = mixed or code != 0
            lsns[i] = it.lsn
            commit_times[i] = it.commit_time_ns
            if it.old_keys.key_names:
                if old_keys is None:
                    old_keys = [OldKeys()] * n
                old_keys[i] = it.old_keys
            if it.txn_id:
                if txn_ids is None:
                    txn_ids = [""] * n
                txn_ids[i] = it.txn_id
            for name, value in zip(it.column_names, it.column_values):
                if name in per_col:
                    per_col[name][i] = value
        cols = {
            c.name: Column.from_pylist(c.name, c.data_type, per_col[c.name])
            for c in schema
        }
        return ColumnBatch(
            tid, schema, cols,
            kinds=kinds if mixed else None,
            lsns=lsns if lsns.any() else None,
            commit_times=commit_times if commit_times.any() else None,
            part_id=first.part_id,
            read_bytes=sum(it.size_bytes for it in items),
            old_keys=old_keys,
            txn_ids=txn_ids,
        )

    # -- row view -----------------------------------------------------------
    def to_rows(self) -> list[ChangeItem]:
        """Unpivot to ChangeItems (row-oriented edges only)."""
        from transferia_tpu.stats import trace

        sp = trace.span("pivot")
        if sp:
            sp.add(rows=self.n_rows, direction="columns_to_rows")
        with sp:
            return self._to_rows_impl()

    def _to_rows_impl(self) -> list[ChangeItem]:
        names = tuple(self.columns.keys())
        cols = list(self.columns.values())
        out = []
        for i in range(self.n_rows):
            out.append(ChangeItem(
                kind=self.kind_at(i),
                schema=self.table_id.namespace,
                table=self.table_id.name,
                column_names=names,
                column_values=tuple(c.value(i) for c in cols),
                table_schema=self.schema,
                lsn=int(self.lsns[i]) if self.lsns is not None else 0,
                commit_time_ns=int(self.commit_times[i])
                if self.commit_times is not None else 0,
                part_id=self.part_id,
                old_keys=self.old_keys[i] if self.old_keys is not None
                else OldKeys(),
                txn_id=self.txn_ids[i] if self.txn_ids is not None else "",
            ))
        return out

    def to_pydict(self) -> dict[str, list[Any]]:
        return {name: c.to_pylist() for name, c in self.columns.items()}

    # -- functional ops -----------------------------------------------------
    def with_columns(self, columns: dict[str, Column],
                     schema: Optional[TableSchema] = None) -> "ColumnBatch":
        return ColumnBatch(
            self.table_id, schema or self.schema, columns,
            kinds=self.kinds, lsns=self.lsns, commit_times=self.commit_times,
            part_id=self.part_id, read_bytes=self.read_bytes,
            old_keys=self.old_keys, txn_ids=self.txn_ids,
        )

    def rename_table(self, table_id: TableID) -> "ColumnBatch":
        return ColumnBatch(
            table_id, self.schema, self.columns,
            kinds=self.kinds, lsns=self.lsns, commit_times=self.commit_times,
            part_id=self.part_id, read_bytes=self.read_bytes,
            old_keys=self.old_keys, txn_ids=self.txn_ids,
        )

    def project(self, names: Sequence[str]) -> "ColumnBatch":
        cols = {n: self.columns[n] for n in names if n in self.columns}
        return self.with_columns(cols, self.schema.project(list(cols)))

    def filter(self, mask: np.ndarray) -> "ColumnBatch":
        idx = np.nonzero(np.asarray(mask))[0]
        return self.take(idx)

    def take(self, indices: np.ndarray) -> "ColumnBatch":
        cols = {n: c.take(indices) for n, c in self.columns.items()}
        return ColumnBatch(
            self.table_id, self.schema, cols,
            kinds=self.kinds[indices] if self.kinds is not None else None,
            lsns=self.lsns[indices] if self.lsns is not None else None,
            commit_times=self.commit_times[indices]
            if self.commit_times is not None else None,
            part_id=self.part_id, read_bytes=self.read_bytes,
            old_keys=[self.old_keys[int(i)] for i in indices]
            if self.old_keys is not None else None,
            txn_ids=[self.txn_ids[int(i)] for i in indices]
            if self.txn_ids is not None else None,
        )

    def slice(self, start: int, stop: int) -> "ColumnBatch":
        return self.take(np.arange(start, min(stop, self.n_rows)))

    @staticmethod
    def concat(batches: Sequence["ColumnBatch"]) -> "ColumnBatch":
        if not batches:
            raise ValueError("concat: empty")
        if len(batches) == 1:
            return batches[0]
        first = batches[0]
        cols = {}
        for name, c0 in first.columns.items():
            parts = [b.columns[name] for b in batches]
            validity = None
            if any(p.validity is not None for p in parts):
                validity = np.concatenate([
                    p.validity if p.validity is not None
                    else np.ones(p.n_rows, dtype=np.bool_)
                    for p in parts
                ])
            if (c0.is_lazy_dict and all(p.is_lazy_dict for p in parts)
                    and all(p.dict_enc.pool is c0.dict_enc.pool
                            for p in parts)):
                # bufferer flushes concat batch slices of one row group:
                # they share one DictPool, so the concat is a pure int32
                # code concat and the column stays encoded end-to-end
                # (touching .offsets below would flatten every part)
                from transferia_tpu.stats.trace import TELEMETRY

                TELEMETRY.record_dict_preserved()
                cols[name] = Column(
                    name, c0.ctype, validity=validity,
                    dict_enc=DictEnc(
                        np.concatenate([p.dict_enc.indices
                                        for p in parts]),
                        pool=c0.dict_enc.pool))
            elif c0.offsets is not None:
                data = np.concatenate([p.data for p in parts])
                lens = np.concatenate([
                    p.offsets[1:] - p.offsets[:-1] for p in parts
                ])
                offsets = _offsets_from_lengths(lens)
                cols[name] = Column(name, c0.ctype, data, offsets, validity)
            else:
                cols[name] = Column(
                    name, c0.ctype,
                    np.concatenate([p.data for p in parts]), None, validity,
                )
        def cat(attr, fill_dtype):
            arrs = [getattr(b, attr) for b in batches]
            if all(a is None for a in arrs):
                return None
            return np.concatenate([
                a if a is not None else np.zeros(b.n_rows, dtype=fill_dtype)
                for a, b in zip(arrs, batches)
            ])
        def cat_list(attr, fill):
            vals = [getattr(b, attr) for b in batches]
            if all(v is None for v in vals):
                return None
            out = []
            for v, b in zip(vals, batches):
                out.extend(v if v is not None else [fill] * b.n_rows)
            return out

        return ColumnBatch(
            first.table_id, first.schema, cols,
            kinds=cat("kinds", np.int8),
            lsns=cat("lsns", np.int64),
            commit_times=cat("commit_times", np.int64),
            part_id=first.part_id,
            read_bytes=sum(b.read_bytes for b in batches),
            old_keys=cat_list("old_keys", OldKeys()),
            txn_ids=cat_list("txn_ids", ""),
        )

    # -- arrow interop ------------------------------------------------------
    def to_arrow(self):
        """Convert to a pyarrow.RecordBatch (for parquet sinks etc.)."""
        import pyarrow as pa

        arrays, fields = [], []
        for cs in self.schema:
            c = self.columns.get(cs.name)
            if c is None:
                continue
            pa_type = _ARROW_TYPES[cs.data_type]
            if c.is_lazy_dict:
                # dictionary-encoded end-to-end: parquet sinks write dict
                # pages straight from the pool, no flat materialization;
                # the arrow pool array memoizes on the shared DictPool so
                # batch slices of one row group serialize it once
                from transferia_tpu.stats.trace import TELEMETRY

                TELEMETRY.record_dict_preserved()
                enc = c.dict_enc
                memo_key = ("arrow_pool", str(pa_type))
                pool = enc.pool.memo_get(memo_key)
                if pool is None:
                    pool = pa.Array.from_buffers(
                        pa_type, enc.n_values,
                        [None,
                         pa.py_buffer(
                             enc.values_offsets.astype(np.int32)
                             .tobytes()),
                         pa.py_buffer(enc.values_data.tobytes())])
                    enc.pool.memo_set(memo_key, pool)
                mask = (~c.validity) if c.validity is not None else None
                idx = pa.array(enc.indices, type=pa.int32(), mask=mask)
                arrays.append(pa.DictionaryArray.from_arrays(idx, pool))
                fields.append(pa.field(
                    cs.name, pa.dictionary(pa.int32(), pa_type),
                    nullable=not cs.required))
                continue
            if c.offsets is not None:
                buf_data = pa.py_buffer(c.data.tobytes())
                buf_off = pa.py_buffer(c.offsets.astype(np.int32).tobytes())
                mask_buf = _arrow_validity(c.validity, c.n_rows)
                arr = pa.Array.from_buffers(
                    pa_type, c.n_rows, [mask_buf, buf_off, buf_data]
                )
            else:
                arr = pa.array(c.data, type=pa_type,
                               mask=(~c.validity) if c.validity is not None else None)
            arrays.append(arr)
            fields.append(pa.field(cs.name, pa_type, nullable=not cs.required))
        return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))

    @staticmethod
    def from_arrow(rb, table_id: TableID,
                   schema: Optional[TableSchema] = None) -> "ColumnBatch":
        """Zero-ish-copy import from a pyarrow RecordBatch.

        Parquet/Arrow sources land here directly — no row pivot, per the
        north star ("never re-row the data between source and sink").
        """
        import pyarrow as pa
        import pyarrow.compute as pc

        if schema is None:
            schema = arrow_to_table_schema(rb.schema)
        cols: dict[str, Column] = {}
        for cs in schema:
            idx = rb.schema.get_field_index(cs.name)
            if idx < 0:
                continue
            arr = rb.column(idx)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            cols[cs.name] = _arrow_to_column(cs, arr)
        return ColumnBatch(table_id, schema, cols)


_ARROW_TYPES: dict[CanonicalType, Any] = {}


def _init_arrow_types():
    import pyarrow as pa

    _ARROW_TYPES.update({
        CanonicalType.INT8: pa.int8(),
        CanonicalType.INT16: pa.int16(),
        CanonicalType.INT32: pa.int32(),
        CanonicalType.INT64: pa.int64(),
        CanonicalType.UINT8: pa.uint8(),
        CanonicalType.UINT16: pa.uint16(),
        CanonicalType.UINT32: pa.uint32(),
        CanonicalType.UINT64: pa.uint64(),
        CanonicalType.FLOAT: pa.float32(),
        CanonicalType.DOUBLE: pa.float64(),
        CanonicalType.BOOLEAN: pa.bool_(),
        CanonicalType.DATE: pa.date32(),
        CanonicalType.DATETIME: pa.timestamp("s"),
        CanonicalType.TIMESTAMP: pa.timestamp("us"),
        CanonicalType.INTERVAL: pa.duration("us"),
        CanonicalType.STRING: pa.binary(),
        CanonicalType.UTF8: pa.string(),
        CanonicalType.ANY: pa.string(),
        CanonicalType.DECIMAL: pa.string(),
    })


try:  # pyarrow is present in the baked image; soft-fail for minimal envs
    _init_arrow_types()
except ImportError:  # pragma: no cover
    pass


def _arrow_validity(validity: Optional[np.ndarray], n: int):
    import pyarrow as pa

    if validity is None:
        return None
    bits = np.packbits(validity, bitorder="little")
    return pa.py_buffer(bits.tobytes())


def _adopt_string_buffers(arr) -> tuple[np.ndarray, np.ndarray]:
    """Zero-ish-copy adoption of a pyarrow string/binary array's buffers."""
    bufs = arr.buffers()
    off = np.frombuffer(bufs[1], dtype=np.int32,
                        count=len(arr) + 1 + arr.offset)
    data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
        else np.zeros(0, dtype=np.uint8)
    if arr.offset:
        off = off[arr.offset:]
    if off[0] != 0:
        data = data[off[0]:off[-1]]
        off = off - off[0]
    return np.ascontiguousarray(data), np.ascontiguousarray(off)


def _adopt_dict_pool(pool_arr, vt, pt, pa,
                     scope: Optional[tuple] = None,
                     ) -> tuple[DictPool, Optional[np.ndarray]]:
    """Adopt an arrow dictionary as a shared DictPool.

    Keyed by buffer identity: all batch slices of one row group reference
    the same dictionary buffers, so they get one DictPool object — and one
    set of memos (the HMAC mask hashes a shared pool exactly once).  The
    cache entry pins the arrow array, keeping the address a valid key.
    An empty-bytes sentinel entry is appended for null rows (null_code).

    When `scope` is given and pool sharing is on, a pool that carries the
    same VALUE SET as the scope's canonical pool in a different order is
    not re-interned: `remap_codes_onto` prices the permutation and the
    caller rewrites codes through the returned table — order-insensitive
    convergence, mirroring `_adopt_dict_page` on the parquet path.
    Returns (pool, remap) where remap is None when codes pass through.
    """
    # key on the ORIGINAL array's buffers: casting large_string allocates
    # fresh buffers each call, which would make the key never repeat
    orig = pool_arr
    bufs = orig.buffers()
    key = (
        bufs[2].address if bufs[2] is not None else 0,
        bufs[1].address if bufs[1] is not None else 0,
        len(orig), orig.offset, str(orig.type),
    )
    with _POOL_CACHE_LOCK:
        hit = _POOL_CACHE.get(key)
        if hit is not None:
            return hit[0], hit[2]
    if pt.is_large_string(vt) or pt.is_large_binary(vt):
        pool_arr = pool_arr.cast(
            pa.string() if pt.is_large_string(vt) else pa.binary())
    pool_data, pool_off = _adopt_string_buffers(pool_arr)
    # append the null sentinel (empty bytes) at index n_values
    pool_off = np.append(pool_off, pool_off[-1]).astype(np.int32)
    dpool, remap = None, None
    if scope is not None and pool_sharing_enabled():
        canon = intern_peek(scope)
        if canon is not None:
            remap = remap_codes_onto(canon, pool_data, pool_off,
                                     len(pool_arr))
            if remap is not None:
                from transferia_tpu.stats.trace import TELEMETRY

                TELEMETRY.record_pool_share_hit()
                if np.array_equal(remap,
                                  np.arange(len(pool_arr) + 1,
                                            dtype=np.int32)):
                    remap = None  # same order: codes pass through
                dpool = canon
    if dpool is None:
        # content interning: arrow dictionaries re-read per row group
        # carry identical bytes in fresh buffers — converge them on one
        # DictPool so memos amortize across row groups exactly as on the
        # native path.  The INTERNED pool owns copied buffers (finalize):
        # a pool view into an IPC message / shm segment would otherwise
        # pin the whole mapping for the cache entry's lifetime
        dpool = intern_pool(
            scope, pool_data, pool_off, null_code=len(pool_arr),
            finalize=lambda d, o: (np.ascontiguousarray(d).copy(),
                                   np.ascontiguousarray(o).copy()))
    with _POOL_CACHE_LOCK:
        hit = _POOL_CACHE.get(key)
        if hit is not None:
            return hit[0], hit[2]
        while len(_POOL_CACHE) >= _POOL_CACHE_MAX:
            _POOL_CACHE.pop(next(iter(_POOL_CACHE)), None)
        # pin the ORIGINAL array: its buffer addresses are the key
        _POOL_CACHE[key] = (dpool, orig, remap)
    return dpool, remap


def _arrow_to_column(cs: ColSchema, arr) -> Column:
    import pyarrow as pa
    import pyarrow.types as pt

    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())
    t = arr.type
    if pt.is_dictionary(t):
        vt = t.value_type
        if cs.data_type.is_variable_width and (
                pt.is_string(vt) or pt.is_large_string(vt)
                or pt.is_binary(vt) or pt.is_large_binary(vt)):
            pool_arr = arr.dictionary
            if pool_arr.null_count == 0:
                dpool, remap = _adopt_dict_pool(
                    pool_arr, vt, pt, pa,
                    scope=("arrow", cs.name, str(vt)))
                idx = arr.indices
                if idx.null_count:
                    idx = idx.fill_null(0)
                codes = np.asarray(idx.cast(pa.int32()))
                if remap is not None:
                    # permuted pool adopted onto the canonical one: the
                    # codes change basis (remap has n_pool+1 slots; real
                    # codes stay < n_pool, slot n_pool is the sentinel)
                    codes = remap[codes]
                if validity is not None:
                    # canonical null representation is empty bytes (matches
                    # the flat path): null rows point at the pool's empty
                    # sentinel so lazy materialization is byte-identical
                    codes = np.where(validity, codes,
                                     dpool.null_code).astype(np.int32)
                return Column(cs.name, cs.data_type, validity=validity,
                              dict_enc=DictEnc(codes, pool=dpool))
        # non-string pool or pool nulls: decode in arrow C++ and re-enter
        return _arrow_to_column(cs, arr.cast(t.value_type))
    if pt.is_string(t) or pt.is_large_string(t) or pt.is_binary(t) \
            or pt.is_large_binary(t):
        if pt.is_large_string(t) or pt.is_large_binary(t):
            arr = arr.cast(pa.string() if pt.is_large_string(t) else pa.binary())
        bufs = arr.buffers()
        off = np.frombuffer(bufs[1], dtype=np.int32,
                            count=len(arr) + 1 + arr.offset)
        data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None \
            else np.zeros(0, dtype=np.uint8)
        if arr.offset:
            off = off[arr.offset:]
        if off[0] != 0:
            data = data[off[0]:off[-1]]
            off = off - off[0]
        return Column(cs.name, cs.data_type, np.ascontiguousarray(data),
                      np.ascontiguousarray(off), validity)
    if cs.data_type.is_variable_width:
        # canonical var-width but arrow gave a non-string type: stringify
        vals = arr.to_pylist()
        col = Column.from_pylist(cs.name, cs.data_type, vals)
        return col
    if pt.is_timestamp(t):
        unit_scale = {"s": 1_000_000, "ms": 1_000, "us": 1, "ns": 1}[t.unit]
        vals = np.asarray(arr.cast(pa.int64()).fill_null(0))
        if cs.data_type == CanonicalType.DATETIME:
            div = {"s": 1, "ms": 1_000, "us": 1_000_000, "ns": 1_000_000_000}[t.unit]
            data = (vals // div).astype(np.int64)
        else:
            data = (vals * unit_scale if t.unit in ("s", "ms")
                    else vals // (1000 if t.unit == "ns" else 1)).astype(np.int64)
        return Column(cs.name, cs.data_type, data, None, validity)
    if pt.is_date32(t):
        data = np.asarray(arr.cast(pa.int32()).fill_null(0))
        return Column(cs.name, cs.data_type, data.astype(np.int32), None, validity)
    if pt.is_boolean(t):
        data = np.asarray(arr.fill_null(False))
        return Column(cs.name, cs.data_type, data.astype(np.bool_), None, validity)
    data = np.asarray(arr.fill_null(0)).astype(cs.data_type.np_dtype, copy=False)
    return Column(cs.name, cs.data_type, np.ascontiguousarray(data), None, validity)


def arrow_to_table_schema(pa_schema) -> TableSchema:
    """Infer a canonical TableSchema from an arrow schema."""
    import pyarrow.types as pt

    cols = []
    for f in pa_schema:
        t = f.type
        if pt.is_dictionary(t):
            t = t.value_type  # canonical type is the value type;
            # the encoding itself travels as Column.dict_enc
        if pt.is_int8(t):
            ct = CanonicalType.INT8
        elif pt.is_int16(t):
            ct = CanonicalType.INT16
        elif pt.is_int32(t):
            ct = CanonicalType.INT32
        elif pt.is_int64(t):
            ct = CanonicalType.INT64
        elif pt.is_uint8(t):
            ct = CanonicalType.UINT8
        elif pt.is_uint16(t):
            ct = CanonicalType.UINT16
        elif pt.is_uint32(t):
            ct = CanonicalType.UINT32
        elif pt.is_uint64(t):
            ct = CanonicalType.UINT64
        elif pt.is_float32(t):
            ct = CanonicalType.FLOAT
        elif pt.is_float64(t):
            ct = CanonicalType.DOUBLE
        elif pt.is_boolean(t):
            ct = CanonicalType.BOOLEAN
        elif pt.is_date32(t) or pt.is_date64(t):
            ct = CanonicalType.DATE
        elif pt.is_timestamp(t):
            ct = CanonicalType.TIMESTAMP if t.unit in ("us", "ns") \
                else CanonicalType.DATETIME
        elif pt.is_string(t) or pt.is_large_string(t):
            ct = CanonicalType.UTF8
        elif pt.is_binary(t) or pt.is_large_binary(t):
            ct = CanonicalType.STRING
        elif pt.is_decimal(t):
            ct = CanonicalType.DECIMAL
        else:
            ct = CanonicalType.ANY
        cols.append(ColSchema(
            name=f.name, data_type=ct, required=not f.nullable,
            original_type=f"arrow:{t}",
        ))
    return TableSchema(cols)
