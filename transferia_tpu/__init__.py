"""transferia-tpu: a TPU-native data-ingestion (EL(T)) framework.

Brand-new framework with the capabilities of the reference Go engine
(transferia/transferia): snapshot + CDC replication between DBMSes, object
stores and message brokers, with pluggable providers, transformers, parsers
and a coordinator-based sharded dataplane.  Unlike the reference — whose data
plane is hand-optimized row-oriented Go — this framework's currency is the
columnar `ColumnBatch` and its hot path (parsing, the transformer chain,
encode/decode) compiles to XLA/Pallas kernels via JAX.

Layer map (cf. SURVEY.md §1 for the reference equivalents; docs/PARITY.md
maps the full §2 inventory line by line):
  abstract/       core data model: ChangeItem, TableSchema, Source/Sink/Storage
  columnar/       ColumnBatch (Arrow-style columnar block) + row pivot
  typesystem/     canonical type lattice, provider rules, versioned fallbacks
  models/         Transfer/Endpoint model, runtimes
  events/         event-typed veneer (abstract2 parity)
  coordinator/    control-plane KV/queue (memory, filestore)
  middlewares/    sink pipeline combinators (bufferer, retrier, ...)
  transform/      transformer framework + 26-plugin registry (JAX path)
  predicate/      WHERE-filter AST -> vectorized masks (SQL 3VL)
  parsers/        queue payloads -> columnar batches (arrow fast path)
  parsequeue/     parallel parse, ordered push, post-push ack
  serializers/    batches -> bytes (json/csv/parquet/raw + queue formats)
  debezium/       Debezium envelope emitter/receiver
  schemaregistry/ Confluent SR client
  dblog/          watermark-chunked snapshot concurrent with CDC
  providers/      connector plugins (wire-protocol clients included)
  tasks/          operations: activate, snapshot loader, upload, checksum
  runtime/        local replication worker, regular-snapshot loop
  parallel/       device mesh sharding of the transform step
  ops/            device kernels (HMAC-SHA256 masking, packing, bucketing)
  native/         C++ host-ops (LEB128, scatter/gather) via ctypes
  metering/       usage metering agent + middlewares
  stats/          typed metric bundles (prometheus)
  cli/            trtpu command-line interface
  utils/          backoff, cron, rollbacks, net helpers
"""

__version__ = "0.1.0"
