"""transferia-tpu: a TPU-native data-ingestion (EL(T)) framework.

Brand-new framework with the capabilities of the reference Go engine
(transferia/transferia): snapshot + CDC replication between DBMSes, object
stores and message brokers, with pluggable providers, transformers, parsers
and a coordinator-based sharded dataplane.  Unlike the reference — whose data
plane is hand-optimized row-oriented Go — this framework's currency is the
columnar `ColumnBatch` and its hot path (parsing, the transformer chain,
encode/decode) compiles to XLA/Pallas kernels via JAX.

Layer map (cf. SURVEY.md §1 for the reference equivalents):
  abstract/     core data model: ChangeItem, TableSchema, Source/Sink/Storage
  columnar/     ColumnBatch (Arrow-style columnar block) + row pivot
  typesystem/   canonical type lattice, per-provider rules, versioned fallbacks
  models/       Transfer/Endpoint model, runtimes
  coordinator/  control-plane KV/queue (memory, filestore)
  middlewares/  sink pipeline combinators
  transform/    transformer framework + registry (JAX compute path)
  parsers/      queue payload -> ChangeItems (vectorized)
  serializers/  ChangeItems -> bytes
  providers/    connector plugins
  tasks/        operations: activate, snapshot loader, upload, checksum
  runtime/      local replication worker, strategies
  parallel/     device mesh sharding of the transform step
  ops/          jax/pallas kernels (hashing, predicates, string ops)
  cli/          trtpu command-line interface
"""

__version__ = "0.1.0"
