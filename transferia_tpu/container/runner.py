"""Container job launcher: run connector/tool images, stream their stdout.

Reference parity: pkg/container/container.go — the docker/k8s launcher
behind the Airbyte provider and the dbt transformer.  Runtimes:

  docker | podman — `<rt> run --rm` with env/mount/network mapping
  exec            — run argv directly on the host (bare-metal connectors
                    and the test harness; the reference's k8s-pod mode is
                    a deployment concern handled by the pod spec there)

Runtime resolution: explicit > $TRANSFERIA_CONTAINER_RUNTIME > first of
docker/podman on PATH.  Streaming is line-oriented (Airbyte's message
protocol and dbt's log output are both line-framed JSON/text).
"""

from __future__ import annotations

import logging
import os
import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from transferia_tpu.abstract.errors import CategorizedError

logger = logging.getLogger(__name__)


class ContainerError(CategorizedError):
    def __init__(self, message: str):
        super().__init__(CategorizedError.INTERNAL, message)


@dataclass
class ContainerSpec:
    image: str = ""
    args: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    mounts: list[tuple[str, str]] = field(default_factory=list)  # host, ctr
    entrypoint: str = ""
    network: str = ""            # e.g. "host" for local endpoints
    workdir: str = ""


class ContainerRunner:
    def __init__(self, runtime: str = ""):
        self.runtime = runtime or os.environ.get(
            "TRANSFERIA_CONTAINER_RUNTIME", "") or self._detect()

    @staticmethod
    def _detect() -> str:
        for rt in ("docker", "podman"):
            if shutil.which(rt):
                return rt
        return ""

    def available(self) -> bool:
        return bool(self.runtime)

    def require(self) -> None:
        if not self.available():
            raise ContainerError(
                "no container runtime found (docker/podman) and "
                "TRANSFERIA_CONTAINER_RUNTIME is unset — install one on "
                "the worker or use runtime 'exec' for host binaries"
            )

    def argv(self, spec: ContainerSpec) -> list[str]:
        if self.runtime == "exec":
            # host execution: image is ignored; mounts are identity
            return list(spec.args)
        out = [self.runtime, "run", "--rm", "-i"]
        for k, v in spec.env.items():
            out += ["-e", f"{k}={v}"]
        for host, ctr in spec.mounts:
            out += ["-v", f"{host}:{ctr}"]
        if spec.network:
            out += [f"--network={spec.network}"]
        if spec.workdir:
            out += ["-w", spec.workdir]
        if spec.entrypoint:
            out += ["--entrypoint", spec.entrypoint]
        out.append(spec.image)
        out += spec.args
        return out

    def stream(self, spec: ContainerSpec,
               timeout: Optional[float] = None,
               on_stderr: Optional[Callable[[str], None]] = None
               ) -> Iterator[str]:
        """Run and yield stdout lines; raises ContainerError on a nonzero
        exit (after the stream is drained)."""
        self.require()
        argv = self.argv(spec)
        logger.info("container run: %s", " ".join(argv[:6]))
        env = None
        if self.runtime == "exec" and spec.env:
            env = {**os.environ, **spec.env}
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env,
            cwd=spec.workdir or None if self.runtime == "exec" else None,
        )
        # drain stderr concurrently: a child filling the stderr pipe past
        # its buffer while we block on stdout would deadlock both sides
        err_tail: list[str] = []

        def _drain_stderr():
            assert proc.stderr is not None
            for ln in proc.stderr:
                ln = ln.rstrip("\n")
                err_tail.append(ln)
                if len(err_tail) > 200:
                    del err_tail[0]
                if on_stderr:
                    on_stderr(ln)

        import threading

        err_thread = threading.Thread(target=_drain_stderr, daemon=True)
        err_thread.start()
        try:
            assert proc.stdout is not None
            for line in proc.stdout:
                yield line.rstrip("\n")
            proc.wait(timeout=timeout)
            err_thread.join(timeout=5)
            if proc.returncode != 0:
                raise ContainerError(
                    f"container exited rc={proc.returncode}: "
                    f"{' | '.join(err_tail[-10:])[-800:]}"
                )
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()

    def run(self, spec: ContainerSpec,
            timeout: Optional[float] = None) -> str:
        """Run to completion; returns stdout (raises on nonzero exit)."""
        return "\n".join(self.stream(spec, timeout=timeout))
