"""Container job launcher (reference: pkg/container/container.go)."""

from transferia_tpu.container.runner import (
    ContainerError,
    ContainerRunner,
    ContainerSpec,
)

__all__ = ["ContainerError", "ContainerRunner", "ContainerSpec"]
