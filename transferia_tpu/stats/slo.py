"""SLO objectives + multi-window burn-rate evaluation (the judge).

The observability plane records (traces, ledgers, mergeable HDR
histograms, freshness watermarks) — this module *interprets*: a small
declarative objective set is evaluated over the durable obs-segment
stream into per-objective verdicts any process can compute.

Objectives
----------
Two kinds:

- ``latency`` — "fraction of <stage> observations at or under
  <threshold_ms> must be >= <target>".  Backed by the mergeable HDR
  stage histograms exported in every obs segment (``replication_lag``
  from the watermark plane, ``part_upload`` from the snapshot engine).
- ``availability`` — "fraction of part commit decisions that are
  granted (not fenced) must be >= <target>".  Backed by the ledger
  ``commits`` / ``commit_fences`` counters in the same segments.

Defaults live in `DEFAULT_OBJECTIVES`; ``TRANSFERIA_TPU_SLO_SPEC`` (a
JSON list of objective dicts) replaces them, and the per-knob envs
``TRANSFERIA_TPU_SLO_LAG_MS`` / ``TRANSFERIA_TPU_SLO_UPLOAD_MS`` tune
the default thresholds without writing JSON.

Burn rate
---------
Classic multi-window error-budget burn: for window W ending at the
evaluation epoch, ``bad_fraction(W) / (1 - target)`` — burn 1.0 spends
the budget exactly at the objective's allowance, burn N spends it N×
faster.  Two windows (fast 5m, slow 1h) must BOTH burn >= 1 to page:
the fast window catches a fresh regression quickly, the slow window
keeps a transient blip from paging.  Windows are carved from the
CUMULATIVE segment stream: per process, the newest segment is the
window end and the newest segment older than (epoch - W) is the
baseline; ``end.diff(baseline)`` is exact bucket subtraction
(stats/hdr.py), and the per-process windows merge bucket-wise.

Determinism
-----------
`evaluate` is PURE over the segment list: the evaluation epoch is the
max segment timestamp (never the caller's clock), merges fold in
sorted process order, and every float is rounded before it lands in a
verdict — so the scheduler leader, any worker, and an offline `trtpu
slo --fleet` compute byte-identical verdicts from the same segments.
The ``slo.evaluate`` failpoint pins that a fault during evaluation
surfaces as an error payload to the caller, never a half-verdict.

Alert hook
----------
`SloAlertHook` latches the fleet QoS plane on burn: every burning
objective latches an external backpressure signal (admission sheds new
work while the budget is burning), and an objective pinned to a tenant
escalates that tenant's WDRR weight (an INTERACTIVE tenant burning
budget drains faster), restoring the baseline weight on recovery.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass
from typing import Optional

from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.runtime import knobs
from transferia_tpu.stats import hdr, trace, watermark

FAST_WINDOW_SECONDS = 300.0     # 5m: catches a fresh regression
SLOW_WINDOW_SECONDS = 3600.0    # 1h: keeps a blip from paging

ENV_SPEC = "TRANSFERIA_TPU_SLO_SPEC"
ENV_LAG_MS = "TRANSFERIA_TPU_SLO_LAG_MS"
ENV_UPLOAD_MS = "TRANSFERIA_TPU_SLO_UPLOAD_MS"


@dataclass(frozen=True)
class SloObjective:
    """One declarative objective.  `tenant` / `transfer` scope the
    alert hook's escalation (evaluation itself is fleet-wide: stage
    histograms merge across the fleet, commit counters likewise)."""

    name: str
    kind: str = "latency"            # "latency" | "availability"
    stage: str = watermark.STAGE_LAG  # hdr stage (latency kind)
    threshold_ms: float = 5000.0
    target: float = 0.99             # good-event fraction objective
    tenant: str = ""
    transfer: str = ""

    def to_json(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "stage": self.stage, "threshold_ms": self.threshold_ms,
                "target": self.target, "tenant": self.tenant,
                "transfer": self.transfer}


def default_objectives(environ=os.environ) -> tuple:
    return (
        SloObjective(
            "replication_lag_p99", stage=watermark.STAGE_LAG,
            threshold_ms=knobs.env_float(ENV_LAG_MS, 5000.0,
                                          environ=environ),
            target=0.99),
        SloObjective(
            "part_upload_p99", stage="part_upload",
            threshold_ms=knobs.env_float(ENV_UPLOAD_MS, 30_000.0,
                                          environ=environ),
            target=0.99),
        SloObjective(
            "part_commit_availability", kind="availability",
            target=0.999),
    )


DEFAULT_OBJECTIVES = default_objectives()


def objectives_from_env(environ=os.environ) -> tuple:
    """The active objective set: ``TRANSFERIA_TPU_SLO_SPEC`` (JSON list
    of objective dicts) replaces the defaults wholesale; a torn spec
    falls back to the defaults rather than silently disabling SLOs."""
    raw = knobs.env_str(ENV_SPEC, "", environ=environ)
    if not raw:
        return default_objectives(environ)
    try:
        spec = json.loads(raw)
        if not isinstance(spec, list):
            raise ValueError("spec must be a JSON list")
        out = []
        for d in spec:
            out.append(SloObjective(
                name=str(d["name"]),
                kind=str(d.get("kind", "latency")),
                stage=str(d.get("stage", watermark.STAGE_LAG)),
                threshold_ms=float(d.get("threshold_ms", 5000.0)),
                target=min(0.999999, max(0.0,
                                         float(d.get("target", 0.99)))),
                tenant=str(d.get("tenant", "")),
                transfer=str(d.get("transfer", "")),
            ))
        return tuple(out)
    except (KeyError, TypeError, ValueError):
        return default_objectives(environ)


# -- pure evaluation over obs segments ----------------------------------------

def _window_state(segments: list[dict], epoch: float,
                  window: float) -> tuple[dict, dict, int]:
    """Carve one burn window out of the cumulative segment stream.
    Returns (merged stage hists, summed ledger-totals delta, process
    count).  Per process: end = newest segment, baseline = newest
    segment with ts <= epoch - window (none -> zero baseline, i.e. the
    process's whole history lies inside the window)."""
    from transferia_tpu.stats.fleetobs import _proc_key

    by_proc: dict = {}
    for seg in segments:
        by_proc.setdefault(_proc_key(seg), []).append(seg)
    hists: dict[str, hdr.LogHistogram] = {}
    totals: dict[str, float] = {}
    cutoff = epoch - window
    for proc in sorted(by_proc):
        run = sorted(by_proc[proc],
                     key=lambda s: (s.get("ts", 0.0) or 0.0,
                                    s.get("seq", 0) or 0))
        end = run[-1]
        base = None
        for seg in run:
            if (seg.get("ts", 0.0) or 0.0) <= cutoff:
                base = seg
            else:
                break
        end_hists = end.get("hists", {}) or {}
        base_hists = (base.get("hists", {}) or {}) if base else {}
        for stage in sorted(end_hists):
            h = hdr.LogHistogram.from_json(end_hists.get(stage))
            if base_hists.get(stage) is not None:
                h = h.diff(hdr.LogHistogram.from_json(
                    base_hists[stage]))
            agg = hists.get(stage)
            if agg is None:
                hists[stage] = h
            else:
                agg.merge(h)
        end_tot = (end.get("ledger", {}) or {}).get("totals", {}) or {}
        base_tot = ((base.get("ledger", {}) or {}).get("totals", {})
                    or {}) if base else {}
        for name, v in end_tot.items():
            if not isinstance(v, (int, float)):
                continue
            prior = base_tot.get(name, 0)
            prior = prior if isinstance(prior, (int, float)) else 0
            totals[name] = totals.get(name, 0) + max(0, v - prior)
    return hists, totals, len(by_proc)


def _burn(objective: SloObjective, hists: dict,
          totals: dict) -> tuple[float, int]:
    """(burn rate, event count) for one objective over one window."""
    budget = max(1e-6, 1.0 - objective.target)
    if objective.kind == "availability":
        commits = int(totals.get("commits", 0))
        fences = int(totals.get("commit_fences", 0))
        events = commits + fences
        bad = (fences / events) if events else 0.0
        return round(bad / budget, 6), events
    h = hists.get(objective.stage)
    if h is None or h.count <= 0:
        return 0.0, 0
    bad = 1.0 - h.fraction_at_most(objective.threshold_ms / 1000.0)
    return round(bad / budget, 6), h.count


def evaluate(raw_segments: list,
             objectives: Optional[tuple] = None,
             fast_window: float = FAST_WINDOW_SECONDS,
             slow_window: float = SLOW_WINDOW_SECONDS) -> dict:
    """Pure multi-window burn-rate verdicts over an obs-segment list
    (the `/debug/slo` payload).  Identical input segments produce an
    identical verdict dict in ANY process and ANY segment order."""
    from transferia_tpu.stats.fleetobs import _parse_segments

    objectives = objectives_from_env() if objectives is None \
        else objectives
    with trace.span("slo_evaluate", segments=len(raw_segments or [])):
        failpoint("slo.evaluate")
        segments, corrupt = _parse_segments(raw_segments)
        epoch = max(((s.get("ts", 0.0) or 0.0) for s in segments),
                    default=0.0)
        fast = _window_state(segments, epoch, fast_window)
        slow = _window_state(segments, epoch, slow_window)
        merged_wm = watermark.merge_maps(
            [seg.get("watermarks") for seg in segments])
        verdicts: dict[str, dict] = {}
        ok = True
        for obj in objectives:
            burn_fast, n_fast = _burn(obj, fast[0], fast[1])
            burn_slow, n_slow = _burn(obj, slow[0], slow[1])
            burning = burn_fast >= 1.0 and burn_slow >= 1.0
            ok = ok and not burning
            v = {
                "objective": obj.to_json(),
                "burn_fast": burn_fast,
                "burn_slow": burn_slow,
                "events_fast": n_fast,
                "events_slow": n_slow,
                "burning": burning,
                "ok": not burning,
            }
            if obj.kind == "latency":
                h = slow[0].get(obj.stage)
                v["window_p99_ms"] = h.summary()["p99_ms"] if h else 0.0
            verdicts[obj.name] = v
        return {
            "epoch": round(epoch, 6),
            "windows": {"fast_seconds": fast_window,
                        "slow_seconds": slow_window},
            "segments": len(segments),
            "corrupt_segments": corrupt,
            "processes": slow[2],
            "objectives": verdicts,
            "burning": sorted(n for n, v in verdicts.items()
                              if v["burning"]),
            "ok": ok,
            "watermarks": watermark.summarize(merged_wm, now=epoch),
        }


def local_segments() -> list[dict]:
    """One synthetic cumulative segment from THIS process's registries
    — lets `/debug/slo` and `trtpu slo --demo` evaluate without a
    coordinator.  Single segment means the burn windows both see the
    whole process history (no baseline yet), which is the honest
    reading for a young process."""
    import socket

    from transferia_tpu.stats.ledger import LEDGER

    snap = LEDGER.snapshot()
    return [{
        "v": 1,
        "worker": "local",
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "seq": 1,
        "ts": time.time(),
        "kind": "local",
        "spans": [],
        "ledger": {
            "totals": snap["totals"],
            "transfers": snap["transfers"],
            "tenants": snap["tenants"],
            "conservation_ok": bool(snap["conservation"].get("ok")),
        },
        "telemetry": {},
        "hists": hdr.STAGES.snapshot(),
        "watermarks": watermark.WATERMARKS.snapshot(),
    }]


def debug_slo() -> dict:
    """The `GET /debug/slo` payload: fleet verdicts through the
    registered obs runtime when there is one, local-process verdicts
    otherwise.  Errors surface as an ``error`` payload (the CLI's
    wrong-shape contract treats those as exit 2), never a raise into
    the health server."""
    from transferia_tpu.stats import fleetobs

    rt = fleetobs._runtime()
    try:
        if rt is not None:
            segments = rt["cp"].list_obs_segments(rt["scope"])
            view = evaluate(segments)
            view["scope"] = rt["scope"]
        else:
            view = evaluate(local_segments())
            view["scope"] = "local"
        return view
    except Exception as e:
        return {"error": f"slo evaluation failed: {e}"}


def format_verdicts(view: dict) -> str:
    """Render one `trtpu slo` frame."""
    lines = []
    lines.append(
        f"slo: {'OK' if view.get('ok') else 'BURNING'}  "
        f"scope={view.get('scope', '-')}  "
        f"segments={view.get('segments', 0)} "
        f"({view.get('processes', 0)} process(es))"
        + (f"  torn={view['corrupt_segments']}"
           if view.get("corrupt_segments") else ""))
    header = (f"{'objective':<28} {'kind':<13} {'burn5m':>8} "
              f"{'burn1h':>8} {'events':>8} {'p99_ms':>10} {'state':>8}")
    lines.append(header)
    for name, v in sorted(view.get("objectives", {}).items()):
        obj = v.get("objective", {})
        lines.append(
            f"{name:<28} {obj.get('kind', '?'):<13} "
            f"{v.get('burn_fast', 0):>8.2f} "
            f"{v.get('burn_slow', 0):>8.2f} "
            f"{v.get('events_slow', 0):>8} "
            f"{v.get('window_p99_ms', '-'):>10} "
            f"{'BURN' if v.get('burning') else 'ok':>8}")
    wm = view.get("watermarks", {})
    if wm:
        lines.append("freshness (max-lag watermark per transfer):")
        for tid, row in sorted(wm.items()):
            lag = row.get("lag_ms")
            lines.append(
                f"  {tid:<30} tables={row.get('tables', 0):<5} "
                f"lag={'-' if lag is None else f'{lag:.0f}ms'}")
    return "\n".join(lines)


def fold_verdicts(metrics, view: dict) -> None:
    """Fold one evaluation into `SloStats` gauges (heartbeat cadence
    exposure for prometheus scrapers)."""
    from transferia_tpu.stats.registry import SloStats

    stats = SloStats(metrics)
    objectives = view.get("objectives", {}) or {}
    stats.objectives.set(len(objectives))
    stats.burning.set(len(view.get("burning", []) or []))
    stats.evaluations.inc()
    worst_fast = max((v.get("burn_fast", 0.0)
                      for v in objectives.values()), default=0.0)
    worst_slow = max((v.get("burn_slow", 0.0)
                      for v in objectives.values()), default=0.0)
    stats.worst_burn_fast.set(worst_fast)
    stats.worst_burn_slow.set(worst_slow)
    lags = [row.get("lag_ms") for row in
            (view.get("watermarks", {}) or {}).values()
            if isinstance(row.get("lag_ms"), (int, float))]
    stats.worst_lag_ms.set(max(lags) if lags else 0.0)


class SloAlertHook:
    """Latches the fleet QoS plane while objectives burn.

    - every burning objective latches an external backpressure signal
      (``slo:<name>``) so admission sheds new work;
    - a burning objective pinned to a tenant escalates that tenant's
      WDRR weight by `escalate_factor` (the INTERACTIVE-tenant story:
      its queue drains faster while its budget burns), restoring the
      remembered baseline on recovery.

    Idempotent per tick: apply() diffs against the currently-latched
    set, so repeated evaluations don't stack escalations."""

    def __init__(self, scheduler=None, backpressure=None,
                 escalate_factor: float = 2.0):
        self.scheduler = scheduler
        self.backpressure = backpressure
        self.escalate_factor = max(1.0, float(escalate_factor))
        self._latched: set[str] = set()
        self._baseline_weights: dict[str, float] = {}

    def apply(self, view: dict) -> dict:
        """Apply one evaluation's verdicts; returns the actions taken
        (for logs/tests)."""
        burning = {}
        for name, v in (view.get("objectives", {}) or {}).items():
            if v.get("burning"):
                burning[name] = v
        actions = {"latched": [], "cleared": [], "escalated": [],
                   "restored": []}
        newly = set(burning) - self._latched
        cleared = self._latched - set(burning)
        if self.backpressure is not None:
            for name in sorted(newly):
                self.backpressure.latch_external(
                    f"slo:{name}",
                    reason=f"burn {burning[name].get('burn_fast', 0)}x")
                actions["latched"].append(f"slo:{name}")
            for name in sorted(cleared):
                self.backpressure.clear_external(f"slo:{name}")
                actions["cleared"].append(f"slo:{name}")
        if self.scheduler is not None:
            for name in sorted(newly):
                tenant = (burning[name].get("objective", {})
                          or {}).get("tenant", "")
                if not tenant or tenant in self._baseline_weights:
                    continue
                prev = self.scheduler.set_tenant_weight(
                    tenant,
                    self.scheduler.tenant_weight(tenant)
                    * self.escalate_factor)
                self._baseline_weights[tenant] = prev
                actions["escalated"].append(tenant)
            still_burning_tenants = {
                (v.get("objective", {}) or {}).get("tenant", "")
                for v in burning.values()}
            for tenant in sorted(set(self._baseline_weights)
                                 - still_burning_tenants):
                self.scheduler.set_tenant_weight(
                    tenant, self._baseline_weights.pop(tenant))
                actions["restored"].append(tenant)
        self._latched = set(burning)
        return actions
