"""End-to-end freshness watermarks (the SLO plane's time axis).

A replication batch is born at some source event time — the broker
write timestamp a queue poll observes (`Message.write_time_ns`), or the
transaction commit time a CDC batch carries (`ChangeItem.commit_time_ns`
/ `ColumnBatch.commit_times`).  Everything the pipeline does after that
point (parse, transform, buffer, publish) is LAG.  This module tracks
two monotone watermarks per transfer:

- **poll watermarks** (`~poll/<topic>:<partition>` keys) — the newest
  source event time a fetch loop has seen, advanced by the queue source
  pump before the batch enters the parsequeue;
- **publish watermarks** (per table) — the newest event time that has
  durably reached the sink, advanced by the Statistician middleware
  after a successful push.  When the batch itself carries no event time
  (non-CDC parsers without system columns), the transfer's poll
  watermark stands in; when there is no poll watermark either (snapshot
  sources that never stamped event time), the publish wall clock is
  recorded with `origin="publish"` so liveness is still visible — but
  no lag is fabricated.

The per-(transfer, table) publish lag lands in the mergeable HDR
histograms (stats/hdr.py) under stage ``replication_lag``, so the fleet
observability plane exports it inside obs segments and any process can
read cluster p50/p99/p999 lag.  The watermark map itself rides obs
segments as a ``watermarks`` payload; `merge_maps` folds N processes'
maps field-wise-MAX per (transfer, table) — max-merge is idempotent and
commutative, so replayed or reordered segments can never regress a
published watermark (the chaos `fleet_distributed` mode asserts this
across a worker kill).

Cardinality is bounded per transfer (a 10k-table transfer must not grow
the obs segment unboundedly): past ``TRANSFERIA_TPU_WATERMARK_TABLES``
entries the oldest per-table entry folds into a ``~overflow`` key — the
same eviction convention as the resource ledger, preserving the max so
the transfer-level freshness rollup stays exact.

Advancing a watermark is bookkeeping, never data plane: the
``watermark.advance`` failpoint fires inside `advance` and any injected
fault is absorbed (counted, watermark unchanged) — a freshness fault
must not fail the batch it rode on.  Worker-kill faults still kill.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from transferia_tpu.abstract.errors import is_worker_kill
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.runtime import knobs
from transferia_tpu.stats import hdr, trace

OVERFLOW = "~overflow"
POLL_PREFIX = "~poll/"
STAGE_LAG = "replication_lag"   # hdr stage the publish lag lands in

ENV_MAX_TABLES = "TRANSFERIA_TPU_WATERMARK_TABLES"
DEFAULT_MAX_TABLES = 256

_ENTRY_FIELDS = ("event_ns", "lsn", "publish_unix")


def _max_tables(environ=os.environ) -> int:
    return max(2, knobs.env_int(ENV_MAX_TABLES, DEFAULT_MAX_TABLES,
                                environ=environ))


def batch_event_ns(batch) -> int:
    """Newest source event time (epoch ns) a batch carries, 0 when it
    carries none.  Carriers, in order: CDC commit times on a columnar
    block, the generic parser's ``_timestamp`` system column (epoch
    microseconds), per-row ChangeItem commit times."""
    from transferia_tpu.abstract.interfaces import is_columnar

    if is_columnar(batch):
        if batch.commit_times is not None and len(batch.commit_times):
            return int(batch.commit_times.max())
        col = batch.columns.get("_timestamp")
        if col is not None:
            try:
                data = col.data
                if data is not None and len(data):
                    return int(data.max()) * 1000
            except (TypeError, ValueError):
                return 0
        return 0
    best = 0
    for it in batch:
        if it.is_row_event() and it.commit_time_ns > best:
            best = it.commit_time_ns
    return best


class WatermarkMap:
    """Process-global monotone watermark registry (singleton
    WATERMARKS).  Keys are (transfer_id, table); poll watermarks use
    ``~poll/<topic>:<partition>`` table keys so they merge and export
    through the same machinery without colliding with real tables."""

    def __init__(self, max_tables: Optional[int] = None):
        self._lock = threading.Lock()
        # transfer -> {table -> {event_ns, lsn, publish_unix, origin}}
        # (insertion-ordered: eviction folds the oldest entry first)
        self._marks: dict[str, dict[str, dict]] = {}
        self._max_tables = max_tables
        self.advances = 0
        self.regressions_skipped = 0
        self.folded_entries = 0
        self.faults_absorbed = 0

    def advance(self, transfer_id: str, table: str, event_ns: int = 0,
                lsn: int = 0, origin: str = "event",
                now: Optional[float] = None) -> bool:
        """Advance the (transfer, table) watermark to max(current, new).
        Returns whether anything moved forward.  Injected faults at the
        ``watermark.advance`` site are absorbed — freshness bookkeeping
        never fails the data plane (worker kills still propagate)."""
        if not transfer_id or not table:
            return False
        try:
            failpoint("watermark.advance")
        except BaseException as e:
            if is_worker_kill(e):
                raise
            with self._lock:
                self.faults_absorbed += 1
            return False
        now = time.time() if now is None else now
        with self._lock:
            tables = self._marks.get(transfer_id)
            if tables is None:
                tables = self._marks[transfer_id] = {}
            entry = tables.get(table)
            if entry is None:
                self._evict_locked(tables)
                entry = tables[table] = {
                    "event_ns": 0, "lsn": 0, "publish_unix": 0.0,
                    "origin": origin}
            moved = False
            if int(event_ns) > entry["event_ns"]:
                entry["event_ns"] = int(event_ns)
                entry["origin"] = origin
                moved = True
            if int(lsn) > entry["lsn"]:
                entry["lsn"] = int(lsn)
                moved = True
            if now > entry["publish_unix"]:
                entry["publish_unix"] = round(float(now), 6)
                moved = moved or entry["event_ns"] == 0
            if moved:
                self.advances += 1
            else:
                self.regressions_skipped += 1
        if moved:
            trace.instant("watermark_advance", transfer_id=transfer_id,
                          table=table, origin=origin)
        return moved

    def _evict_locked(self, tables: dict) -> None:
        """Fold oldest entries into ``~overflow`` (field-wise max) when
        a transfer's table map is full — the ledger's eviction
        convention, max-preserving so rollups stay exact."""
        limit = self._max_tables if self._max_tables is not None \
            else _max_tables()
        while len(tables) >= limit:
            victim = next((t for t in tables if t != OVERFLOW), None)
            if victim is None:
                return
            old = tables.pop(victim)
            sink = tables.get(OVERFLOW)
            if sink is None:
                old["origin"] = "overflow"
                tables[OVERFLOW] = old
            else:
                for f in _ENTRY_FIELDS:
                    sink[f] = max(sink[f], old[f])
            self.folded_entries += 1

    def observe_publish(self, transfer_id: str, batch,
                        now_ns: Optional[int] = None) -> Optional[float]:
        """Sink-publish hook (Statistician): record end-to-end lag into
        the ``replication_lag`` histogram and advance the publish
        watermark.  Returns the lag in seconds, or None when the batch
        (and the transfer's poll watermark) carry no event time."""
        from transferia_tpu.abstract.interfaces import is_columnar

        if not transfer_id:
            return None
        now_ns = time.time_ns() if now_ns is None else now_ns
        event_ns = batch_event_ns(batch)
        origin = "event"
        if not event_ns:
            event_ns = self.poll_event_ns(transfer_id)
            origin = "poll"
        if is_columnar(batch):
            table = str(batch.table_id)
            lsn = int(batch.lsns.max()) if batch.lsns is not None \
                and len(batch.lsns) else 0
        else:
            table = next((str(it.table_id) for it in batch
                          if it.is_row_event()), "")
            lsn = max((it.lsn for it in batch if it.is_row_event()),
                      default=0)
        if not table:
            return None
        if not event_ns:
            self.advance(transfer_id, table, 0, lsn, origin="publish",
                         now=now_ns / 1e9)
            return None
        lag = max(0.0, (now_ns - event_ns) / 1e9)
        self.advance(transfer_id, table, event_ns, lsn, origin=origin,
                     now=now_ns / 1e9)
        hdr.observe(STAGE_LAG, lag)
        return lag

    def poll_event_ns(self, transfer_id: str) -> int:
        """Newest poll-watermark event time for a transfer (0 = none)."""
        with self._lock:
            tables = self._marks.get(transfer_id)
            if not tables:
                return 0
            return max((e["event_ns"] for t, e in tables.items()
                        if t.startswith(POLL_PREFIX)), default=0)

    def snapshot(self) -> dict:
        """The obs-segment ``watermarks`` payload:
        {transfer: {table: {event_ns, lsn, publish_unix, origin}}}."""
        with self._lock:
            return {tid: {t: dict(e) for t, e in tables.items()}
                    for tid, tables in sorted(self._marks.items())}

    def reset(self) -> None:
        with self._lock:
            self._marks.clear()
            self.advances = 0
            self.regressions_skipped = 0
            self.folded_entries = 0
            self.faults_absorbed = 0


WATERMARKS = WatermarkMap()


def _clean_entry(raw) -> Optional[dict]:
    if not isinstance(raw, dict):
        return None
    out = {"event_ns": 0, "lsn": 0, "publish_unix": 0.0,
           "origin": str(raw.get("origin", "event"))}
    try:
        out["event_ns"] = max(0, int(raw.get("event_ns", 0) or 0))
        out["lsn"] = max(0, int(raw.get("lsn", 0) or 0))
        out["publish_unix"] = max(0.0, float(raw.get("publish_unix",
                                                     0.0) or 0.0))
    except (TypeError, ValueError):
        return None
    return out


def merge_maps(maps: list) -> dict:
    """Fold N processes' watermark payloads field-wise MAX per
    (transfer, table).  Commutative and idempotent — segment order,
    replays and overlapping export windows cannot regress a published
    watermark.  Junk-tolerant: torn entries contribute nothing."""
    out: dict[str, dict[str, dict]] = {}
    for m in maps:
        if not isinstance(m, dict):
            continue
        for tid, tables in m.items():
            if not isinstance(tables, dict):
                continue
            dst = out.setdefault(str(tid), {})
            for table, raw in tables.items():
                entry = _clean_entry(raw)
                if entry is None:
                    continue
                cur = dst.get(str(table))
                if cur is None:
                    dst[str(table)] = entry
                    continue
                for f in _ENTRY_FIELDS:
                    if entry[f] > cur[f]:
                        cur[f] = entry[f]
                        if f == "event_ns":
                            cur["origin"] = entry["origin"]
    return {tid: dict(sorted(tables.items()))
            for tid, tables in sorted(out.items())}


def summarize(merged: dict, now: Optional[float] = None) -> dict:
    """Per-transfer freshness rollup for the fleet pane: table count,
    the max-lag (oldest) published event watermark, and its lag vs
    `now`.  Poll/overflow keys inform liveness but only real published
    tables define the freshness floor; transfers with no event-time
    watermark report lag_ms=None (unknown, not zero)."""
    now = time.time() if now is None else now
    out: dict[str, dict] = {}
    for tid, tables in merged.items():
        published = {t: e for t, e in tables.items()
                     if not t.startswith(POLL_PREFIX)}
        event_marks = [e["event_ns"] for e in published.values()
                       if e["event_ns"] > 0]
        floor_ns = min(event_marks) if event_marks else 0
        last_pub = max((e["publish_unix"] for e in tables.values()),
                       default=0.0)
        out[tid] = {
            "tables": len(published),
            "watermark_unix": round(floor_ns / 1e9, 6) if floor_ns
            else 0.0,
            "lag_ms": round(max(0.0, now - floor_ns / 1e9) * 1000.0, 3)
            if floor_ns else None,
            "last_publish_unix": round(last_pub, 6),
        }
    return out
