"""Trace-derived critical-path attribution (`trtpu explain`).

The causal trace records *what ran when* (parent/child spans on each
thread, plus cross-thread/cross-process links carried over parsequeue
handoffs, fleet tickets, the Flight wire and shm framing).  This module
answers *why the wall clock went where it went*: a backward walk over
the span forest attributes every second of end-to-end wall time to a
named pipeline stage.

Algorithm (backward sweep)
--------------------------
Wall time is the window [min t0, max end] over the kept spans.  A
cursor starts at the global end and sweeps backward through the root
spans (newest end first):

- the gap between the cursor and the next root below it is time no
  traced span covers — scheduler/queue air, attributed to
  ``orchestration``;
- inside a span, children are visited newest-end-first; the gap
  between the cursor and a child's end is the span's own time
  (attributed to the span's stage), then the walk descends into the
  child and the cursor jumps to the child's start.

Every interval lands in exactly one stage, so attribution sums to the
wall window by construction (clock skew across processes is clamped,
which is the only loss).  Cross-process parent links make a child in
worker B extend the critical path of a span in worker A — the flow
links ARE the multi-worker critical path.

Stage mapping is by span name (`stage_of`): decode, transform,
device dispatch, queue wait, wire, publish, commit, orchestration.
Unknown names inherit their nearest mapped ancestor's flavor by
falling back to ``orchestration`` — the walk never drops time on the
floor because a new span name appeared.
"""

from __future__ import annotations

from typing import Optional

from transferia_tpu.stats import trace

# Stage buckets, in render order.  Exact-name rules first, then prefix
# rules; first hit wins.
STAGES_ORDER = ("decode", "transform", "device dispatch", "queue wait",
                "wire", "publish", "commit", "orchestration")

_EXACT = {
    "source_decode": "decode",
    "decode_readahead": "decode",
    "native_rowgroup_decode": "decode",
    "pivot": "decode",
    "batch": "decode",
    "transform": "transform",
    "device_dispatch": "device dispatch",
    "device_wait": "device dispatch",
    "device_decode": "device dispatch",
    "pack": "device dispatch",
    "fused_run": "device dispatch",
    "host_post": "device dispatch",
    "host_mask": "device dispatch",
    "pool_upload": "device dispatch",
    "dict_adopt": "device dispatch",
    "rowhash_pool_accs": "device dispatch",
    "sink_wait": "queue wait",
    "fleet_queue_wait": "queue wait",
    "replication_pump": "queue wait",
    "serialize": "wire",
    "shm_map": "wire",
    "shm_attach": "wire",
    "kafka_roundtrip": "wire",
    "s3_request": "wire",
    "sink": "publish",
    "sink_push": "publish",
    "sink_stage": "publish",
    "sink_publish": "publish",
    "bufferer_flush": "publish",
    "s3_publish_copy": "publish",
    "ch_publish_partition": "publish",
    "coord_commit_part": "commit",
}

_PREFIX = (
    ("flight_", "wire"),
    ("file_part", "decode"),
    ("snapshot_", "orchestration"),
    ("fleet_", "orchestration"),
    ("coord_", "commit"),
    ("lease_", "orchestration"),
    ("replication_", "orchestration"),
    ("obs_", "orchestration"),
    ("slo_", "orchestration"),
)

_PUBLISH_SUFFIXES = ("_publish_txn", "_publish")


def stage_of(name: str) -> str:
    s = _EXACT.get(name)
    if s:
        return s
    for prefix, stage in _PREFIX:
        if name.startswith(prefix):
            return stage
    for suffix in _PUBLISH_SUFFIXES:
        if name.endswith(suffix):
            return "publish"
    return "orchestration"


# -- record normalization -----------------------------------------------------

def _clean_record(rec, shift: float, proc) -> Optional[dict]:
    try:
        (name, tid, _tname, t0, dur, _self_s, depth, args,
         trace_id, span_id, parent_id) = rec[:11]
    except (ValueError, TypeError):
        return None
    if depth is not None and depth < 0:
        return None                    # instants carry no duration
    try:
        t0 = float(t0) + shift
        dur = max(0.0, float(dur))
    except (TypeError, ValueError):
        return None
    return {
        "name": str(name), "proc": proc, "tid": tid,
        "t0": t0, "end": t0 + dur, "dur": dur,
        "args": args if isinstance(args, dict) else {},
        "trace_id": int(trace_id or 0),
        "span_id": int(span_id or 0),
        "parent_id": int(parent_id or 0),
    }


def records_from_segments(raw_segments: list) -> list[dict]:
    """Flatten N obs segments onto one wall-clock axis (the same
    epoch-shift + (proc, trace, span) dedup the fleet Chrome export
    uses — overlapping export windows re-send spans)."""
    from transferia_tpu.stats.fleetobs import _parse_segments, _proc_key

    segments, _ = _parse_segments(raw_segments)
    epochs = [float(s.get("epoch_unix", 0.0) or 0.0) for s in segments
              if s.get("spans")]
    epoch0 = min(epochs) if epochs else 0.0
    out: list[dict] = []
    seen: set = set()
    for seg in segments:
        proc = _proc_key(seg)
        shift = float(seg.get("epoch_unix", epoch0) or epoch0) - epoch0
        for rec in seg.get("spans", []):
            r = _clean_record(rec, shift, proc)
            if r is None:
                continue
            key = (proc, r["trace_id"], r["span_id"]) if r["span_id"] \
                else (proc, r["tid"], r["name"], round(r["t0"], 9))
            if key in seen:
                continue
            seen.add(key)
            out.append(r)
    return out


def records_from_local() -> list[dict]:
    """This process's span ring as explain records (demo mode)."""
    out = []
    for rec in trace.spans():
        r = _clean_record(rec, 0.0, ("local", 0))
        if r is not None:
            out.append(r)
    return out


# -- the walk -----------------------------------------------------------------

def _trace_ids_for(records: list[dict], transfer_id: str) -> set:
    ids = set()
    for r in records:
        a = r["args"]
        if r["trace_id"] and transfer_id in (
                a.get("transfer_id"), a.get("transfer"),
                a.get("ticket_id")):
            ids.add(r["trace_id"])
    return ids


def _walk(span: dict, cursor: float, children: dict,
          stages: dict, path: set) -> None:
    """Attribute [span.t0, cursor] — the span's own time minus the
    intervals its children cover, with each child recursed into.
    `path` guards against corrupt parent links forming a cycle."""
    if span["span_id"] in path:
        return
    path.add(span["span_id"])
    own = stage_of(span["name"])
    t = cursor
    floor = max(span["t0"], 0.0)
    kids = sorted(children.get(span["span_id"], []),
                  key=lambda c: c["end"], reverse=True)
    for child in kids:
        if t <= floor:
            break
        if child["t0"] >= t:
            continue
        c_end = min(child["end"], t)
        if t - c_end > 0:
            stages[own] = stages.get(own, 0.0) + (t - c_end)
        _walk(child, c_end, children, stages, path)
        t = max(floor, min(t, child["t0"]))
    if t > floor:
        stages[own] = stages.get(own, 0.0) + (t - floor)
    path.discard(span["span_id"])


def _sweep(spans: list[dict], children: dict, start: float,
           end: float) -> dict:
    """Backward sweep over root spans: every second of [start, end]
    lands in exactly one stage."""
    stages: dict[str, float] = {}
    cursor = end
    for root in sorted(spans, key=lambda r: r["end"], reverse=True):
        if cursor <= start:
            break
        seg_end = min(root["end"], cursor)
        if seg_end <= max(root["t0"], start):
            continue
        if cursor - seg_end > 0:
            stages["orchestration"] = stages.get(
                "orchestration", 0.0) + (cursor - seg_end)
        _walk(root, seg_end, children, stages, set())
        cursor = max(start, min(cursor, root["t0"]))
    if cursor > start:
        stages["orchestration"] = stages.get(
            "orchestration", 0.0) + (cursor - start)
    return stages


_LEVER_HINTS = {
    "decode": "decode-bound: raise source/parser parallelism or use "
              "the native rowgroup path",
    "transform": "transform-bound: vectorize or prune transformer "
                 "chain",
    "device dispatch": "device-bound: bigger pivots, donated buffers, "
                       "check compile cache hits",
    "queue wait": "backpressure: downstream slower than source — "
                  "raise sink parallelism or bufferer flush size",
    "wire": "transport-bound: more Flight streams / larger frames / "
            "shm for co-located hops",
    "publish": "sink-bound: batch the publish path or raise sink "
               "parallelism",
    "commit": "coordinator-bound: commit round-trips dominate — batch "
              "part commits",
    "orchestration": "scheduler air: gaps between parts — raise "
                     "worker slots or reduce part granularity",
}


def explain(records: list[dict], transfer_id: str = "") -> dict:
    """Critical-path report over explain records.  `transfer_id`
    narrows to the traces that touch one transfer (falls back to every
    record when nothing matches — a demo trace has exactly one
    transfer anyway)."""
    kept = records
    if transfer_id:
        ids = _trace_ids_for(records, transfer_id)
        narrowed = [r for r in records if r["trace_id"] in ids]
        if narrowed:
            kept = narrowed
    kept = [r for r in kept if r["dur"] > 0 or r["span_id"]]
    if not kept:
        return {"transfer": transfer_id, "wall_s": 0.0,
                "attributed_pct": 0.0, "spans": 0, "stages": {},
                "levers": [], "parts": []}
    index = {r["span_id"]: r for r in kept if r["span_id"]}
    children: dict[int, list] = {}
    roots: list[dict] = []
    for r in kept:
        if r["parent_id"] and r["parent_id"] in index \
                and r["parent_id"] != r["span_id"]:
            children.setdefault(r["parent_id"], []).append(r)
        else:
            roots.append(r)
    start = min(r["t0"] for r in kept)
    end = max(r["end"] for r in kept)
    wall = max(0.0, end - start)
    stages = _sweep(roots, children, start, end)
    attributed = sum(stages.values())

    # per-part critical paths: each "part" span re-walked in isolation
    parts = []
    for r in kept:
        if r["name"] == "part" and r["dur"] > 0:
            pstages: dict[str, float] = {}
            _walk(r, r["end"], children, pstages, set())
            top = max(pstages.items(), key=lambda kv: kv[1])[0] \
                if pstages else "-"
            label = r["args"].get("path") or r["args"].get("name") \
                or r["args"].get("part") or r["span_id"]
            parts.append({"part": str(label),
                          "wall_s": round(r["dur"], 6),
                          "top_stage": top})
    parts.sort(key=lambda p: -p["wall_s"])

    ordered = {
        s: {"seconds": round(stages[s], 6),
            "pct": round(100.0 * stages[s] / wall, 2) if wall else 0.0}
        for s in STAGES_ORDER if stages.get(s, 0.0) > 0}
    levers = [
        {"stage": s, "pct": ordered[s]["pct"],
         "hint": _LEVER_HINTS.get(s, "")}
        for s in sorted(ordered, key=lambda s: -ordered[s]["seconds"])
    ][:3]
    return {
        "transfer": transfer_id,
        "wall_s": round(wall, 6),
        "spans": len(kept),
        "processes": len({r["proc"] for r in kept}),
        "attributed_pct": round(100.0 * attributed / wall, 2)
        if wall else 0.0,
        "stages": ordered,
        "levers": levers,
        "parts": parts[:5],
    }


def format_report(report: dict) -> str:
    """Render one `trtpu explain` frame."""
    lines = [
        f"critical path: transfer={report.get('transfer') or '-'}  "
        f"wall={report.get('wall_s', 0.0):.3f}s  "
        f"spans={report.get('spans', 0)} "
        f"({report.get('processes', 0)} process(es))  "
        f"attributed={report.get('attributed_pct', 0.0):.1f}%"]
    stages = report.get("stages", {})
    if stages:
        lines.append(f"{'stage':<18} {'seconds':>10} {'pct':>7}")
        for s, row in stages.items():
            lines.append(f"{s:<18} {row['seconds']:>10.3f} "
                         f"{row['pct']:>6.1f}%")
    levers = report.get("levers", [])
    if levers:
        lines.append("top levers:")
        for i, lv in enumerate(levers, 1):
            lines.append(f"  {i}. [{lv['stage']} {lv['pct']:.1f}%] "
                         f"{lv['hint']}")
    parts = report.get("parts", [])
    if parts:
        lines.append("slowest parts:")
        for p in parts:
            lines.append(f"  {p['part']:<40} {p['wall_s']:>9.3f}s  "
                         f"top={p['top_stage']}")
    return "\n".join(lines)
