"""Per-transfer resource ledger: who spent what, attributed causally.

`DeviceTelemetry` (stats/trace.py) answers "what did the process
spend"; after PR 8 put 120 concurrent transfers from N tenants on one
process, that's telemetry soup — "which tenant burned the link" and
"where did transfer X's 2 seconds go" have no answer in global
counters.  This module is the attribution plane: a contextvar carries
the active `(transfer_id, tenant, part)` scope, and every resource
event recorded while that scope is active lands in that scope's ledger
entry.  The fleet lane sets (transfer_id, tenant) around a ticket run;
the snapshot engine narrows to the part; worker threads adopt the
submitting scope exactly like trace contexts (stats/trace.py adopted).

Conservation is the design invariant: the process-global
`DeviceTelemetry` counters route THROUGH `LEDGER.add` (see the
record_* methods in stats/trace.py), so for the shared fields

    sum over all ledger entries (incl. the unattributed bucket)
        == the global DeviceTelemetry counter

holds by construction, and the `/debug/ledger` payload carries the
reconciliation so drift (a resource event recorded outside the ledger
hook) is visible immediately.  Work with no scope set — module
warmups, stray background threads — lands in the `(-, -, -)`
unattributed entry rather than vanishing.

Cardinality is bounded in two tiers (the fleet runs 100k+ transfers
through one process over its lifetime):

- at most `TRANSFERIA_TPU_LEDGER_ENTRIES` (default 4096) live
  (transfer, tenant, part) entries; overflow folds into a per-tenant
  `~overflow` entry (totals stay conserved, per-transfer detail is
  shed oldest-first);
- the prometheus fold (`fold_into`) publishes aggregate ledger_*
  counters plus per-TENANT counters for at most `MAX_PROM_TENANTS`
  tenants (name-mangled, REST fold into `ledger_tenant_other_*`) —
  per-transfer series never reach /metrics.
"""

from __future__ import annotations

import contextvars
import os
import re
import threading
import time
import weakref
from typing import NamedTuple, Optional

from transferia_tpu.runtime import knobs, lockwatch

UNATTRIBUTED = "-"


def _telemetry_snapshot() -> dict:
    """Lazy import: trace.py imports this module back for the
    record-through-ledger hooks."""
    from transferia_tpu.stats.trace import TELEMETRY

    return TELEMETRY.snapshot()

# every accountable resource dimension; append-only (snapshot shape is
# a wire format for /debug/ledger and `trtpu top`)
FIELDS = (
    "rows_in", "rows_out", "bytes_in", "bytes_out",
    "h2d_bytes", "d2h_bytes",
    "h2d_encoded_bytes", "h2d_raw_equiv_bytes",
    "launches", "compiles", "compile_seconds", "kernel_seconds",
    "decode_wait_seconds", "queue_wait_seconds",
    "retries", "lease_steals", "chaos_fires",
    # staged two-phase sink commits (abstract/commit.py): granted
    # publish decisions, fenced (stale-epoch) attempts, and rows the
    # staging dedup window dropped before publish
    "commits", "commit_fences", "dedup_rows_dropped",
    # pool-once encoded Arrow wire (interchange/convert
    # EncodedWireState): dict pool bytes shipped (once per stream) vs
    # codes-only batch bytes
    "pool_bytes_shipped", "codes_bytes_shipped",
)

_INT_FIELDS = frozenset(f for f in FIELDS if not f.endswith("_seconds"))

MAX_PROM_TENANTS = 32
# the per-tenant prometheus surface: bounded to the dimensions an
# operator alerts on (full detail lives on /debug/ledger)
_PROM_TENANT_FIELDS = ("rows_out", "bytes_out", "h2d_bytes",
                       "launches", "retries", "chaos_fires")


class LedgerKey(NamedTuple):
    transfer_id: str
    tenant: str
    part: str


_scope: "contextvars.ContextVar[Optional[LedgerKey]]" = \
    contextvars.ContextVar("trtpu_ledger_scope", default=None)


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", name) or "_"


class _Entry:
    __slots__ = ("values", "first_seen", "last_seen")

    def __init__(self):
        self.values = dict.fromkeys(FIELDS, 0)
        self.first_seen = time.time()
        self.last_seen = self.first_seen


class ResourceLedger:
    """The process-wide attribution table (module singleton `LEDGER`).

    `add(**fields)` attributes to the ambient scope; `add_for(...)`
    attributes to an explicit key (callers that know the identity but
    run outside the scope, e.g. the fleet scheduler rebalancing a
    ticket under its own lock).  Both are cheap enough for per-batch
    call sites: one contextvar read + one dict update under a lock."""

    def __init__(self, max_entries: Optional[int] = None):
        if max_entries is None:
            max_entries = knobs.env_int(
                "TRANSFERIA_TPU_LEDGER_ENTRIES", 4096)
        self.max_entries = max(8, max_entries)
        self._lock = lockwatch.named_lock("ledger.records")
        # serializes fold_into: concurrent folds into one target would
        # both read the same baseline and double-publish the delta
        # (DeviceTelemetry.fold_into holds its lock for the same
        # reason).  Separate from _lock so folds never stall record_*.
        self._fold_lock = lockwatch.named_lock("ledger.fold")
        self._entries: dict[LedgerKey, _Entry] = {}
        # insertion order for evictions; a dict for O(1) removal
        self._order: dict[LedgerKey, None] = {}
        self._folded_entries = 0  # entries shed into ~overflow
        # per-target fold baselines (same pattern as DeviceTelemetry):
        # weak keys so a discarded Metrics registry frees its baseline
        # instead of leaking it — and, worse, a reused id() would hand
        # a FRESH registry a dead registry's baselines, silently
        # suppressing its counter deltas
        self._prev_folds: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()

    # -- scope ---------------------------------------------------------------
    def context(self, transfer_id: Optional[str] = None,
                tenant: Optional[str] = None,
                part: Optional[str] = None) -> "_Scope":
        """Enter an attribution scope; unset fields INHERIT from the
        ambient scope (the fleet lane sets transfer+tenant, the part
        uploader narrows to the part without knowing the tenant)."""
        return _Scope(transfer_id, tenant, part)

    @staticmethod
    def current_key() -> Optional[LedgerKey]:
        """The ambient scope — capture before a thread hop, re-enter
        on the worker with `adopted()`."""
        return _scope.get()

    @staticmethod
    def adopted(key: Optional[LedgerKey]) -> "_Adopted":
        return _Adopted(key)

    # -- recording -----------------------------------------------------------
    def add(self, **fields) -> None:
        key = _scope.get()
        if key is None:
            key = LedgerKey(UNATTRIBUTED, UNATTRIBUTED, UNATTRIBUTED)
        self._add(key, fields)

    def add_for(self, transfer_id: str, tenant: str = UNATTRIBUTED,
                part: str = UNATTRIBUTED, **fields) -> None:
        self._add(LedgerKey(transfer_id, tenant, part), fields)

    def _add(self, key: LedgerKey, fields: dict) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                if len(self._entries) >= self.max_entries:
                    key = self._evict_locked(key)
                    e = self._entries.get(key)
                if e is None:
                    e = self._entries[key] = _Entry()
                    self._order[key] = None
            vals = e.values
            for name, v in fields.items():
                vals[name] += v
            e.last_seen = time.time()

    def _evict_locked(self, incoming: LedgerKey) -> LedgerKey:
        """At the cardinality bound: fold the OLDEST per-part entry of
        some transfer into its tenant's `~overflow` entry and route the
        incoming key there too when no room frees up.  Totals (and so
        conservation) are preserved exactly — only per-transfer detail
        degrades."""
        # iterate a copy: the body removes `old` and may append the
        # `~overflow` sink, either of which would skew a live iterator
        # off the oldest-first order this method promises
        for old in list(self._order):
            if old.transfer_id in (UNATTRIBUTED, "~overflow"):
                continue
            dst = LedgerKey("~overflow", old.tenant, UNATTRIBUTED)
            src = self._entries.pop(old)
            del self._order[old]
            sink = self._entries.get(dst)
            if sink is None:
                sink = self._entries[dst] = _Entry()
                self._order[dst] = None
            for name, v in src.values.items():
                sink.values[name] += v
            self._folded_entries += 1
            if len(self._entries) < self.max_entries:
                return incoming
        return LedgerKey("~overflow", incoming.tenant, UNATTRIBUTED)

    # -- reading -------------------------------------------------------------
    def snapshot(self) -> dict:
        """The `/debug/ledger` payload: per-transfer entries (parts
        aggregated + listed), per-tenant rollups, grand totals, and the
        conservation reconciliation against DeviceTelemetry."""
        # telemetry is read BEFORE the entries (and telemetry records
        # route through the ledger first), so at this point the ledger
        # can only lead the counters, never trail them — see
        # conservation() for what each drift sign means
        tel = _telemetry_snapshot()
        with self._lock:
            items = [(k, dict(e.values), e.first_seen, e.last_seen)
                     for k, e in self._entries.items()]
            folded = self._folded_entries
        transfers: dict[str, dict] = {}
        tenants: dict[str, dict] = {}
        totals = dict.fromkeys(FIELDS, 0)
        for key, vals, first, last in items:
            tr = transfers.setdefault(key.transfer_id, {
                "tenant": key.tenant, "parts": 0,
                **dict.fromkeys(FIELDS, 0)})
            if tr["tenant"] != key.tenant:
                # the ~overflow row aggregates entries from several
                # tenants; don't attribute them all to the first one
                # (per-tenant rollups below stay exact)
                tr["tenant"] = "~multiple"
            tr["parts"] += 1 if key.part != UNATTRIBUTED else 0
            tn = tenants.setdefault(key.tenant, {
                "transfers": set(), **dict.fromkeys(FIELDS, 0)})
            tn["transfers"].add(key.transfer_id)
            for name, v in vals.items():
                tr[name] += v
                tn[name] += v
                totals[name] += v
        for tn in tenants.values():
            tn["transfers"] = len(tn["transfers"])
        for agg in (totals, *transfers.values(), *tenants.values()):
            for name in FIELDS:
                if name not in _INT_FIELDS:
                    agg[name] = round(agg[name], 6)
        return {
            "entries": len(items),
            "max_entries": self.max_entries,
            "overflow_folded": folded,
            "transfers": dict(sorted(transfers.items())),
            "tenants": dict(sorted(tenants.items())),
            "totals": totals,
            "conservation": self.conservation(totals, tel=tel),
        }

    def conservation(self, totals: Optional[dict] = None,
                     tel: Optional[dict] = None) -> dict:
        """Reconcile ledger totals against the global DeviceTelemetry
        counters for the fields that route through the ledger hooks.

        drift == 0 for every field is the quiescent invariant the
        tests pin.  On a live poll the ledger may transiently LEAD the
        counters (records bill the ledger first, and telemetry is read
        first here), so negative drift is in-flight activity and still
        `ok`; positive drift — a telemetry increment the attribution
        hooks never saw — is the violation this check exists to catch.
        """
        if tel is None:
            tel = _telemetry_snapshot()
        if totals is None:
            totals = self.snapshot()["totals"]
        out = {}
        for lf, tf in (("h2d_bytes", "h2d_bytes"),
                       ("d2h_bytes", "d2h_bytes"),
                       ("h2d_encoded_bytes", "h2d_encoded_bytes"),
                       ("h2d_raw_equiv_bytes", "h2d_raw_equiv_bytes"),
                       ("launches", "device_launches"),
                       ("compiles", "compile_events")):
            drift = tel[tf] - totals[lf]
            out[lf] = {"ledger": totals[lf], "telemetry": tel[tf],
                       "drift": drift}
        out["ok"] = all(v["drift"] <= 0 for v in out.values()
                        if isinstance(v, dict))
        return out

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._order.clear()
            self._folded_entries = 0
            self._prev_folds.clear()

    # -- prometheus ----------------------------------------------------------
    def _rollups(self) -> tuple[dict, dict, int, int]:
        """(totals, per-tenant sums, entries, overflow_folded) — the
        fold_into subset of snapshot(): no per-transfer aggregation, no
        sorting of transfers, no telemetry reconciliation read.  This
        runs on every part completion and heartbeat; the full snapshot
        is the /debug/ledger surface only."""
        with self._lock:
            items = [(k.tenant, dict(e.values))
                     for k, e in self._entries.items()]
            folded = self._folded_entries
        totals = dict.fromkeys(FIELDS, 0)
        tenants: dict[str, dict] = {}
        for tenant, vals in items:
            tn = tenants.setdefault(tenant, dict.fromkeys(FIELDS, 0))
            for name, v in vals.items():
                tn[name] += v
                totals[name] += v
        return totals, tenants, len(items), folded

    def fold_into(self, metrics) -> None:
        """Delta-fold into a Metrics registry: aggregate ledger_*
        counters + bounded per-tenant counters (see module doc).
        Idempotent per target, like DeviceTelemetry.fold_into, and
        serialized under _fold_lock — two part-completion threads
        folding into the same registry would otherwise read one
        baseline and each publish the full delta."""
        with self._fold_lock:
            totals, tenants, entries, folded = self._rollups()
            prev = self._prev_folds.setdefault(metrics, {})
            for name in FIELDS:
                self._fold_counter(metrics, f"ledger_{name}",
                                   totals[name], prev)
            ranked = [(t, v) for t, v in tenants.items()
                      if t != UNATTRIBUTED]
            ranked.sort(key=lambda kv: -kv[1]["bytes_out"])
            # bounded per-tenant series: top MAX_PROM_TENANTS by
            # bytes_out get named counters; the rest stay on
            # /debug/ledger only (the aggregate ledger_* counters
            # above still include them)
            for tenant, vals in ranked[:MAX_PROM_TENANTS]:
                label = _sanitize(tenant)
                for name in _PROM_TENANT_FIELDS:
                    self._fold_counter(
                        metrics, f"ledger_tenant_{label}_{name}",
                        vals[name], prev)
            metrics.gauge("ledger_entries").set(entries)
            metrics.gauge("ledger_overflow_folded").set(folded)

    @staticmethod
    def _fold_counter(metrics, name: str, value, prev: dict) -> None:
        delta = value - prev.get(name, 0)
        if delta > 0:
            metrics.counter(name).inc(delta)
        prev[name] = max(prev.get(name, 0), value)


class _Scope:
    __slots__ = ("_fields", "_token")

    def __init__(self, transfer_id, tenant, part):
        self._fields = (transfer_id, tenant, part)
        self._token = None

    def __enter__(self):
        base = _scope.get()
        transfer_id, tenant, part = self._fields
        key = LedgerKey(
            transfer_id if transfer_id is not None
            else (base.transfer_id if base else UNATTRIBUTED),
            tenant if tenant is not None
            else (base.tenant if base else UNATTRIBUTED),
            part if part is not None
            else (base.part if base else UNATTRIBUTED),
        )
        self._token = _scope.set(key)
        return key

    def __exit__(self, *exc):
        if self._token is not None:
            _scope.reset(self._token)
            self._token = None
        return False


class _Adopted:
    __slots__ = ("_key", "_token")

    def __init__(self, key: Optional[LedgerKey]):
        self._key = key
        self._token = None

    def __enter__(self):
        if self._key is not None:
            self._token = _scope.set(self._key)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _scope.reset(self._token)
            self._token = None
        return False


LEDGER = ResourceLedger()


# -- `trtpu top` rendering ---------------------------------------------------

_TOP_COLS = (
    ("transfer", 22), ("tenant", 10), ("rows_in", 9), ("rows_out", 9),
    ("mb_in", 8), ("mb_out", 8), ("h2d_mb", 8), ("launch", 7),
    ("wait_s", 7), ("retry", 6), ("steal", 6), ("fires", 6),
    ("commit", 7), ("fence", 6), ("dedup", 6),
)


def format_top(snapshot: dict, limit: int = 20) -> str:
    """Render one `trtpu top` frame from a /debug/ledger snapshot."""
    lines = []
    tot = snapshot["totals"]
    cons = snapshot.get("conservation", {})
    lines.append(
        f"ledger: {snapshot['entries']} entries "
        f"({snapshot['overflow_folded']} folded)  "
        f"rows {tot['rows_in']}→{tot['rows_out']}  "
        f"h2d {tot['h2d_bytes'] / 1e6:.1f}MB  "
        f"launches {tot['launches']}  "
        f"commits {tot.get('commits', 0)} "
        f"({tot.get('commit_fences', 0)} fenced, "
        f"{tot.get('dedup_rows_dropped', 0)} deduped)  "
        f"conservation {'OK' if cons.get('ok') else 'DRIFT'}")
    tenants = snapshot.get("tenants", {})
    if tenants:
        roll = "  ".join(
            f"{t}[{v['transfers']}tx "
            f"{v['bytes_out'] / 1e6:.1f}MB out]"
            for t, v in sorted(
                tenants.items(),
                key=lambda kv: -kv[1]["bytes_out"])[:8])
        lines.append(f"tenants: {roll}")
    slo_view = snapshot.get("slo")
    if isinstance(slo_view, dict) and slo_view.get("objectives"):
        burning = slo_view.get("burning", []) or []
        lags = [row.get("lag_ms") for row in
                (slo_view.get("watermarks", {}) or {}).values()
                if isinstance(row.get("lag_ms"), (int, float))]
        lines.append(
            f"slo: {'BURNING ' + ','.join(burning) if burning else 'OK'}"
            f" ({len(slo_view['objectives'])} objectives)"
            + (f"  max lag {max(lags):.0f}ms" if lags else ""))
    header = " ".join(f"{name:>{w}}" for name, w in _TOP_COLS)
    lines.append(header)
    rows = sorted(snapshot.get("transfers", {}).items(),
                  key=lambda kv: -(kv[1]["bytes_out"]
                                   + kv[1]["bytes_in"]))
    for transfer_id, v in rows[:limit]:
        wait = v["decode_wait_seconds"] + v["queue_wait_seconds"]
        cells = (transfer_id[:22], v["tenant"][:10], v["rows_in"],
                 v["rows_out"], f"{v['bytes_in'] / 1e6:.1f}",
                 f"{v['bytes_out'] / 1e6:.1f}",
                 f"{v['h2d_bytes'] / 1e6:.1f}", v["launches"],
                 f"{wait:.2f}", v["retries"], v["lease_steals"],
                 v["chaos_fires"], v.get("commits", 0),
                 v.get("commit_fences", 0),
                 v.get("dedup_rows_dropped", 0))
        lines.append(" ".join(
            f"{c:>{w}}" for c, (_n, w) in zip(cells, _TOP_COLS)))
    if len(rows) > limit:
        lines.append(f"... {len(rows) - limit} more transfers")
    return "\n".join(lines)
