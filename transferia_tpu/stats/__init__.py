"""Typed metric bundles (reference: pkg/stats/, internal/metrics/).

Thin facade over prometheus_client with a per-pipeline registry so tests can
read values without global state.  Bundles mirror the reference's
SourceStats / SinkerStats / MiddlewareBuffererStats etc. (pkg/stats/source.go:11,
sinker.go:12, middleware.go) and are exposed on the CLI's /metrics port.
"""

from transferia_tpu.stats.registry import Metrics, SinkerStats, SourceStats, \
    BuffererStats, DeviceStats, ReplicationStats, TableStats, TransformStats

__all__ = [
    "Metrics", "SourceStats", "SinkerStats", "BuffererStats",
    "DeviceStats", "ReplicationStats", "TableStats", "TransformStats",
]
