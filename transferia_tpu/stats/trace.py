"""End-to-end pipeline tracing: spans, device telemetry, Perfetto export.

The sampling profiler (stats/profiler.py) answers "which frame burns
CPU"; stagetimer answers "how much wall per stage".  Neither shows the
*timeline*: whether device waits overlap host packing, where a batch
stalls between parsequeue and the sink, or when an XLA recompile lands
inside the measured window.  This module records begin/end spans into a
bounded ring buffer and exports Chrome trace-event JSON loadable in
Perfetto / `chrome://tracing` — the span-level attribution Thallus-style
transport analysis needs (PAPERS.md) and the per-stage transfer
accounting the Arrow Flight benchmarking work shows wire-speed columnar
systems live or die on.

Design constraints:

- near-zero overhead when disabled: `span()` does ONE module-bool check
  and returns a shared no-op singleton — no allocation, no lock;
- thread-safe when enabled: per-thread span stacks (nesting + self-time
  attribution need no lock), one lock only around ring appends;
- monotonic clocks (`time.perf_counter`), microsecond timestamps
  relative to the capture epoch (what the trace-event format expects);
- bounded memory: a `deque(maxlen=capacity)` ring — a forgotten-enabled
  tracer on a long replication run costs a fixed buffer, never OOM.

Span taxonomy (see ARCHITECTURE.md "Tracing & device telemetry"):
roots `part` / `batch` / `replication_attempt` carry identity args
(transfer_id, table, part, batch_seq); stage spans `source_decode`,
`pivot`, `pack`, `device_dispatch`, `device_wait`, `host_post`,
`transform`, `serialize`, `bufferer_flush`, `sink_push`, `sink` nest
under them.  `device_dispatch`/`device_wait` carry byte counts as args.
`decode_readahead` spans live on the prefetcher worker threads
(providers/readahead.py) — decode running there shows as its own
track, overlapping the part's downstream spans.

`DeviceTelemetry` is the always-on counter half: H2D/D2H bytes and
transfer counts, device launches, XLA compile events (hooked via jax's
monitoring events — fired exactly on jit-cache misses that reach the
backend compiler), and per-kernel wall time.  It folds into the
prometheus `Metrics` facade via `fold_into()` (stats/registry.py
DeviceStats).

Causality (PR 10): every recorded span carries (trace_id, span_id,
parent_id).  The active span context rides a `contextvars.ContextVar`,
so nesting links parent→child automatically on one thread, and the
capture/adopt pair carries it across thread hops (readahead workers,
upload-part pool, fleet worker slots) and — via `wire_format` /
`parse_wire` — across the Flight gRPC metadata and the shm framing
metadata.  The Chrome export emits flow events for every parent link
that crosses a thread, so one transfer renders as a single
causally-linked timeline in Perfetto even when its spans live on six
threads.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
import weakref
from collections import deque
from typing import NamedTuple, Optional

DEFAULT_CAPACITY = 200_000  # spans; ~100 bytes each -> bounded ~20MB

_enabled = False
_epoch = 0.0
_lock = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_CAPACITY)
_tls = threading.local()
# lifetime count of ring appends (NOT ring length: the deque evicts).
# The obs-segment exporter (stats/fleetobs.py) uses this as its delta
# mark — "how many new records since my last export" — without the
# record tuples themselves needing sequence fields.
_recorded = 0


class SpanContext(NamedTuple):
    """The propagation token: which trace, which span is 'current'.

    Immutable and tiny on purpose — it crosses thread boundaries by
    value and the wire as `"<trace_id>:<span_id>"`."""

    trace_id: int
    span_id: int


# span/trace ids are process-unique counters salted by (host, pid) so
# ids minted on both ends of an in-host wire (Flight loopback, shm
# handoff between forked workers) — or by two pid-1 containers on
# DIFFERENT hosts feeding one merged fleet timeline
# (stats/fleetobs.py) — never collide in one merged view
import socket as _socket
import zlib as _zlib

_ids = itertools.count(
    ((_zlib.crc32(_socket.gethostname().encode()) & 0xFFFF) << 48)
    + ((os.getpid() & 0xFFFF) << 32) + 1)
_ctx: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("trtpu_trace_ctx", default=None)


class _NoopSpan:
    """Shared disabled-path singleton: falsy, allocation-free."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False

    def add(self, **args) -> None:
        pass

    def context(self) -> Optional[SpanContext]:
        return None


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "args", "_t0", "_child",
                 "trace_id", "span_id", "parent_id", "_token")

    def __init__(self, name: str, args: Optional[dict] = None):
        self.name = name
        self.args = args
        self._t0 = 0.0
        self._child = 0.0  # seconds covered by nested spans
        self.trace_id = 0
        self.span_id = 0
        self.parent_id = 0
        self._token = None

    def __bool__(self):
        return True

    def add(self, **args) -> None:
        """Attach args discovered mid-span (bytes moved, row counts)."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)

    def context(self) -> SpanContext:
        """This span's propagation token (valid after __enter__)."""
        return SpanContext(self.trace_id, self.span_id)

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        parent = _ctx.get()
        self.span_id = next(_ids)
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = self.span_id  # a new root starts its trace
        self._token = _ctx.set(SpanContext(self.trace_id, self.span_id))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        dur = t1 - self._t0
        if self._token is not None:
            _ctx.reset(self._token)
            self._token = None
        stack = _tls.stack
        stack.pop()
        depth = len(stack)
        if depth:
            stack[-1]._child += dur
        t = threading.current_thread()
        global _recorded
        with _lock:
            _recorded += 1
            _ring.append((
                self.name, t.ident, t.name,
                self._t0 - _epoch, dur, max(0.0, dur - self._child),
                depth, self.args,
                self.trace_id, self.span_id, self.parent_id,
            ))
        return False


def enable(on: bool = True, capacity: Optional[int] = None) -> None:
    global _enabled, _epoch, _ring
    if capacity is not None and capacity != _ring.maxlen:
        with _lock:
            _ring = deque(_ring, maxlen=capacity)
    if on and not _enabled and _epoch == 0.0:
        _epoch = time.perf_counter()
    _enabled = on
    if on:
        install_jit_hooks()


def enabled() -> bool:
    return _enabled


def reset() -> None:
    """Clear the span ring and restart the capture epoch.  Does NOT
    touch TELEMETRY: the device counters are cumulative process state
    (a /metrics scrape depends on them); reset those explicitly."""
    global _epoch
    with _lock:
        _ring.clear()
    _epoch = time.perf_counter()


def span(name: str, **args):
    """The ONE per-site call.  Disabled: one bool check, shared no-op
    singleton back (hot sites attach args via `if sp: sp.add(...)` so
    the disabled path allocates nothing)."""
    if not _enabled:
        return _NOOP
    return Span(name, args or None)


def instant(name: str, ctx: Optional[SpanContext] = None,
            **args) -> None:
    """Point event (XLA compiles, retries, chaos fires).  Lands ON the
    active span: the recorded tuple carries the current trace/span ids
    (or an explicit `ctx`), so Perfetto shows the instant inside the
    span that was running when it fired."""
    if not _enabled:
        return
    at = ctx if ctx is not None else _ctx.get()
    trace_id = at.trace_id if at else 0
    parent_id = at.span_id if at else 0
    t = threading.current_thread()
    global _recorded
    with _lock:
        _recorded += 1
        _ring.append((name, t.ident, t.name,
                      time.perf_counter() - _epoch, 0.0, 0.0, -1,
                      args or None, trace_id, 0, parent_id))


def complete(name: str, t0: float, dur: float,
             parent: Optional[SpanContext] = None, **args) -> None:
    """Record a span RETROACTIVELY from wall measurements already taken
    (`t0` in time.perf_counter seconds).  This is how queue-wait style
    intervals — observed only once they end, on whatever thread ends
    them — still land as real spans on the owning trace (fleet ticket
    queue wait, admission→dispatch)."""
    if not _enabled:
        return
    at = parent if parent is not None else _ctx.get()
    span_id = next(_ids)
    trace_id = at.trace_id if at else span_id
    parent_id = at.span_id if at else 0
    t = threading.current_thread()
    global _recorded
    with _lock:
        _recorded += 1
        _ring.append((name, t.ident, t.name, t0 - _epoch, dur,
                      dur, 0, args or None, trace_id, span_id,
                      parent_id))


def current() -> Optional[str]:
    """Innermost active span name on this thread (tests, debugging)."""
    stack = getattr(_tls, "stack", None)
    return stack[-1].name if stack else None


def current_context() -> Optional[SpanContext]:
    """The active span's propagation token (None when tracing is off or
    no span is open).  Capture this BEFORE handing work to another
    thread; the worker re-enters it with `adopted()`."""
    if not _enabled:
        return None
    return _ctx.get()


class adopted:
    """Re-enter a captured SpanContext on another thread:

        ctx = trace.current_context()          # submitting thread
        ...
        with trace.adopted(ctx):               # worker thread
            with trace.span("decode_readahead"):  # parents to ctx
                ...

    A None ctx is a no-op, so call sites never need to branch on
    whether tracing was on at capture time."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[SpanContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx is not None and _enabled:
            self._token = _ctx.set(self._ctx)
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            _ctx.reset(self._token)
            self._token = None
        return False


def wire_format(ctx: Optional[SpanContext]) -> str:
    """Serialize a context for a wire hop (Flight gRPC metadata, shm
    framing metadata).  Empty string when there is nothing to carry."""
    if ctx is None:
        return ""
    return f"{ctx.trace_id}:{ctx.span_id}"


def parse_wire(s) -> Optional[SpanContext]:
    """Inverse of wire_format; tolerant of junk (a malformed header
    must never fail the data-plane call it rode in on)."""
    if not s:
        return None
    if isinstance(s, bytes):
        s = s.decode("ascii", "replace")
    trace_s, _, span_s = s.partition(":")
    try:
        return SpanContext(int(trace_s), int(span_s))
    except ValueError:
        return None


def spans() -> list[tuple]:
    """Raw recorded tuples (name, tid, tname, t0_s, dur_s, self_s,
    depth, args, trace_id, span_id, parent_id) — depth -1 marks
    instants (span_id 0, parent_id = the span they fired on)."""
    with _lock:
        return list(_ring)


def record_count() -> int:
    """Lifetime number of records appended (monotonic; survives ring
    eviction).  `spans()[-(record_count() - mark):]` is the exporter's
    bounded delta since `mark` — records evicted past the ring capacity
    are simply lost, which the obs plane reports as `spans_dropped`."""
    with _lock:
        return _recorded


def spans_with_count() -> tuple[int, list]:
    """(record_count, ring snapshot) under ONE lock hold — the obs
    exporter's delta window.  Reading the two separately would let
    concurrent appends displace the oldest records of the intended
    window out of the tail slice, silently losing them while
    `spans_dropped` stays 0."""
    with _lock:
        return _recorded, list(_ring)


def epoch_unix() -> float:
    """Wall-clock time of this process's capture epoch (the zero point
    of every recorded t0).  Cross-process merge (stats/fleetobs.py)
    aligns N processes' timelines by shifting each one's spans by its
    exported epoch — perf_counter zeros are process-arbitrary, wall
    clocks are the only shared axis."""
    return time.time() - (time.perf_counter() - _epoch)


# -- export -----------------------------------------------------------------

def export_chrome_trace() -> dict:
    """Chrome trace-event JSON (dict; json.dump it).  Loadable in
    Perfetto and chrome://tracing: "X" complete events with tid/ts/dur
    in microseconds, thread-name metadata, instants as "i", and flow
    events ("s"/"f" pairs keyed by the child span id) for every
    parent→child link that crosses a thread — the arrows that stitch a
    readahead worker's decode, a fleet lane's run, and a Flight
    server-side span onto the submitting timeline."""
    recorded = spans()
    events: list[dict] = []
    seen_threads: dict[int, str] = {}
    # span_id -> (tid, ts_us) for flow-arrow sources
    located: dict[int, tuple[int, float]] = {}
    for rec in recorded:
        name, tid, tname, t0, dur, _self_s, depth, args = rec[:8]
        trace_id, span_id, parent_id = rec[8:11]
        if tid not in seen_threads:
            seen_threads[tid] = tname
        ts = round(t0 * 1e6, 1)
        ev = {
            "name": name,
            "cat": "pipeline",
            "pid": 1,
            "tid": tid,
            "ts": ts,
        }
        if depth < 0:
            ev["ph"] = "i"
            ev["s"] = "t"
        else:
            ev["ph"] = "X"
            ev["dur"] = round(dur * 1e6, 1)
            if span_id:
                located[span_id] = (tid, ts)
        if args:
            ev["args"] = {k: _jsonable(v) for k, v in args.items()}
        if trace_id:
            ids = ev.setdefault("args", {})
            ids["trace_id"] = trace_id
            if span_id:
                ids["span_id"] = span_id
            if parent_id:
                ids["parent_id"] = parent_id
        events.append(ev)
    flows: list[dict] = []
    for rec in recorded:
        _name, tid, _tn, t0, _dur, _s, depth, _a = rec[:8]
        _trace_id, span_id, parent_id = rec[8:11]
        if depth < 0 or not parent_id:
            continue
        src = located.get(parent_id)
        if src is None or src[0] == tid:
            continue  # same-thread nesting needs no arrow
        ts = round(t0 * 1e6, 1)
        flows.append({"name": "causal", "cat": "flow", "ph": "s",
                      "id": span_id, "pid": 1, "tid": src[0],
                      "ts": src[1]})
        flows.append({"name": "causal", "cat": "flow", "ph": "f",
                      "bp": "e", "id": span_id, "pid": 1, "tid": tid,
                      "ts": ts})
    meta = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "transferia-tpu"}},
    ]
    for tid, tname in sorted(seen_threads.items()):
        meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                     "tid": tid, "args": {"name": tname}})
    counters = TELEMETRY.snapshot()
    return {
        "traceEvents": meta + events + flows,
        "displayTimeUnit": "ms",
        "otherData": {"device_telemetry": counters},
    }


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


def write_chrome_trace(path: str) -> int:
    """Dump the trace to a file; returns the number of events."""
    doc = export_chrome_trace()
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return len(doc["traceEvents"])


def stage_summary(wall_seconds: Optional[float] = None) -> dict:
    """Per-stage aggregation: calls, p50/p99 ms, total and self seconds,
    bytes moved (summed from span `bytes` args), plus wall span and the
    overlap factor (sum of self-times / wall — >1 means stages overlap
    across threads; the ratio between stages is the signal)."""
    recorded = [s for s in spans() if s[6] >= 0]
    per: dict[str, dict] = {}
    t_min, t_max = None, None
    for name, _tid, _tn, t0, dur, self_s, _depth, args in (
            s[:8] for s in recorded):
        d = per.setdefault(name, {"calls": 0, "total_s": 0.0,
                                  "self_s": 0.0, "bytes": 0,
                                  "durs": []})
        d["calls"] += 1
        d["total_s"] += dur
        d["self_s"] += self_s
        d["durs"].append(dur)
        if args and isinstance(args.get("bytes"), (int, float)):
            d["bytes"] += int(args["bytes"])
        t_min = t0 if t_min is None else min(t_min, t0)
        t_max = max(t_max or 0.0, t0 + dur)
    wall = wall_seconds if wall_seconds else (
        (t_max - t_min) if recorded else 0.0)
    out: dict[str, dict] = {}
    for name, d in per.items():
        durs = sorted(d.pop("durs"))
        n = len(durs)
        d["p50_ms"] = round(durs[max(0, (n + 1) // 2 - 1)] * 1000, 3)
        d["p99_ms"] = round(
            durs[max(0, min(n - 1, int(0.99 * n)))] * 1000, 3)
        d["total_s"] = round(d["total_s"], 4)
        d["self_s"] = round(d["self_s"], 4)
        out[name] = d
    total_self = sum(d["self_s"] for d in out.values())
    return {
        "wall_s": round(wall, 4),
        "overlap_factor": round(total_self / wall, 3) if wall else 0.0,
        "stages": dict(sorted(out.items(),
                              key=lambda kv: -kv[1]["self_s"])),
    }


def format_summary(wall_seconds: Optional[float] = None) -> str:
    """Human table for `trtpu trace` / bench output."""
    s = stage_summary(wall_seconds)
    lines = [
        f"wall={s['wall_s']:.2f}s overlap_factor={s['overlap_factor']}",
        f"{'stage':<18} {'calls':>7} {'p50_ms':>9} {'p99_ms':>9} "
        f"{'total_s':>8} {'self_s':>8} {'bytes':>12}",
    ]
    for name, d in s["stages"].items():
        lines.append(
            f"{name:<18} {d['calls']:>7} {d['p50_ms']:>9.2f} "
            f"{d['p99_ms']:>9.2f} {d['total_s']:>8.2f} "
            f"{d['self_s']:>8.2f} {d['bytes']:>12}")
    tel = TELEMETRY.snapshot()
    if tel["device_launches"] or tel["compile_events"]:
        lines.append(
            f"device: launches={tel['device_launches']} "
            f"h2d={tel['h2d_bytes']}B/{tel['h2d_transfers']}x "
            f"d2h={tel['d2h_bytes']}B/{tel['d2h_transfers']}x "
            f"kernel={tel['kernel_seconds']:.3f}s "
            f"compiles={tel['compile_events']} "
            f"({tel['compile_seconds']:.2f}s)")
    return "\n".join(lines)


_capture_lock = threading.Lock()


def _capture_window(wait: float, cancelled: threading.Event,
                    lock_timeout: float) -> Optional[dict]:
    """One capture cycle (see capture_seconds for the policy).  Holds
    the capture lock for the whole window so concurrent requests can't
    clobber each other's enable-state restore.  The lock acquire is
    BOUNDED and the cancel flag is re-checked after it: an abandoned
    helper whose caller already 503'd must exit instead of queueing
    forever and then running a full reset/enable window nobody reads
    (that both leaked one blocked thread per timed-out request and
    kept clearing the span ring long after the clients were gone)."""
    if not _capture_lock.acquire(timeout=lock_timeout):
        return None
    try:
        if cancelled.is_set():
            return None
        if _enabled:
            time.sleep(wait)
            return export_chrome_trace()
        reset()
        enable(True)
        time.sleep(wait)
        doc = export_chrome_trace()
        enable(False)
        return doc
    finally:
        _capture_lock.release()


def capture_seconds(seconds: float,
                    deadline_grace: float = 15.0) -> dict:
    """The `/debug/trace?seconds=N` implementation.

    When tracing is already on (a `trtpu trace` run, bench --trace, or
    an operator who enabled it), the ring belongs to that capture:
    sample the window WITHOUT resetting — destroying an in-progress
    capture from a debug endpoint would be hostile.  Only a
    tracing-off process gets the reset/enable/disable cycle.

    The window runs on a dedicated HELPER thread with a hard deadline:
    a long capture must never pin the calling HTTP worker past
    `seconds + grace` (earlier versions slept on the request thread
    and, behind the shared capture lock or a keep-alive connection,
    starved every other `/debug/*` endpoint — including `/debug/fleet`
    mid kill-trial).  On deadline the helper is abandoned (it finishes
    its cycle and restores the enable state on its own) and
    TimeoutError is raised for the caller to turn into a 503."""
    wait = max(0.05, min(seconds, 60.0))
    # the helper may also queue behind another capture holding the
    # lock for up to a full window — budget one extra window for that
    deadline = 2 * wait + max(1.0, deadline_grace)
    out: dict = {}
    done = threading.Event()
    cancelled = threading.Event()

    def _run() -> None:
        try:
            out["doc"] = _capture_window(wait, cancelled,
                                         lock_timeout=deadline)
        except BaseException as e:  # surfaced on the caller
            out["err"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, name="trace-capture",
                         daemon=True)
    t.start()
    if not done.wait(deadline):
        cancelled.set()
        raise TimeoutError(
            f"trace capture exceeded its deadline "
            f"({wait:.0f}s window); helper abandoned")
    if "err" in out:
        raise out["err"]
    if out.get("doc") is None:
        # the helper lost the lock race past its own deadline or was
        # cancelled between acquire and check — same operator story
        raise TimeoutError(
            "trace capture could not take the capture lock "
            "(another capture window in flight)")
    return out["doc"]


def iter_chrome_trace_chunks(doc: Optional[dict] = None,
                             chunk_bytes: int = 64 * 1024):
    """Yield the Chrome trace JSON as a sequence of ~chunk_bytes string
    chunks — the `/debug/trace` endpoint streams these with chunked
    transfer encoding instead of materializing one multi-MB `bytes`
    (a 60s capture of a busy fleet is easily 100k+ events).  Events
    are accumulated up to the chunk size before yielding: the
    handler's wfile is unbuffered, so one yield per event would mean
    one syscall/TCP segment per ~100-byte event."""
    if doc is None:
        doc = export_chrome_trace()
    buf: list[str] = ['{"traceEvents":[']
    size = len(buf[0])
    first = True
    for ev in doc["traceEvents"]:
        piece = ("" if first else ",") + json.dumps(ev)
        first = False
        buf.append(piece)
        size += len(piece)
        if size >= chunk_bytes:
            yield "".join(buf)
            buf, size = [], 0
    other = {k: v for k, v in doc.items() if k != "traceEvents"}
    tail = json.dumps(other)
    # splice the remaining top-level keys after the events array
    buf.append("]" + ("," + tail[1:-1] if tail != "{}" else "") + "}")
    yield "".join(buf)


# -- device telemetry --------------------------------------------------------

def _ledger():
    """The attribution plane (stats/ledger.py LEDGER): device counters
    route their increments through it under the ambient (transfer,
    tenant, part) scope, which is what makes the ledger's conservation
    invariant hold by construction.  Lazy import: ledger lazily reads
    TELEMETRY back for reconciliation."""
    from transferia_tpu.stats.ledger import LEDGER

    return LEDGER


class DeviceTelemetry:
    """Always-on device-side counters (increments are per-dispatch, not
    per-row — a lock'd int add is noise next to a device launch).

    The sampling profiler cannot see any of these: device waits look
    like idle, H2D/D2H time hides inside jnp.asarray/np.asarray calls,
    and a jit recompile inside a measured window silently poisons it.
    On the measured v5e link (~70 ms/launch, 5-40 MB/s D2H) these
    counters ARE the performance model's inputs.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.h2d_bytes = 0
            self.h2d_transfers = 0
            self.d2h_bytes = 0
            self.d2h_transfers = 0
            self.device_launches = 0
            self.compile_events = 0
            self.compile_seconds = 0.0
            self.kernel_seconds = 0.0
            # compressed dispatch plane (ops/dispatch.py): actual bytes
            # staged vs what the raw wire would have shipped, plus the
            # dict-pool residency economics
            self.h2d_encoded_bytes = 0
            self.h2d_raw_equiv_bytes = 0
            self.dict_pool_hits = 0
            self.dict_pool_uploads = 0
            # pool interning (columnar/batch.intern_pool): producers
            # re-creating identical pool bytes converged on one object
            self.dict_pool_share_hits = 0
            # decode-buffer pinning decisions (parquet_native
            # _finish_bytearray): bytes a kept pool VIEW pins beyond the
            # pool itself vs bytes copied out to release the buffer
            self.dict_pool_pinned_bytes = 0
            self.dict_pool_copied_bytes = 0
            # dict-native pipeline honesty pair: columns handled in
            # their code+pool encoding end-to-end vs columns some
            # consumer flattened (Column._materialize) — a dict-heavy
            # snapshot that finishes with nonzero flat materializations
            # has a leak in a code-aware fast path
            self.lazy_dict_preserved = 0
            self.dict_flat_materializations = 0
            # per-target fold baselines: several pipelines may each
            # fold the (process-global) counters into their own
            # Metrics; one shared baseline would split deltas between
            # them arbitrarily
            self._folded: "weakref.WeakKeyDictionary" = \
                weakref.WeakKeyDictionary()

    # Ledger adds happen BEFORE the telemetry increment (and the
    # ledger reads telemetry first in its reconciliation): at any poll
    # the ledger total is >= the telemetry counter for routed fields,
    # so positive drift (telemetry ahead) always means a real
    # attribution bypass, never an increment caught between the two
    # locks.

    def record_h2d(self, nbytes: int) -> None:
        _ledger().add(h2d_bytes=int(nbytes))
        with self._lock:
            self.h2d_bytes += int(nbytes)
            self.h2d_transfers += 1

    def record_d2h(self, nbytes: int) -> None:
        _ledger().add(d2h_bytes=int(nbytes))
        with self._lock:
            self.d2h_bytes += int(nbytes)
            self.d2h_transfers += 1

    def record_launch(self, n: int = 1) -> None:
        _ledger().add(launches=n)
        with self._lock:
            self.device_launches += n

    def record_dispatch(self, encoded_bytes: int,
                        raw_equiv_bytes: int) -> None:
        """One encoded H2D staging: what actually crossed the link vs
        what the uncompressed wire would have shipped."""
        _ledger().add(h2d_encoded_bytes=int(encoded_bytes),
                      h2d_raw_equiv_bytes=int(raw_equiv_bytes))
        with self._lock:
            self.h2d_encoded_bytes += int(encoded_bytes)
            self.h2d_raw_equiv_bytes += int(raw_equiv_bytes)

    def record_pool_hit(self) -> None:
        """A dict pool's hexed form was already device-memoized."""
        with self._lock:
            self.dict_pool_hits += 1

    def record_pool_upload(self) -> None:
        with self._lock:
            self.dict_pool_uploads += 1

    def record_pool_share_hit(self) -> None:
        """A re-created pool matched an interned one by content."""
        with self._lock:
            self.dict_pool_share_hits += 1

    def record_pool_buffer(self, pinned: int = 0, copied: int = 0) -> None:
        """One decode-buffer retention decision: `pinned` extra bytes a
        kept view keeps alive, or `copied` pool bytes memcpy'd out."""
        with self._lock:
            self.dict_pool_pinned_bytes += int(pinned)
            self.dict_pool_copied_bytes += int(copied)

    def record_dict_preserved(self, n: int = 1) -> None:
        """A dict column crossed a pipeline stage still code-encoded."""
        with self._lock:
            self.lazy_dict_preserved += n

    def record_dict_materialize(self) -> None:
        """A lazy dict column flattened to (data, offsets) — the event
        the dict-native reduction plane exists to eliminate."""
        with self._lock:
            self.dict_flat_materializations += 1

    def record_kernel(self, seconds: float) -> None:
        _ledger().add(kernel_seconds=seconds)
        with self._lock:
            self.kernel_seconds += seconds

    def record_compile(self, seconds: float) -> None:
        _ledger().add(compiles=1, compile_seconds=seconds)
        with self._lock:
            self.compile_events += 1
            self.compile_seconds += seconds

    def snapshot(self) -> dict:
        with self._lock:
            ratio = (self.h2d_raw_equiv_bytes
                     / max(self.h2d_encoded_bytes, 1))
            return {
                "h2d_bytes": self.h2d_bytes,
                "h2d_transfers": self.h2d_transfers,
                "d2h_bytes": self.d2h_bytes,
                "d2h_transfers": self.d2h_transfers,
                "device_launches": self.device_launches,
                "compile_events": self.compile_events,
                "compile_seconds": round(self.compile_seconds, 4),
                "kernel_seconds": round(self.kernel_seconds, 4),
                "h2d_encoded_bytes": self.h2d_encoded_bytes,
                "h2d_raw_equiv_bytes": self.h2d_raw_equiv_bytes,
                "dispatch_compression_ratio": round(ratio, 2),
                "dict_pool_hits": self.dict_pool_hits,
                "dict_pool_uploads": self.dict_pool_uploads,
                "dict_pool_share_hits": self.dict_pool_share_hits,
                "dict_pool_pinned_bytes": self.dict_pool_pinned_bytes,
                "dict_pool_copied_bytes": self.dict_pool_copied_bytes,
                "lazy_dict_preserved": self.lazy_dict_preserved,
                "dict_flat_materializations":
                    self.dict_flat_materializations,
            }

    def fold_into(self, metrics) -> None:
        """Publish deltas since this target's last fold into the
        prometheus Metrics facade (stats/registry.py DeviceStats) —
        counters only inc, so folds carry the delta, making repeated
        folds safe.  The counters are process-global (the device is
        shared), so every pipeline's metrics sees full device
        activity."""
        from transferia_tpu.stats.registry import DeviceStats

        ds = DeviceStats(metrics)
        with self._lock:
            # counters AND baseline read/update under ONE lock hold: a
            # snapshot taken outside it could be stale by the time the
            # baseline updates, regressing prev and re-publishing
            # already-counted deltas on the next fold
            snap = {
                "h2d_bytes": self.h2d_bytes,
                "h2d_transfers": self.h2d_transfers,
                "d2h_bytes": self.d2h_bytes,
                "d2h_transfers": self.d2h_transfers,
                "device_launches": self.device_launches,
                "compile_events": self.compile_events,
                "compile_seconds": self.compile_seconds,
                "kernel_seconds": self.kernel_seconds,
                "h2d_encoded_bytes": self.h2d_encoded_bytes,
                "h2d_raw_equiv_bytes": self.h2d_raw_equiv_bytes,
                "dict_pool_hits": self.dict_pool_hits,
                "dict_pool_uploads": self.dict_pool_uploads,
                "dict_pool_share_hits": self.dict_pool_share_hits,
                "dict_pool_pinned_bytes": self.dict_pool_pinned_bytes,
                "dict_pool_copied_bytes": self.dict_pool_copied_bytes,
                "lazy_dict_preserved": self.lazy_dict_preserved,
                "dict_flat_materializations":
                    self.dict_flat_materializations,
            }
            prev = self._folded.setdefault(metrics, {})
            for key, counter in (
                ("h2d_bytes", ds.h2d_bytes),
                ("h2d_transfers", ds.h2d_transfers),
                ("d2h_bytes", ds.d2h_bytes),
                ("d2h_transfers", ds.d2h_transfers),
                ("device_launches", ds.launches),
                ("compile_events", ds.compiles),
                ("compile_seconds", ds.compile_seconds),
                ("kernel_seconds", ds.kernel_seconds),
                ("h2d_encoded_bytes", ds.h2d_encoded_bytes),
                ("h2d_raw_equiv_bytes", ds.h2d_raw_equiv_bytes),
                ("dict_pool_hits", ds.dict_pool_hits),
                ("dict_pool_uploads", ds.dict_pool_uploads),
                ("dict_pool_share_hits", ds.dict_pool_share_hits),
                ("dict_pool_pinned_bytes", ds.dict_pool_pinned_bytes),
                ("dict_pool_copied_bytes", ds.dict_pool_copied_bytes),
                ("lazy_dict_preserved", ds.lazy_dict_preserved),
                ("dict_flat_materializations",
                 ds.dict_flat_materializations),
            ):
                delta = snap[key] - prev.get(key, 0)
                if delta > 0:
                    counter.inc(delta)
                prev[key] = snap[key]
            # ratio is a gauge (an absolute, not a delta): raw-equiv
            # over encoded across the process lifetime
            if self.h2d_encoded_bytes:
                ds.compression_ratio.set(
                    self.h2d_raw_equiv_bytes / self.h2d_encoded_bytes)


TELEMETRY = DeviceTelemetry()

_hooks_installed = False
_hooks_lock = threading.Lock()


def install_jit_hooks() -> None:
    """Route jax's compile-duration monitoring events into TELEMETRY
    (+ a trace instant).  The backend-compile event fires exactly when a
    jit cache miss reaches the XLA compiler — the recompile signal a
    bucketed-shape engine must watch (ARCHITECTURE.md shape
    discipline).  Idempotent; silently a no-op without jax."""
    global _hooks_installed
    with _hooks_lock:
        if _hooks_installed:
            return
        try:
            from jax import monitoring as _mon
        except ImportError:  # pragma: no cover - jax optional
            return

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event.endswith("backend_compile_duration"):
                TELEMETRY.record_compile(duration)
                instant("xla_compile", seconds=round(duration, 4))

        _mon.register_event_duration_secs_listener(_on_duration)
        _hooks_installed = True
