"""Sampling CPU profiler (pure Python, zero deps).

The reference runs always-on pprof and its documented perf loop is
"profile -> speedscope -> fix the top frame"
(/root/reference/cmd/trcli/main.go:62-64, docs/benchmarks.md:44-60).
This module is the engine's equivalent: a wall-clock sampler over
`sys._current_frames()` that attributes self-time to the innermost
frame and renders a top-N table.  Exposed two ways: the
`/debug/profile?seconds=N` endpoint on the health port (cli/main.py)
and `profile()` as a context manager for bench harnesses.

Sampling keeps overhead proportional to the rate (~100 Hz default ≈
<1% on one core) and needs no instrumentation of the profiled code —
the same reason the reference chose pprof's sampling profile over
tracing.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Optional


# innermost frames that mean "this thread is parked, not computing" —
# wall samplers count blocked threads (server accept loops, pool idlers);
# CPU attribution excludes them by default, like pprof's CPU profile
_IDLE_FRAMES = {
    ("select", "selectors.py"),
    ("poll", "selectors.py"),
    ("wait", "threading.py"),
    ("_wait_for_tstate_lock", "threading.py"),
    ("accept", "socket.py"),
    ("readinto", "socket.py"),
    ("recv_into", "socket.py"),
    ("sleep", "time"),
}


def _is_idle(qualname: str, filename: str) -> bool:
    leaf = qualname.rsplit(".", 1)[-1]
    return (leaf, filename) in _IDLE_FRAMES


@dataclass
class ProfileReport:
    seconds: float = 0.0
    samples: int = 0          # busy samples
    idle_samples: int = 0     # parked threads (waits, accept loops)
    rate_hz: float = 0.0
    # (func, file:line) -> sample count
    self_counts: Counter = field(default_factory=Counter)
    cum_counts: Counter = field(default_factory=Counter)

    def top(self, n: int = 10) -> list[tuple[str, float, float]]:
        """[(location, self_cpu_seconds, self_pct)] — hottest first.

        Weights are CPU seconds (per-thread /proc deltas) on Linux, or
        one sampling tick per busy sample in the wall fallback."""
        total = sum(self.self_counts.values())
        if not total:
            return []
        return [
            (loc, secs, 100.0 * secs / total)
            for loc, secs in self.self_counts.most_common(n)
        ]

    @property
    def cpu_seconds(self) -> float:
        return sum(self.self_counts.values())

    def format(self, n: int = 10) -> str:
        lines = [
            f"wall={self.seconds:.2f}s cpu={self.cpu_seconds:.2f}s "
            f"busy_samples={self.samples} "
            f"idle_samples={self.idle_samples} "
            f"rate={self.rate_hz:.0f}Hz",
            f"{'self':>8}  {'%':>6}  location",
        ]
        for loc, secs, pct in self.top(n):
            lines.append(f"{secs:>7.3f}s  {pct:>5.1f}%  {loc}")
        return "\n".join(lines)


def _thread_cpu_seconds() -> Optional[dict[int, float]]:
    """native_id -> CPU seconds (utime+stime) from /proc/self/task.

    This is what turns the wall sampler into a real CPU profiler: a
    thread blocked in recv()/select() accrues no CPU, so its frames get
    zero weight — without this, on a busy multi-threaded process most
    samples land on parked threads and the hot code drowns.  Returns
    None off Linux (callers fall back to wall weighting)."""
    import os

    try:
        tids = os.listdir("/proc/self/task")
    except OSError:
        return None
    hz = _clk_tck()
    out: dict[int, float] = {}
    for tid in tids:
        try:
            with open(f"/proc/self/task/{tid}/stat", "rb") as fh:
                raw = fh.read()
        except OSError:
            continue  # thread exited between listdir and read
        # comm can contain spaces/parens: split after the LAST ')'
        rest = raw[raw.rfind(b")") + 2:].split()
        utime, stime = int(rest[11]), int(rest[12])
        out[int(tid)] = (utime + stime) / hz
    return out


_CLK = None


def _clk_tck() -> float:
    global _CLK
    if _CLK is None:
        import os

        try:
            _CLK = float(os.sysconf("SC_CLK_TCK"))
        except (ValueError, OSError, AttributeError):
            _CLK = 100.0
    return _CLK


class Sampler:
    """Background sampling thread; use via profile() or start/stop.

    Each tick attributes every thread's current Python frame weighted by
    that thread's CPU-time delta since the previous tick (Linux); ticks
    where a thread burned no CPU count as idle.  Off Linux it degrades
    to plain wall sampling with a frame-based idle heuristic.
    """

    def __init__(self, hz: float = 97.0,
                 threads: Optional[set[int]] = None):
        # 97 Hz (prime) avoids phase-locking with periodic work
        self.hz = hz
        self._threads = threads
        self._stop = threading.Event()
        self._report = ProfileReport(rate_hz=hz)
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        my_ident = threading.get_ident()
        rep = self._report
        prev_cpu = _thread_cpu_seconds()
        cpu_mode = prev_cpu is not None
        while not self._stop.wait(interval):
            frames = sys._current_frames()
            cpu = _thread_cpu_seconds() if cpu_mode else None
            ident_to_nid = {
                t.ident: t.native_id for t in threading.enumerate()
            } if cpu_mode else {}
            for ident, frame in frames.items():
                if ident == my_ident:
                    continue
                if self._threads is not None and ident not in self._threads:
                    continue
                code = frame.f_code
                fname = code.co_filename.rsplit("/", 1)[-1]
                weight = 1.0 / self.hz  # wall fallback: one tick
                if cpu_mode:
                    nid = ident_to_nid.get(ident)
                    delta = 0.0
                    if nid is not None and cpu is not None:
                        delta = (cpu.get(nid, 0.0)
                                 - prev_cpu.get(nid, 0.0))
                    if delta <= 0.0:
                        rep.idle_samples += 1
                        continue
                    weight = delta
                elif _is_idle(code.co_qualname, fname):
                    rep.idle_samples += 1
                    continue
                loc = (f"{code.co_qualname} ({fname}:{frame.f_lineno})")
                rep.self_counts[loc] += weight
                rep.samples += 1
                seen = set()
                f = frame
                while f is not None:
                    c = f.f_code
                    cum = f"{c.co_qualname} ({c.co_filename.rsplit('/', 1)[-1]})"
                    if cum not in seen:  # recursion counts once
                        rep.cum_counts[cum] += weight
                        seen.add(cum)
                    f = f.f_back
            if cpu_mode:
                prev_cpu = cpu

    def start(self) -> "Sampler":
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._loop,
                                        name="profile-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> ProfileReport:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._report.seconds = time.perf_counter() - self._t0
        return self._report


class profile:
    """Context manager: `with profile() as p: ...; print(p.report.format())`"""

    def __init__(self, hz: float = 97.0):
        self._sampler = Sampler(hz=hz)
        self.report: Optional[ProfileReport] = None

    def __enter__(self) -> "profile":
        self._sampler.start()
        return self

    def __exit__(self, *exc) -> None:
        self.report = self._sampler.stop()


def sample_seconds(seconds: float, hz: float = 97.0) -> ProfileReport:
    """Block for `seconds`, sampling every live thread (the HTTP
    endpoint's implementation — it runs in a server worker thread, so
    blocking here never stalls the profiled program)."""
    s = Sampler(hz=hz).start()
    time.sleep(max(0.05, min(seconds, 60.0)))
    return s.stop()
