"""Sampling CPU profiler (pure Python, zero deps).

The reference runs always-on pprof and its documented perf loop is
"profile -> speedscope -> fix the top frame"
(/root/reference/cmd/trcli/main.go:62-64, docs/benchmarks.md:44-60).
This module is the engine's equivalent: a wall-clock sampler over
`sys._current_frames()` that attributes self-time to the innermost
frame and renders a top-N table.  Exposed two ways: the
`/debug/profile?seconds=N` endpoint on the health port (cli/main.py)
and `profile()` as a context manager for bench harnesses.

Sampling keeps overhead proportional to the rate (~100 Hz default ≈
<1% on one core) and needs no instrumentation of the profiled code —
the same reason the reference chose pprof's sampling profile over
tracing.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Optional


# innermost frames that mean "this thread is parked, not computing" —
# wall samplers count blocked threads (server accept loops, pool idlers);
# CPU attribution excludes them by default, like pprof's CPU profile
_IDLE_FRAMES = {
    ("select", "selectors.py"),
    ("poll", "selectors.py"),
    ("wait", "threading.py"),
    ("_wait_for_tstate_lock", "threading.py"),
    ("accept", "socket.py"),
    ("readinto", "socket.py"),
    ("recv_into", "socket.py"),
    ("sleep", "time"),
}


def _is_idle(qualname: str, filename: str) -> bool:
    leaf = qualname.rsplit(".", 1)[-1]
    return (leaf, filename) in _IDLE_FRAMES


def _qualname(code) -> str:
    # co_qualname is 3.11+; co_name keeps 3.10 samplers alive (the
    # attribute error killed the sampler thread on its first tick,
    # silently producing empty profiles)
    return getattr(code, "co_qualname", None) or code.co_name


# -- native-frame attribution -------------------------------------------------
#
# A ctypes call into the C++ hostops kernels creates no Python frame:
# a sample landing mid-kernel shows the CALLER's line, so profiles
# silently inflated Python lines that were really C++ time (e.g.
# `_native_hmac_hex (mask.py:104)` at 22.5% of BENCH_r05 was almost
# entirely inside hmac_sha256_hex).  The native bindings
# (native/__init__.py) publish "thread T is inside native symbol S"
# around every exported call; the sampler reads the marker and tags
# the sample explicitly instead of blaming the Python line.
#
# ident-keyed dict, not a threading.local: the SAMPLER thread must read
# other threads' markers.  CPython dict get/set are atomic under the
# GIL, so no lock is needed on this per-native-call hot path.
_NATIVE_ACTIVE: dict[int, str] = {}

NATIVE_TAG = "[native hostops]"


class native_call:
    """Marks the calling thread as executing the named C++ symbol for
    the duration (re-entrant: nested native calls restore the outer
    marker on exit)."""

    __slots__ = ("_name", "_ident", "_prev")

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        self._ident = threading.get_ident()
        self._prev = _NATIVE_ACTIVE.get(self._ident)
        _NATIVE_ACTIVE[self._ident] = self._name
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            _NATIVE_ACTIVE.pop(self._ident, None)
        else:
            _NATIVE_ACTIVE[self._ident] = self._prev
        return False


def active_native(ident: int) -> Optional[str]:
    """The native symbol thread `ident` is currently inside, if any."""
    return _NATIVE_ACTIVE.get(ident)


@dataclass
class ProfileReport:
    seconds: float = 0.0
    samples: int = 0          # busy samples
    idle_samples: int = 0     # parked threads (waits, accept loops)
    rate_hz: float = 0.0
    # (func, file:line) -> sample count
    self_counts: Counter = field(default_factory=Counter)
    cum_counts: Counter = field(default_factory=Counter)

    def top(self, n: int = 10) -> list[tuple[str, float, float]]:
        """[(location, self_cpu_seconds, self_pct)] — hottest first.

        Weights are CPU seconds (per-thread POSIX CPU-clock deltas) on
        POSIX, or one sampling tick per busy sample in the wall
        fallback."""
        total = sum(self.self_counts.values())
        if not total:
            return []
        return [
            (loc, secs, 100.0 * secs / total)
            for loc, secs in self.self_counts.most_common(n)
        ]

    @property
    def cpu_seconds(self) -> float:
        return sum(self.self_counts.values())

    def format(self, n: int = 10) -> str:
        lines = [
            f"wall={self.seconds:.2f}s cpu={self.cpu_seconds:.2f}s "
            f"busy_samples={self.samples} "
            f"idle_samples={self.idle_samples} "
            f"rate={self.rate_hz:.0f}Hz",
            f"{'self':>8}  {'%':>6}  location",
        ]
        for loc, secs, pct in self.top(n):
            lines.append(f"{secs:>7.3f}s  {pct:>5.1f}%  {loc}")
        return "\n".join(lines)


class Sampler:
    """Background sampling thread; use via profile() or start/stop.

    Each tick attributes every thread's current Python frame weighted by
    that thread's CPU-time delta since the previous tick (POSIX
    per-thread CPU clocks); ticks where a thread burned no CPU count as
    idle.  Without pthread_getcpuclockid it degrades to plain wall
    sampling with a frame-based idle heuristic.
    """

    def __init__(self, hz: float = 97.0,
                 threads: Optional[set[int]] = None):
        # 97 Hz (prime) avoids phase-locking with periodic work
        self.hz = hz
        self._threads = threads
        self._stop = threading.Event()
        self._report = ProfileReport(rate_hz=hz)
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0

    def _loop(self) -> None:
        # CPU-time source: per-thread POSIX CPU clocks read via
        # time.clock_gettime — these do NOT release the GIL, unlike the
        # /proc/self/task stat reads the first version used.  Under a
        # busy interpreter every GIL release costs up to the 5ms switch
        # interval to win back, so a /proc-based tick (6+ syscalls)
        # degraded the sampler to ~20Hz and starved the profile; the
        # clock reads keep the loop at its configured rate and resolve
        # in nanoseconds instead of the 10ms /proc quantum.
        interval = 1.0 / self.hz
        my_ident = threading.get_ident()
        rep = self._report
        cpu_mode = hasattr(time, "pthread_getcpuclockid")
        clk: dict[int, int] = {}
        prev: dict[int, float] = {}
        while not self._stop.wait(interval):
            frames = sys._current_frames()
            for ident, frame in frames.items():
                if ident == my_ident:
                    continue
                if self._threads is not None and ident not in self._threads:
                    continue
                code = frame.f_code
                fname = code.co_filename.rsplit("/", 1)[-1]
                weight = 1.0 / self.hz  # wall fallback: one tick
                if cpu_mode:
                    delta = self._cpu_delta(ident, clk, prev)
                    if delta is None or delta <= 0.0:
                        rep.idle_samples += 1
                        continue
                    weight = delta
                elif _is_idle(_qualname(code), fname):
                    rep.idle_samples += 1
                    continue
                loc = (f"{_qualname(code)} ({fname}:{frame.f_lineno})")
                native = _NATIVE_ACTIVE.get(ident)
                if native is not None:
                    # the thread is inside a C++ kernel: blame the
                    # native symbol (tagged), not the Python call line
                    loc = f"{native} {NATIVE_TAG} <- {loc}"
                rep.self_counts[loc] += weight
                rep.samples += 1
                seen = set()
                f = frame
                while f is not None:
                    c = f.f_code
                    cum = (f"{_qualname(c)} "
                           f"({c.co_filename.rsplit('/', 1)[-1]})")
                    if cum not in seen:  # recursion counts once
                        rep.cum_counts[cum] += weight
                        seen.add(cum)
                    f = f.f_back

    @staticmethod
    def _cpu_delta(ident: int, clk: dict, prev: dict) -> Optional[float]:
        """CPU seconds this thread burned since its previous tick; None
        on the first sighting (no baseline yet) or for exited threads
        (clock ids die with their pthread — stale cache entries surface
        as OSError and are dropped; an ident reuse recomputes)."""
        c = clk.get(ident)
        if c is None:
            try:
                c = time.pthread_getcpuclockid(ident)
                clk[ident] = c
                prev[ident] = time.clock_gettime(c)
            except (OSError, AttributeError):
                pass
            return None
        try:
            now = time.clock_gettime(c)
        except OSError:
            clk.pop(ident, None)
            prev.pop(ident, None)
            return None
        delta = now - prev.get(ident, now)
        prev[ident] = now
        return delta

    def start(self) -> "Sampler":
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._loop,
                                        name="profile-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> ProfileReport:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self._report.seconds = time.perf_counter() - self._t0
        return self._report


class profile:
    """Context manager: `with profile() as p: ...; print(p.report.format())`

    `threads={ident, ...}` restricts sampling to those threads — e.g.
    `{threading.get_ident()}` to profile just the calling thread in a
    process where unrelated daemon threads also burn CPU."""

    def __init__(self, hz: float = 97.0,
                 threads: Optional[set[int]] = None):
        self._sampler = Sampler(hz=hz, threads=threads)
        self.report: Optional[ProfileReport] = None

    def __enter__(self) -> "profile":
        self._sampler.start()
        return self

    def __exit__(self, *exc) -> None:
        self.report = self._sampler.stop()


def sample_seconds(seconds: float, hz: float = 97.0) -> ProfileReport:
    """Block for `seconds`, sampling every live thread (the HTTP
    endpoint's implementation — it runs in a server worker thread, so
    blocking here never stalls the profiled program)."""
    s = Sampler(hz=hz).start()
    time.sleep(max(0.05, min(seconds, 60.0)))
    return s.stop()
