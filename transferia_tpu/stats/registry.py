"""Metrics facade + typed stat bundles."""

from __future__ import annotations

import threading
import time
from typing import Optional

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
    )
    _HAVE_PROM = True
except ImportError:  # pragma: no cover - degrade to local counters
    _HAVE_PROM = False

    class _Local:
        def __init__(self, name, doc, registry=None, **kw):
            self._v = 0.0

        def inc(self, amount=1.0):
            self._v += amount

        def dec(self, amount=1.0):
            self._v -= amount

        def set(self, value):
            self._v = value

        def observe(self, value):
            self._v += value

        class _ValueView:
            def __init__(self, outer):
                self._outer = outer

            def get(self):
                return self._outer._v

        @property
        def _value(self):
            return self._ValueView(self)

    CollectorRegistry = None  # type: ignore
    Counter = Gauge = Histogram = _Local  # type: ignore


class Metrics:
    """Per-pipeline metric registry.

    Wraps a prometheus CollectorRegistry; `value()` reads back a sample for
    tests and progress reporting (the reference reads typed stat structs the
    same way, pkg/stats/*).
    """

    def __init__(self, registry: Optional["CollectorRegistry"] = None,
                 labels: Optional[dict[str, str]] = None):
        self.registry = registry if registry is not None else (
            CollectorRegistry() if _HAVE_PROM else None
        )
        if not _HAVE_PROM:
            self.registry = None
        self.labels = labels or {}
        self._metrics: dict[str, object] = {}
        # get-or-create must be atomic: one Metrics is shared by a
        # loader's parallel part-upload threads (fold_into constructs a
        # DeviceStats bundle per fold), and a lost race re-registers the
        # collector — prometheus raises "Duplicated timeseries", the
        # part retries, and an at-least-once sink shows duplicate rows
        self._get_lock = threading.Lock()

    def _get(self, cls, name: str, doc: str, **kw):
        with self._get_lock:
            if name not in self._metrics:
                self._metrics[name] = cls(
                    name, doc, registry=self.registry, **kw
                )
            return self._metrics[name]

    def counter(self, name: str, doc: str = "") -> "Counter":
        return self._get(Counter, name, doc or name)

    def gauge(self, name: str, doc: str = "") -> "Gauge":
        return self._get(Gauge, name, doc or name)

    def histogram(self, name: str, doc: str = "") -> "Histogram":
        return self._get(Histogram, name, doc or name,
                         buckets=(.001, .005, .01, .05, .1, .5, 1, 5, 30, 120))

    def value(self, name: str) -> float:
        """Read back a counter/gauge current value (tests, progress)."""
        m = self._metrics.get(name)
        if m is None:
            return 0.0
        try:
            return m._value.get()  # Counter/Gauge internal, stable in practice
        except AttributeError:
            total = 0.0
            for mf in self.registry.collect():
                if mf.name == name:
                    for s in mf.samples:
                        if s.name in (name, name + "_total"):
                            total += s.value
            return total


class _Bundle:
    def __init__(self, metrics: Optional[Metrics] = None):
        self.m = metrics or Metrics()


class SourceStats(_Bundle):
    """publisher.data.* (pkg/stats/source.go:11-31)."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.changeitems = self.m.counter("publisher_data_changeitems")
        self.parsed_rows = self.m.counter("publisher_data_parsed_rows")
        self.unparsed_rows = self.m.counter("publisher_data_unparsed_rows")
        self.read_bytes = self.m.counter("publisher_data_read_bytes")
        self.decode_time = self.m.histogram("publisher_time_decode")
        self.push_time = self.m.histogram("publisher_time_push")
        self.usage_lag = self.m.gauge("publisher_lag_seconds")


class SinkerStats(_Bundle):
    """sinker.* (pkg/stats/sinker.go:12)."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.inflight_rows = self.m.gauge("sinker_inflight_rows")
        self.rows = self.m.counter("sinker_pushed_rows")
        self.bytes = self.m.counter("sinker_pushed_bytes")
        self.errors = self.m.counter("sinker_push_errors")
        self.push_time = self.m.histogram("sinker_time_push")
        self.table_rows: dict[str, int] = {}

    def record_table(self, table: str, rows: int) -> None:
        self.table_rows[table] = self.table_rows.get(table, 0) + rows


class SloStats(_Bundle):
    """SLO plane gauges (stats/slo.py fold_verdicts): the scrapeable
    shape of the latest burn-rate evaluation."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.objectives = self.m.gauge("slo_objectives")
        self.burning = self.m.gauge("slo_burning")
        self.worst_burn_fast = self.m.gauge("slo_worst_burn_fast")
        self.worst_burn_slow = self.m.gauge("slo_worst_burn_slow")
        self.worst_lag_ms = self.m.gauge("slo_worst_replication_lag_ms")
        self.evaluations = self.m.counter("slo_evaluations")


class BuffererStats(_Bundle):
    """middleware bufferer flush metrics."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.flush_count = self.m.counter("bufferer_flushes")
        self.flush_rows = self.m.counter("bufferer_flush_rows")
        self.buffered_rows = self.m.gauge("bufferer_buffered_rows")
        self.buffered_bytes = self.m.gauge("bufferer_buffered_bytes")
        self.flush_time = self.m.histogram("bufferer_time_flush")


class ReplicationStats(_Bundle):
    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.running = self.m.gauge("replication_running")
        self.restarts = self.m.counter("replication_restarts")
        self.fatal_errors = self.m.counter("replication_fatal_errors")


class TransformStats(_Bundle):
    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.rows_in = self.m.counter("transform_rows_in")
        self.rows_out = self.m.counter("transform_rows_out")
        self.errors = self.m.counter("transform_error_rows")
        self.time = self.m.histogram("transform_time")
        self.compiles = self.m.counter("transform_plan_compiles")


class DeviceStats(_Bundle):
    """Device-link counters (stats/trace.py DeviceTelemetry folds its
    deltas in here so /metrics exposes the link physics: H2D/D2H bytes
    and transfer counts, launches, XLA compiles, kernel wall time)."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.h2d_bytes = self.m.counter("device_h2d_bytes")
        self.h2d_transfers = self.m.counter("device_h2d_transfers")
        self.d2h_bytes = self.m.counter("device_d2h_bytes")
        self.d2h_transfers = self.m.counter("device_d2h_transfers")
        self.launches = self.m.counter("device_launches")
        self.compiles = self.m.counter("device_xla_compiles")
        self.compile_seconds = self.m.counter("device_xla_compile_seconds")
        self.kernel_seconds = self.m.counter("device_kernel_seconds")
        # decode-pipeline readahead (providers/readahead.py): prefetch
        # queue depth and in-flight decoded bytes — host-side gauges,
        # but they live with the link physics because overlapping host
        # decode with device dispatch is what the prefetcher buys
        self.readahead_depth = self.m.gauge("decode_readahead_depth")
        self.readahead_bytes = self.m.gauge(
            "decode_readahead_inflight_bytes")
        # compressed dispatch plane (ops/dispatch.py): encoded vs
        # raw-equivalent H2D bytes — the ratio gauge IS the plane's
        # honesty metric (a "compressed" wire showing ~1.0 is shipping
        # flat buffers after all) — plus dict-pool residency counters
        self.h2d_encoded_bytes = self.m.counter("h2d_encoded_bytes")
        self.h2d_raw_equiv_bytes = self.m.counter("h2d_raw_equiv_bytes")
        self.compression_ratio = self.m.gauge(
            "dispatch_compression_ratio")
        self.dict_pool_hits = self.m.counter("dict_pool_device_hits")
        self.dict_pool_uploads = self.m.counter(
            "dict_pool_device_uploads")
        # pool interning + decode-buffer economics (columnar/batch
        # intern_pool, parquet_native._finish_bytearray): content-hit
        # pool reuse across row groups/parts, and bytes a kept pool
        # view pins vs bytes copied out to free the decode buffer
        self.dict_pool_share_hits = self.m.counter("dict_pool_share_hits")
        self.dict_pool_pinned_bytes = self.m.counter(
            "dict_pool_pinned_bytes")
        self.dict_pool_copied_bytes = self.m.counter(
            "dict_pool_copied_bytes")
        # dict-native reduction plane (ops/rowhash.py, mask fast paths):
        # columns that crossed a stage still code-encoded vs columns a
        # consumer flattened — nonzero flat materializations on a
        # dict-heavy pipeline mean a code-aware fast path leaked
        self.lazy_dict_preserved = self.m.counter("lazy_dict_preserved")
        self.dict_flat_materializations = self.m.counter(
            "dict_flat_materializations")
        # concurrency sentinel (runtime/lockwatch.py fold_into): lock
        # acquisitions observed under the armed watch, plus the three
        # finding classes — any nonzero inversion count is a potential
        # deadlock witnessed at runtime
        self.lockwatch_acquisitions = self.m.counter(
            "lockwatch_acquisitions")
        self.lockwatch_inversions = self.m.counter("lockwatch_inversions")
        self.lockwatch_long_holds = self.m.counter("lockwatch_long_holds")
        self.lockwatch_blocking_in_lock = self.m.counter(
            "lockwatch_blocking_in_lock")


class InterchangeStats(_Bundle):
    """Arrow interchange plane counters (interchange/telemetry.py folds
    its deltas in here).  `zero_copy_buffers` vs `copied_buffers` is the
    plane's honesty metric: a wire that claims zero-copy but shows a
    copied-buffer majority is pivoting after all."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.bytes_in = self.m.counter("interchange_bytes_in")
        self.bytes_out = self.m.counter("interchange_bytes_out")
        self.batches_in = self.m.counter("interchange_batches_in")
        self.batches_out = self.m.counter("interchange_batches_out")
        self.zero_copy_buffers = self.m.counter(
            "interchange_zero_copy_buffers")
        self.copied_buffers = self.m.counter("interchange_copied_buffers")
        self.flight_streams = self.m.counter("interchange_flight_streams")
        self.shm_segments = self.m.counter("interchange_shm_segments")
        # pool-once encoded wire (interchange/convert.EncodedWireState):
        # the ratio gauge is the wire's honesty metric — flat-equivalent
        # bytes over (pool-once + codes) bytes actually framed; ~1.0 on
        # a dict-heavy stream means pools re-ship or columns cross flat
        self.pools_shipped = self.m.counter("interchange_pools_shipped")
        self.pool_bytes_shipped = self.m.counter(
            "interchange_pool_bytes_shipped")
        self.codes_bytes_shipped = self.m.counter(
            "interchange_codes_bytes_shipped")
        self.flat_equiv_bytes = self.m.counter(
            "interchange_flat_equiv_bytes")
        self.encoded_wire_ratio = self.m.gauge("encoded_wire_ratio")
        # multi-stream transport lane (interchange/flight.py): DoPut /
        # DoGet substreams striped per part on top of the part stream
        self.substreams_out = self.m.counter("interchange_substreams_out")
        self.substreams_in = self.m.counter("interchange_substreams_in")
        # region buffer pool (interchange/regions.py): sealed regions
        # and the pinned-vs-copied byte split — zero region_copied_bytes
        # on the region path is the zero-intermediate-copy proof
        self.regions_sealed = self.m.counter("interchange_regions_sealed")
        self.region_pinned_bytes = self.m.counter(
            "interchange_region_pinned_bytes")
        self.region_copied_bytes = self.m.counter(
            "interchange_region_copied_bytes")


class ChaosStats(_Bundle):
    """Fault-injection counters (chaos/).  Per-site fire counts land as
    `chaos_fires_<site with dots -> underscores>` so a chaos soak's
    injection activity is visible on the same /metrics surface as the
    delivery counters it perturbs."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.fires = self.m.counter("chaos_fires")
        self.trials = self.m.counter("chaos_trials")
        self.invariant_failures = self.m.counter(
            "chaos_invariant_failures")
        self.duplicates_absorbed = self.m.counter(
            "chaos_duplicates_absorbed")
        self.restarts = self.m.counter("chaos_restarts")

    @staticmethod
    def site_counter_name(site: str) -> str:
        """chaos/failpoints.fold_into shares this naming — keep single."""
        return "chaos_fires_" + site.replace(".", "_")

    def record_site(self, site: str, fires: int) -> None:
        if fires <= 0:
            return
        self.m.counter(self.site_counter_name(site),
                       f"chaos fires at {site}").inc(fires)
        self.fires.inc(fires)


class LeaseStats(_Bundle):
    """Worker-liveness plane counters (coordinator leases + epoch
    fencing, tasks/snapshot.py).  `fence_rejected` is the operator's
    zombie alarm: a nonzero count means a worker tried to complete a
    part after its lease expired and the part was reclaimed."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.renewals = self.m.counter("lease_renewals")
        self.steals = self.m.counter("lease_steals")
        self.heartbeat_failures = self.m.counter(
            "lease_heartbeat_failures")
        self.fence_rejected = self.m.counter("fence_rejected")


class CommitStats(_Bundle):
    """Staged two-phase sink commit counters (abstract/commit.py,
    tasks/snapshot.py).  The pair to watch is `commit_fenced` +
    `publish_stale_rejected` vs `published_parts`: nonzero fences mean
    zombies tried to publish reclaimed parts and were stopped — at the
    coordinator's grant or at the sink's own epoch fence."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.staged_parts = self.m.counter("commit_staged_parts")
        self.published_parts = self.m.counter("commit_published_parts")
        self.aborted_parts = self.m.counter("commit_aborted_parts")
        self.commit_granted = self.m.counter("commit_granted")
        self.commit_fenced = self.m.counter("commit_fenced")
        self.publish_stale_rejected = self.m.counter(
            "publish_stale_rejected")
        self.dedup_rows_dropped = self.m.counter(
            "commit_dedup_rows_dropped")


class FleetStats(_Bundle):
    """Fleet control plane counters (fleet/scheduler.py).  The pair to
    watch is `shed` vs `admitted`: a fleet that sheds while
    `desired_workers` exceeds the live worker count is asking the
    autoscaler for capacity; one that sheds with idle workers is
    backpressured by the data plane (see fleet/backpressure.py)."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.admitted = self.m.counter("fleet_admitted")
        self.shed = self.m.counter("fleet_shed")
        self.completed = self.m.counter("fleet_completed")
        self.failed = self.m.counter("fleet_failed")
        self.rebalanced = self.m.counter("fleet_rebalanced")
        self.worker_deaths = self.m.counter("fleet_worker_deaths")
        self.queue_depth = self.m.gauge("fleet_queue_depth")
        self.inflight = self.m.gauge("fleet_inflight")
        self.desired_workers = self.m.gauge("fleet_desired_workers")
        self.tenant_debt_max = self.m.gauge("fleet_tenant_debt_max")
        self.dispatch_time = self.m.histogram("fleet_time_dispatch")


class DistributedFleetStats(_Bundle):
    """Distributed fleet counters (fleet/distributed.py, fleet/worker.py,
    fleet/autoscaler.py).  The pair to watch is `ticket_fences` +
    `ticket_steals` vs `tickets_completed`: fences are zombies whose
    completions were rejected after a crash reclaim or a preemption
    revoke — nonzero fences with zero steals/preemptions means a lease
    TTL is too short for the real part cadence."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.enqueued = self.m.counter("fleet_tickets_enqueued")
        self.claimed = self.m.counter("fleet_tickets_claimed")
        self.completed = self.m.counter("fleet_tickets_completed")
        self.failed = self.m.counter("fleet_tickets_failed")
        self.released = self.m.counter("fleet_tickets_released")
        self.fenced = self.m.counter("fleet_ticket_fences")
        self.steals = self.m.counter("fleet_ticket_steals")
        self.shed = self.m.counter("fleet_tickets_shed")
        self.preemptions = self.m.counter("fleet_preemptions")
        self.preempt_yields = self.m.counter("fleet_preempt_yields")
        self.worker_spawns = self.m.counter("fleet_worker_spawns")
        self.worker_exits = self.m.counter("fleet_worker_exits")
        self.autoscale_ups = self.m.counter("fleet_autoscale_ups")
        self.autoscale_downs = self.m.counter("fleet_autoscale_downs")
        self.gc_pruned = self.m.counter("fleet_tickets_gc_pruned")
        self.queued = self.m.gauge("fleet_dist_queued")
        self.inflight = self.m.gauge("fleet_dist_inflight")
        self.desired_workers = self.m.gauge("fleet_dist_desired_workers")
        self.live_workers = self.m.gauge("fleet_dist_live_workers")


class MvccStats(_Bundle):
    """MVCC staging-store counters (transferia_tpu/mvcc/).  The pair to
    watch is `layers_fenced` vs `cutovers`: nonzero fences mean zombie
    snapshot/delta workers published after the cutover sealed and were
    stopped at the coordinator.  `watermark_lag` is the distance
    between the newest delta LSN seen and the sealed cutover watermark
    — a growing lag after cutover means the resumed replication lane
    is falling behind the source."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.base_versions = self.m.counter("mvcc_base_versions")
        self.base_rows = self.m.counter("mvcc_base_rows")
        self.delta_layers = self.m.counter("mvcc_delta_layers")
        self.delta_rows = self.m.counter("mvcc_delta_rows")
        self.layers_replaced = self.m.counter("mvcc_layers_replaced")
        self.layers_fenced = self.m.counter("mvcc_layers_fenced")
        self.merged_reads = self.m.counter("mvcc_merged_reads")
        self.merged_rows = self.m.counter("mvcc_merged_rows")
        self.cutovers = self.m.counter("mvcc_cutovers")
        self.cutover_fenced = self.m.counter("mvcc_cutover_fenced")
        self.compactions = self.m.counter("mvcc_compactions")
        self.compacted_rows = self.m.counter("mvcc_compacted_rows")
        self.spill_blobs = self.m.counter("mvcc_spill_blobs")
        self.spill_bytes = self.m.counter("mvcc_spill_bytes")
        self.rebuilds = self.m.counter("mvcc_rebuilds")
        self.rebuilt_layers = self.m.counter("mvcc_rebuilt_layers")
        self.pump_rows = self.m.counter("mvcc_pump_rows")
        self.pump_layers = self.m.counter("mvcc_pump_layers")
        self.offset_commits = self.m.counter("mvcc_offset_commits")
        self.live_layers = self.m.gauge("mvcc_live_layers")
        self.watermark_lag = self.m.gauge("mvcc_watermark_lag")


class TableStats(_Bundle):
    """Per-table progress gauges (pkg/stats/table.go)."""

    def __init__(self, metrics: Optional[Metrics] = None):
        super().__init__(metrics)
        self.completed_parts = self.m.counter("snapshot_completed_parts")
        self.completed_rows = self.m.counter("snapshot_completed_rows")
        self.total_parts = self.m.gauge("snapshot_total_parts")
        self.eta_rows = self.m.gauge("snapshot_eta_rows")


class Timer:
    """Context manager feeding a histogram."""

    def __init__(self, hist):
        self.hist = hist

    def __enter__(self):
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.monotonic() - self.t0)
        return False
