"""Fleet-wide observability plane: durable cross-process export + merge.

PR 10 gave one PROCESS a complete causal story — a linked Perfetto
timeline, a conservation-checked resource ledger, always-on device
counters.  The distributed fleet (PR 12) made that insufficient: a
`trtpu worker` process's spans, ledger, and DeviceStats die with the
process, so a 10-worker transfer has no single pane and a SIGKILLed
worker takes its last minutes of observability to the grave.

This module is the durable half:

- **ObsExporter** — each worker process periodically (heartbeat
  cadence, plus part completion, ticket completion, and a final flush
  on drain) serializes a SEGMENT through the coordinator
  (`put_obs_segment` on memory / filestore / s3 — same trio and
  retention conventions as fleet tickets): a bounded DELTA of the
  trace ring, plus the CUMULATIVE resource-ledger snapshot, device
  telemetry counters, and per-stage latency histograms (stats/hdr.py).
  Export is strictly best-effort: a failed export never fails the part
  or ticket it rode on (`obs.export` failpoint pins that), and a
  SIGKILL loses at most one export interval.

- **merge_segments** — any reader (the scheduler/leader, `trtpu top
  --fleet`, `GET /debug/fleet/obs`) folds N processes' segments into
  one fleet view.  Merge rules: cumulative payloads (ledger /
  telemetry / histograms) take the LATEST segment per PROCESS (pid)
  and sum across processes — re-reading an old segment can never
  double-count; span deltas UNION across all segments with
  (pid, span) dedup; worker liveness is the newest segment age per
  worker label.  The cross-process conservation check — merged ledger
  totals == Σ per-process totals, field by field — is recomputed from
  two independent aggregations so a merge bug or torn segment shows as
  DRIFT instead of silently lying.  Torn/truncated segments are
  skipped and counted (`obs.merge` failpoint pins that).

- **export_fleet_chrome_trace** — `trtpu trace --fleet <transfer>`:
  stitches span deltas from N processes into ONE Perfetto timeline.
  Each process's spans are shifted onto the shared wall-clock axis via
  its exported capture epoch, each process renders as its own pid lane,
  and the already-propagated trace ids (Flight metadata, shm framing,
  fleet tickets) make the cross-process parent links render as flow
  arrows.

Export semantics are AT-LEAST-ONCE, deliberately: segments are
idempotent ((worker, seq) re-put replaces; cumulative payloads merge
by latest-per-pid), so a retried export or a replayed read changes
nothing — exactly-once machinery would buy no additional correctness
for monotone counters and would couple the data plane's commit path to
the observability plane's availability.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import socket
import threading
import time
import weakref
from typing import Optional

from transferia_tpu.abstract.errors import is_worker_kill
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.runtime import knobs, lockwatch
from transferia_tpu.stats import hdr, trace, watermark
# _INT_FIELDS is the ledger's own exact-vs-rounded field split — the
# merge's conservation check must agree with it, so share the set
from transferia_tpu.stats.ledger import FIELDS, LEDGER, _INT_FIELDS

logger = logging.getLogger(__name__)

DEFAULT_SCOPE = "fleet"
ENV_SCOPE = "TRANSFERIA_TPU_OBS_SCOPE"
ENV_EXPORT = "TRANSFERIA_TPU_OBS_EXPORT"        # "0" = kill switch
ENV_MAX_SPANS = "TRANSFERIA_TPU_OBS_MAX_SPANS"  # per segment
ENV_MIN_INTERVAL = "TRANSFERIA_TPU_OBS_INTERVAL"
DEFAULT_MAX_SPANS = 4_000
# part completions can be ms apart; exports coalesce to this cadence
# (final flushes always go through)
DEFAULT_MIN_INTERVAL = 1.0
SEGMENT_VERSION = 1


def default_scope(environ=os.environ) -> str:
    return knobs.env_str(ENV_SCOPE, "", environ=environ) or DEFAULT_SCOPE


def export_enabled(environ=os.environ) -> bool:
    return knobs.env_str(ENV_EXPORT, "1",
                         environ=environ) not in ("0", "false", "no")


# -- exporter -----------------------------------------------------------------

class ObsExporter:
    """One process-side export stream to one (coordinator, scope).

    Shared by everything in the process that exports to the same pair
    (see `exporter_for`): the fleet worker and the SnapshotLoaders it
    runs write ONE seq stream, so segments never clobber each other
    and the span delta mark advances once per export.  All state
    mutates under one lock; the put itself happens inside the lock too
    — exports are heartbeat-cadence rare, and serializing them keeps
    the (seq, span-mark) pair atomic with the segment that carries it.
    """

    def __init__(self, coordinator, worker: str,
                 scope: Optional[str] = None):
        # weak coordinator reference: exporters live in a process-wide
        # registry keyed by coordinator (exporter_for) — a strong ref
        # here would keep every coordinator ever exported to (and all
        # its retained state) alive for the process lifetime
        try:
            self._cpref = weakref.ref(coordinator)
        except TypeError:  # unweakrefable test double: hold it
            self._cpref = (lambda obj: (lambda: obj))(coordinator)
        self.worker = worker
        self.scope = scope or default_scope()
        self.enabled = export_enabled() and \
            bool(getattr(coordinator, "supports_obs_segments",
                         lambda: False)())
        self._lock = lockwatch.named_lock("obs.exporter")
        self._seq = 0
        self._span_mark = 0
        self._last_attempt = 0.0
        self.exports = 0
        self.export_failures = 0

    @property
    def cp(self):
        return self._cpref()

    def _build(self, kind: str, seq: int) -> tuple[dict, int]:
        """Assemble one segment (caller holds the lock).  Returns the
        segment and the new span mark to commit on a successful put."""
        max_spans = int(knobs.env_float(ENV_MAX_SPANS,
                                        DEFAULT_MAX_SPANS))
        # one lock hold for (count, ring): reading them separately
        # would let concurrent appends displace the oldest records of
        # this window out of the tail slice uncounted
        total, ring = trace.spans_with_count()
        new = total - self._span_mark
        recorded: list = []
        dropped = 0
        if new > 0:
            tail = ring[-new:]
            dropped = max(0, new - len(tail))      # evicted by the ring
            if len(tail) > max_spans:
                dropped += len(tail) - max_spans
                tail = tail[-max_spans:]
            for rec in tail:
                args = rec[7]
                if args is not None:
                    args = {k: trace._jsonable(v) for k, v in args.items()}
                recorded.append([rec[0], rec[1], rec[2], rec[3], rec[4],
                                 rec[5], rec[6], args, rec[8], rec[9],
                                 rec[10]])
        ledger_snap = LEDGER.snapshot()
        seg = {
            "v": SEGMENT_VERSION,
            "worker": self.worker,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "seq": seq,
            "ts": time.time(),
            "kind": kind,
            "epoch_unix": trace.epoch_unix(),
            "spans": recorded,
            "spans_dropped": dropped,
            "ledger": {
                "totals": ledger_snap["totals"],
                "transfers": ledger_snap["transfers"],
                "tenants": ledger_snap["tenants"],
                "conservation_ok":
                    bool(ledger_snap["conservation"].get("ok")),
            },
            "telemetry": trace.TELEMETRY.snapshot(),
            "hists": hdr.STAGES.snapshot(),
            "watermarks": watermark.WATERMARKS.snapshot(),
        }
        watch = lockwatch.active()
        if watch is not None:
            # cumulative per process, merged latest-per-pid like the
            # ledger: the fleet pane's "zero inversions" assertion must
            # survive worker restarts and coalesced exports
            seg["lockwatch"] = watch.snapshot()
        return seg, total

    def export(self, kind: str = "periodic") -> bool:
        """Serialize and durably put one segment.  Best-effort: every
        failure is swallowed (logged + counted) except worker kills —
        chaos kill semantics must keep killing whatever they hit.
        Non-final exports coalesce to TRANSFERIA_TPU_OBS_INTERVAL."""
        cp = self.cp
        if not self.enabled or cp is None:
            return False
        final = kind == "final"
        # non-final exports never WAIT: if another export holds the
        # lock (e.g. the heartbeat thread stuck in a slow coordinator
        # put), a part-completion export must coalesce into it, not
        # stall the data-plane thread behind a best-effort write
        if not self._lock.acquire(blocking=final):
            return False
        try:
            now = time.monotonic()
            if not final and now - self._last_attempt < \
                    knobs.env_float(ENV_MIN_INTERVAL,
                                    DEFAULT_MIN_INTERVAL):
                return False
            self._last_attempt = now
            seq = self._seq + 1
            seg, new_mark = self._build(kind, seq)
            try:
                sp = trace.span("obs_export", worker=self.worker,
                                seq=seq, kind=kind)
                with sp:
                    failpoint("obs.export")
                    cp.put_obs_segment(self.scope, seg)
                    if sp:
                        sp.add(spans=len(seg["spans"]))
            except Exception as e:
                if is_worker_kill(e):
                    raise
                # seq and span mark stay: the next export RE-SENDS the
                # same window under the same seq (idempotent replace) —
                # at most one export interval is ever lost
                self.export_failures += 1
                logger.warning(
                    "obs export %s seq %d failed (best-effort; next "
                    "beat retries the window): %s", self.worker, seq, e)
                return False
            self._seq = seq
            self._span_mark = new_mark
            self.exports += 1
        finally:
            self._lock.release()
        if final or seq % 16 == 0:
            try:
                cp.gc_obs_segments(self.scope)
            except Exception as e:  # GC is advisory
                logger.debug("obs gc failed: %s", e)
        return True


# One exporter per (coordinator, scope, worker) per process — a fleet
# worker and the loaders it runs share one stream (the ambient
# contextvar carries the worker's exporter into the loader), while
# thread-mode supervisors running N workers in one process keep one
# stream each.
_reg_lock = threading.Lock()
_registry: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_ambient: "contextvars.ContextVar[Optional[ObsExporter]]" = \
    contextvars.ContextVar("trtpu_obs_exporter", default=None)


class ambient_exporter:
    """Install an exporter as the ambient one for the calling context
    (the fleet worker wraps each ticket run so the SnapshotLoader it
    constructs joins the worker's export stream)."""

    __slots__ = ("_exp", "_token")

    def __init__(self, exp: Optional[ObsExporter]):
        self._exp = exp
        self._token = None

    def __enter__(self):
        if self._exp is not None:
            self._token = _ambient.set(self._exp)
        return self._exp

    def __exit__(self, *exc):
        if self._token is not None:
            _ambient.reset(self._token)
            self._token = None
        return False


def exporter_for(coordinator, worker: str,
                 scope: Optional[str] = None) -> ObsExporter:
    """The process-wide exporter for (coordinator, scope, worker) — or
    the AMBIENT one when the caller runs inside a fleet worker's ticket
    context against the same coordinator (one process, one stream)."""
    amb = _ambient.get()
    if amb is not None and amb.cp is coordinator:
        return amb
    scope = scope or default_scope()
    with _reg_lock:
        try:
            per = _registry.setdefault(coordinator, {})
        except TypeError:        # unweakrefable test double
            return ObsExporter(coordinator, worker, scope)
        exp = per.get((scope, worker))
        if exp is None:
            exp = per[(scope, worker)] = ObsExporter(
                coordinator, worker, scope)
        return exp


# -- merge --------------------------------------------------------------------

def _proc_key(seg: dict) -> tuple:
    """Process identity across the FLEET: (host, pid).  Bare pids
    collide across hosts (every containerized worker is pid 1) — a
    pid-keyed merge would silently drop one host's cumulative state."""
    return (str(seg.get("host", "")), int(seg.get("pid", 0) or 0))


def _latest_per(segments: list[dict], key_fn) -> dict:
    """Newest segment per `key_fn(seg)`, by (ts, seq) — the
    cumulative-merge rule."""
    out: dict = {}
    for seg in segments:
        k = key_fn(seg)
        cur = out.get(k)
        mark = (seg.get("ts", 0.0) or 0.0, seg.get("seq", 0) or 0)
        if cur is None or mark >= (cur.get("ts", 0.0) or 0.0,
                                   cur.get("seq", 0) or 0):
            out[k] = seg
    return out


def _sum_fields(target: dict, values: dict) -> None:
    for name in FIELDS:
        v = values.get(name, 0)
        if isinstance(v, (int, float)):
            target[name] = target.get(name, 0) + v


def _parse_segments(raw: list) -> tuple[list[dict], int]:
    """Validate/normalize raw segments; torn or truncated ones are
    skipped and counted, never raised (the `obs.merge` failpoint lands
    here: an injected fault IS a torn segment)."""
    good: list[dict] = []
    corrupt = 0
    sp = trace.span("obs_parse_segments", segments=len(raw or []))
    with sp:
        for seg in raw or []:
            try:
                failpoint("obs.merge")
                if not isinstance(seg, dict) or "worker" not in seg:
                    raise ValueError("not a segment")
                int(seg.get("pid", 0))
                int(seg.get("seq", 0))
                float(seg.get("ts", 0.0))
                if not isinstance(seg.get("ledger", {}), dict) or \
                        not isinstance(seg.get("hists", {}), dict) or \
                        not isinstance(seg.get("spans", []), list):
                    raise ValueError("torn segment payload")
                good.append(seg)
            except Exception:
                corrupt += 1
        if sp:
            sp.add(corrupt=corrupt)
    return good, corrupt


def merge_segments(raw_segments: list,
                   now: Optional[float] = None) -> dict:
    """Fold segments into the fleet view (`/debug/fleet/obs` payload):
    per-worker liveness, per-(transfer, tenant) merged ledger rows,
    fleet totals, merged per-stage latency histograms, and the
    cross-process conservation check."""
    now = time.time() if now is None else now
    with trace.span("obs_merge", segments=len(raw_segments or [])):
        segments, corrupt = _parse_segments(raw_segments)
        by_pid = _latest_per(segments, _proc_key)
        # liveness per (worker label, host): two pid-1 containers with
        # the same worker index produce the same LABEL on different
        # hosts — they are different workers
        by_worker = _latest_per(
            segments, lambda s: (str(s.get("worker", "")),
                                 str(s.get("host", ""))))
        label_hosts: dict[str, set] = {}
        for (label, host) in by_worker:
            label_hosts.setdefault(label, set()).add(host)

        workers: dict[str, dict] = {}
        for (label, host), seg in sorted(by_worker.items()):
            ts = float(seg.get("ts", 0.0) or 0.0)
            shown = label if len(label_hosts[label]) == 1 \
                else f"{label}@{host}"
            workers[shown] = {
                "pid": seg.get("pid", 0),
                "host": seg.get("host", ""),
                "seq": seg.get("seq", 0),
                "kind": seg.get("kind", ""),
                "age_seconds": round(max(0.0, now - ts), 3),
                "conservation_ok": bool(
                    seg.get("ledger", {}).get("conservation_ok", True)),
                "spans_dropped": seg.get("spans_dropped", 0),
            }

        # cumulative payloads: latest per PROCESS, summed across
        # processes (two exporters in one process — a fleet worker and
        # a bare loader — both carry the same process-global ledger;
        # per-pid latest keeps that from double-counting)
        totals = dict.fromkeys(FIELDS, 0)
        per_pid_totals: dict = {}
        transfer_rows = 0      # per-process ledger rows contributing
        transfers: dict[str, dict] = {}
        tenants: dict[str, dict] = {}
        telemetry: dict = {}
        lockwatch_counters: dict = {}
        lockwatch_findings: list = []
        worker_conservation_ok = True
        for proc, seg in by_pid.items():
            led = seg.get("ledger", {})
            if not led.get("conservation_ok", True):
                worker_conservation_ok = False
            pt = dict.fromkeys(FIELDS, 0)
            _sum_fields(pt, led.get("totals", {})
                        if isinstance(led.get("totals"), dict) else {})
            per_pid_totals[proc] = pt
            _sum_fields(totals, pt)
            trs = led.get("transfers", {})
            if isinstance(trs, dict):
                transfer_rows += len(trs)
                for tid, vals in trs.items():
                    if not isinstance(vals, dict):
                        continue
                    row = transfers.setdefault(tid, {
                        "tenant": vals.get("tenant", "-"),
                        "workers": [],
                        **dict.fromkeys(FIELDS, 0)})
                    if row["tenant"] != vals.get("tenant", "-"):
                        row["tenant"] = "~multiple"
                    label = str(seg.get("worker", proc[1]))
                    if label not in row["workers"]:
                        row["workers"].append(label)
                    _sum_fields(row, vals)
            tns = led.get("tenants", {})
            if isinstance(tns, dict):
                for name, vals in tns.items():
                    if not isinstance(vals, dict):
                        continue
                    row = tenants.setdefault(
                        name, dict.fromkeys(FIELDS, 0))
                    _sum_fields(row, vals)
            tel = seg.get("telemetry", {})
            if isinstance(tel, dict):
                for name, v in tel.items():
                    if isinstance(v, (int, float)):
                        telemetry[name] = telemetry.get(name, 0) + v
            lw = seg.get("lockwatch", {})
            if isinstance(lw, dict):
                for name, v in (lw.get("counters") or {}).items():
                    if isinstance(v, (int, float)):
                        lockwatch_counters[name] = \
                            lockwatch_counters.get(name, 0) + v
                for f in (lw.get("findings") or [])[:8]:
                    if isinstance(f, dict) and \
                            len(lockwatch_findings) < 64:
                        lockwatch_findings.append(
                            dict(f, worker=str(seg.get("worker", ""))))

        # cross-process conservation: the per-transfer aggregation and
        # the per-process totals are INDEPENDENT sums of the same
        # events — field-wise equality is the merge's self-check
        # (fences/retries were billed by the stealing worker's process,
        # so both sums see them exactly once)
        from_transfers = dict.fromkeys(FIELDS, 0)
        for row in transfers.values():
            _sum_fields(from_transfers, row)
        drift = {}
        ok = worker_conservation_ok
        for name in FIELDS:
            d = totals[name] - from_transfers[name]
            # integer fields must balance exactly; *_seconds fields
            # carry per-aggregate rounding from each process's ledger
            # snapshot (6 decimals on the totals AND on every
            # per-transfer row), so the worst-case benign difference
            # scales with processes + contributing rows
            tol = 0.0 if name in _INT_FIELDS \
                else 1e-6 * max(2, len(by_pid) + transfer_rows)
            if abs(d) > tol:
                drift[name] = round(d, 6)
                ok = False
        conservation = {
            "ok": ok,
            "workers_ok": worker_conservation_ok,
            "drift": drift,
            "per_process_totals": {
                f"{host}:{pid}": vals for (host, pid), vals in
                sorted(per_pid_totals.items())
            },
        }

        hists = hdr.merge_stage_maps(
            [seg.get("hists", {}) for seg in by_pid.values()])
        # watermarks merge over ALL segments, not latest-per-pid:
        # max-merge is idempotent, and a SIGKILLed worker's lost final
        # segment must not regress what its earlier segments published
        merged_wm = watermark.merge_maps(
            [seg.get("watermarks") for seg in segments])
        span_count = sum(len(seg.get("spans", [])) for seg in segments)
        return {
            "segments": len(segments),
            "corrupt_segments": corrupt,
            "processes": len(by_pid),
            "span_records": span_count,
            "workers": workers,
            "transfers": dict(sorted(transfers.items())),
            "tenants": dict(sorted(tenants.items())),
            "totals": totals,
            "telemetry": telemetry,
            "hists": {name: h.summary()
                      for name, h in sorted(hists.items())},
            "watermarks": merged_wm,
            "freshness": watermark.summarize(merged_wm, now=now),
            "conservation": conservation,
            "lockwatch": {"counters": lockwatch_counters,
                          "findings": lockwatch_findings},
        }


# -- merged Perfetto export ---------------------------------------------------

def _segment_trace_ids(segments: list[dict],
                       transfer_id: str) -> set:
    """Trace ids belonging to `transfer_id`: any span whose args name
    it roots the membership (snapshot_op / part carry `transfer_id`,
    fleet spans carry `transfer_id`/`transfer`)."""
    ids: set = set()
    for seg in segments:
        for rec in seg.get("spans", []):
            try:
                args, trace_id = rec[7], rec[8]
            except (IndexError, TypeError):
                continue
            if not trace_id or not isinstance(args, dict):
                continue
            if transfer_id in (args.get("transfer_id"),
                               args.get("transfer"),
                               args.get("ticket_id")):
                ids.add(trace_id)
    return ids


def export_fleet_chrome_trace(raw_segments: list,
                              transfer_id: str = "") -> dict:
    """ONE Chrome trace-event doc out of N processes' span deltas.

    Each process renders as its own Perfetto pid lane (named by its
    worker label); spans shift onto the shared wall-clock axis via the
    per-segment capture epoch; cross-process/thread parent links
    (trace ids propagated over the Flight wire, shm framing, and fleet
    tickets) render as flow arrows.  `transfer_id` filters to the
    traces that touch one transfer — `trtpu trace --fleet <id>`."""
    segments, corrupt = _parse_segments(raw_segments)
    keep_ids = _segment_trace_ids(segments, transfer_id) \
        if transfer_id else None
    epochs = [float(s.get("epoch_unix", 0.0) or 0.0) for s in segments
              if s.get("spans")]
    epoch0 = min(epochs) if epochs else 0.0

    events: list[dict] = []
    located: dict[int, tuple] = {}      # span_id -> (lane, tid, ts)
    pending_links: list[tuple] = []     # (lane, tid, ts, parent, span_id)
    pid_names: dict[int, str] = {}
    thread_names: dict[tuple, str] = {}
    seen: set = set()
    # Perfetto lane per PROCESS — keyed (host, pid), because bare pids
    # collide across hosts (pid-1 containers).  The real pid is kept
    # as the lane id when it is unique; a cross-host collision bumps
    # the later host onto an offset lane.
    lanes: dict[tuple, int] = {}
    used_lanes: set = set()
    hosts = {str(s.get("host", "")) for s in segments}

    def _lane(proc: tuple) -> int:
        lane = lanes.get(proc)
        if lane is None:
            lane = proc[1]
            while lane in used_lanes:
                lane += 1_000_000
            lanes[proc] = lane
            used_lanes.add(lane)
        return lane

    for seg in segments:
        proc = _proc_key(seg)
        pid = _lane(proc)
        shift = float(seg.get("epoch_unix", epoch0) or epoch0) - epoch0
        label = str(seg.get("worker", proc[1]))
        if len(hosts) > 1 and proc[0]:
            label = f"{label}@{proc[0]}"
        for rec in seg.get("spans", []):
            try:
                (name, tid, tname, t0, dur, _self_s, depth, args,
                 trace_id, span_id, parent_id) = rec[:11]
            except (ValueError, TypeError):
                continue
            if keep_ids is not None and trace_id not in keep_ids:
                continue
            key = (proc, trace_id, span_id) if span_id else \
                (proc, tid, name, round(float(t0), 9), parent_id)
            if key in seen:     # overlapping export windows re-send
                continue
            seen.add(key)
            pid_names.setdefault(pid, label)
            thread_names.setdefault((pid, tid), tname)
            ts = round((float(t0) + shift) * 1e6, 1)
            ev = {"name": name, "cat": "pipeline", "pid": pid,
                  "tid": tid, "ts": ts}
            if depth is not None and depth < 0:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = round(float(dur) * 1e6, 1)
                if span_id:
                    located[span_id] = (pid, tid, ts)
            if args:
                ev["args"] = dict(args)
            if trace_id:
                ids = ev.setdefault("args", {})
                ids["trace_id"] = trace_id
                if span_id:
                    ids["span_id"] = span_id
                if parent_id:
                    ids["parent_id"] = parent_id
            events.append(ev)
            if parent_id and span_id:
                pending_links.append((pid, tid, ts, parent_id, span_id))
    flows: list[dict] = []
    for pid, tid, ts, parent_id, span_id in pending_links:
        src = located.get(parent_id)
        if src is None or (src[0], src[1]) == (pid, tid):
            continue            # same-lane nesting needs no arrow
        flows.append({"name": "causal", "cat": "flow", "ph": "s",
                      "id": span_id, "pid": src[0], "tid": src[1],
                      "ts": src[2]})
        flows.append({"name": "causal", "cat": "flow", "ph": "f",
                      "bp": "e", "id": span_id, "pid": pid, "tid": tid,
                      "ts": ts})
    meta: list[dict] = []
    for pid, label in sorted(pid_names.items()):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": f"trtpu {label}"}})
    for (pid, tid), tname in sorted(thread_names.items()):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": tname}})
    return {
        "traceEvents": meta + events + flows,
        "displayTimeUnit": "ms",
        "otherData": {
            "segments": len(segments),
            "corrupt_segments": corrupt,
            "processes": len(pid_names),
            "transfer_filter": transfer_id,
        },
    }


# -- panes --------------------------------------------------------------------

_FLEET_TOP_COLS = (
    ("transfer", 22), ("tenant", 10), ("wrk", 4), ("rows_in", 9),
    ("rows_out", 9), ("mb_in", 8), ("mb_out", 8), ("h2d_mb", 8),
    ("launch", 7), ("retry", 6), ("steal", 6), ("fires", 6),
    ("commit", 7), ("fence", 6), ("lag_ms", 8),
)


def format_fleet_top(view: dict, limit: int = 20) -> str:
    """Render one `trtpu top --fleet` frame from a merged view."""
    lines = []
    tot = view.get("totals", {})
    cons = view.get("conservation", {})
    lines.append(
        f"fleet obs: {view.get('segments', 0)} segment(s) from "
        f"{view.get('processes', 0)} process(es)"
        + (f" ({view['corrupt_segments']} torn)"
           if view.get("corrupt_segments") else "")
        + f"  rows {tot.get('rows_in', 0)}→{tot.get('rows_out', 0)}"
        f"  h2d {tot.get('h2d_bytes', 0) / 1e6:.1f}MB"
        f"  launches {tot.get('launches', 0)}"
        f"  conservation {'OK' if cons.get('ok') else 'DRIFT'}")
    workers = view.get("workers", {})
    if workers:
        roll = "  ".join(
            f"{label}[{w['age_seconds']:.1f}s ago, {w.get('kind', '?')}]"
            for label, w in sorted(
                workers.items(),
                key=lambda kv: kv[1]["age_seconds"])[:8])
        lines.append(f"workers: {roll}")
    hists = view.get("hists", {})
    if hists:
        ranked = sorted(hists.items(),
                        key=lambda kv: -kv[1].get("count", 0))[:6]
        lines.append("latency: " + "  ".join(
            f"{name}[p50={h.get('p50_ms', 0)} p99={h.get('p99_ms', 0)} "
            f"p999={h.get('p999_ms', 0)}ms n={h.get('count', 0)}]"
            for name, h in ranked))
    lag = hists.get(watermark.STAGE_LAG) if hists else None
    if lag and lag.get("count"):
        lines.append(
            f"replication lag: p50={lag.get('p50_ms', 0)} "
            f"p99={lag.get('p99_ms', 0)} p999={lag.get('p999_ms', 0)}ms "
            f"n={lag.get('count', 0)}")
    lines.append(" ".join(f"{name:>{w}}"
                          for name, w in _FLEET_TOP_COLS))
    rows = sorted(view.get("transfers", {}).items(),
                  key=lambda kv: -(kv[1].get("bytes_out", 0)
                                   + kv[1].get("bytes_in", 0)))
    fresh = view.get("freshness", {})
    for tid, v in rows[:limit]:
        lag_ms = fresh.get(tid, {}).get("lag_ms")
        cells = (tid[:22], str(v.get("tenant", "-"))[:10],
                 len(v.get("workers", [])), v.get("rows_in", 0),
                 v.get("rows_out", 0),
                 f"{v.get('bytes_in', 0) / 1e6:.1f}",
                 f"{v.get('bytes_out', 0) / 1e6:.1f}",
                 f"{v.get('h2d_bytes', 0) / 1e6:.1f}",
                 v.get("launches", 0), v.get("retries", 0),
                 v.get("lease_steals", 0), v.get("chaos_fires", 0),
                 v.get("commits", 0), v.get("commit_fences", 0),
                 "-" if lag_ms is None else f"{lag_ms:.0f}")
        lines.append(" ".join(
            f"{c:>{w}}" for c, (_n, w) in zip(cells, _FLEET_TOP_COLS)))
    if len(rows) > limit:
        lines.append(f"... {len(rows) - limit} more transfers")
    return "\n".join(lines)


# -- runtime registration (the /debug surfaces) -------------------------------

_runtime_lock = threading.Lock()
_RUNTIME: Optional[dict] = None


def register_runtime(coordinator, scope: Optional[str] = None,
                     health_scope: str = "") -> None:
    """Give this process's health port a coordinator to read the fleet
    panes from (`trtpu worker` registers on startup; tests register
    explicitly).  `health_scope` is the operation_health scope the
    fleet workers heartbeat into (`fleet:<queue>`) — the liveness
    source for `/debug/fleet`."""
    global _RUNTIME
    with _runtime_lock:
        _RUNTIME = {"cp": coordinator,
                    "scope": scope or default_scope(),
                    "health_scope": health_scope}


def unregister_runtime() -> None:
    global _RUNTIME
    with _runtime_lock:
        _RUNTIME = None


def _runtime() -> Optional[dict]:
    with _runtime_lock:
        return dict(_RUNTIME) if _RUNTIME else None


def debug_fleet_obs() -> Optional[dict]:
    """The `GET /debug/fleet/obs` payload: the merged fleet view read
    through the registered coordinator (None = nothing registered)."""
    rt = _runtime()
    if rt is None:
        return None
    try:
        segments = rt["cp"].list_obs_segments(rt["scope"])
    except Exception as e:
        return {"error": f"obs segment listing failed: {e}"}
    view = merge_segments(segments)
    view["scope"] = rt["scope"]
    return view


def worker_liveness() -> Optional[dict]:
    """Per-worker heartbeat liveness ages from the coordinator's
    `get_operation_health` (the `/debug/fleet` satellite: a stale
    worker is visible here long before its lease expires)."""
    rt = _runtime()
    if rt is None or not rt.get("health_scope"):
        return None
    try:
        health = rt["cp"].get_operation_health(rt["health_scope"])
    except Exception as e:
        return {"error": f"health read failed: {e}"}
    now = time.time()
    out = {}
    for widx, rep in sorted(health.items(), key=lambda kv: str(kv[0])):
        ts = rep.get("ts")
        payload = rep.get("payload") or {}
        out[str(widx)] = {
            "age_seconds": round(max(0.0, now - ts), 3)
            if isinstance(ts, (int, float)) else None,
            "state": payload.get("state", ""),
            "ticket": payload.get("ticket", ""),
            "tickets_run": payload.get("tickets_run", 0),
        }
    return {"scope": rt["health_scope"], "workers": out}


def read_view(coordinator, scope: Optional[str] = None) -> dict:
    """One-shot read+merge for CLI panes (`trtpu top --fleet`)."""
    scope = scope or default_scope()
    view = merge_segments(coordinator.list_obs_segments(scope))
    view["scope"] = scope
    return view


def _json_default(v):
    return str(v)


def dumps_view(view: dict) -> str:
    return json.dumps(view, default=_json_default)
