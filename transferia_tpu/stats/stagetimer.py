"""Lightweight pipeline stage timing (bench/diagnostic instrumentation).

The reference's perf workflow is pprof+speedscope (docs/benchmarks.md:44-60);
the TPU-native equivalent needs wall-time attribution across the
host/device boundary, which a sampling profiler can't see (device waits
look like idle).  This module accumulates per-thread wall time into named
stages — source_decode, pivot, pack, device_dispatch, device_wait,
host_post, sink — with near-zero overhead when disabled (one module-level
bool check).

Totals are summed across threads, so with N part-upload threads a stage
total can exceed wall time; the point is the *ratio* between stages and
the overlap factor (sum(stages)/wall).

Per-stage latency DISTRIBUTIONS live in stats/hdr.py: every enabled
stage() / add() also lands in the process-global mergeable log-bucket
histograms (STAGES), which is what the fleet observability plane
exports — the scalar totals here stay the cheap single-process
breakdown, the histograms are the cross-process p50/p99/p999 source.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

from transferia_tpu.stats import hdr

_enabled = False
_lock = threading.Lock()
_totals: dict[str, float] = {}
_counts: dict[str, int] = {}
_sample_stages: set[str] = set()
_samples: dict[str, list[float]] = {}


def collect_samples(*names: str) -> None:
    """Also keep per-call durations for these stages (for percentiles)."""
    _sample_stages.update(names)


def samples(name: str) -> list[float]:
    with _lock:
        return list(_samples.get(name, ()))


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _lock:
        _totals.clear()
        _counts.clear()
        _samples.clear()


@contextmanager
def stage(name: str):
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        with _lock:
            _totals[name] = _totals.get(name, 0.0) + dt
            _counts[name] = _counts.get(name, 0) + 1
            if name in _sample_stages:
                _samples.setdefault(name, []).append(dt)
        hdr.observe(name, dt)


def add(name: str, seconds: float) -> None:
    if not _enabled:
        return
    with _lock:
        _totals[name] = _totals.get(name, 0.0) + seconds
        _counts[name] = _counts.get(name, 0) + 1
        if name in _sample_stages:
            _samples.setdefault(name, []).append(seconds)
    hdr.observe(name, seconds)


def snapshot() -> dict[str, dict]:
    with _lock:
        return {
            k: {"seconds": round(v, 4), "calls": _counts.get(k, 0)}
            for k, v in sorted(_totals.items())
        }


def format_breakdown(wall_seconds: float) -> str:
    snap = snapshot()
    if not snap:
        return ""
    parts = []
    for name, d in sorted(snap.items(), key=lambda kv: -kv[1]["seconds"]):
        pct = 100.0 * d["seconds"] / wall_seconds if wall_seconds else 0.0
        parts.append(f"{name}={d['seconds']:.2f}s({pct:.0f}%)")
    total = sum(d["seconds"] for d in snap.values())
    overlap = total / wall_seconds if wall_seconds else 0.0
    parts.append(f"overlap_factor={overlap:.2f}")
    return " ".join(parts)
