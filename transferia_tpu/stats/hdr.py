"""Mergeable log-bucketed latency histograms (HDR-style).

The scalar stage timers (stats/stagetimer.py totals, trace.py
stage_summary percentiles over raw duration lists) are per-process and
un-mergeable: N workers each holding a sorted list of durations cannot
produce a fleet p99 without shipping every sample.  This module is the
mergeable replacement the fleet observability plane exports inside obs
segments (stats/fleetobs.py): values land in fixed log2 buckets with
SUB sub-buckets per octave, so

    merge(h(A), h(B)) == h(A ++ B)     (exact, bucket-wise add)

holds by construction — the property the fleet panes and `bench.py
--fleet` tail rely on to report cross-process p50/p99/p999.

Bucketing: for v seconds, frexp(v) = (m, e) with m in [0.5, 1);
the bucket index is (e + BIAS) * SUB + floor((m - 0.5) * 2 * SUB) —
SUB=16 sub-buckets per octave bounds the relative quantile error at
~1/(2*16) ≈ 3%, plenty for tail-latency SLO work, while a year-long
duration still fits in a couple thousand sparse buckets.

Exemplars: the histogram remembers the largest observed value and the
trace id active when it was recorded (`max_trace`) — the fleet pane's
"jump to the worst dispatch in Perfetto" hook.  Merging keeps the
exemplar of whichever side holds the larger max.

`STAGES` is the process-global registry the export plane snapshots;
recording is one dict lookup + a few int adds under a lock, cheap
enough for per-part / per-dispatch call sites (never per-row).
"""

from __future__ import annotations

import math
import threading
from typing import Optional

SUB = 16          # sub-buckets per octave (power of two)
BIAS = 64         # supports values down to 2^-64 s
_MIN_VALUE = 2.0 ** -BIAS


def bucket_index(value: float) -> int:
    """Sparse bucket index for a duration in seconds (<=0 clamps to
    the smallest bucket — negative latencies are clock skew, not
    data)."""
    if value < _MIN_VALUE:
        return 0
    m, e = math.frexp(value)          # value = m * 2**e, m in [0.5, 1)
    idx = (e + BIAS) * SUB + int((m - 0.5) * 2 * SUB)
    return max(0, idx)


def bucket_mid(idx: int) -> float:
    """Representative value (bucket midpoint) for quantile read-back."""
    e = idx // SUB - BIAS
    sub = idx % SUB
    lo = math.ldexp(1.0 + sub / SUB, e - 1)
    hi = math.ldexp(1.0 + (sub + 1) / SUB, e - 1)
    return (lo + hi) / 2.0


class LogHistogram:
    """One mergeable latency distribution (sparse log2 buckets).

    Not thread-safe on its own — callers (the STAGES registry, the
    merge plane) hold their own locks; a histogram inside an obs
    segment is immutable data."""

    __slots__ = ("counts", "count", "total", "max_value", "max_trace",
                 "min_value")

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.max_value = 0.0
        self.min_value = 0.0
        self.max_trace = 0        # trace id active at the max (0 = none)

    def observe(self, value: float, trace_id: int = 0) -> None:
        value = float(value)
        idx = bucket_index(value)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.count += 1
        self.total += value
        if value > self.max_value or self.count == 1:
            self.max_value = value
            self.max_trace = int(trace_id or 0)
        if value < self.min_value or self.count == 1:
            self.min_value = value

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Bucket-wise add of `other` into self (exact: merge of two
        histograms equals the histogram of the concatenated samples)."""
        for idx, n in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        if other.count:
            if not self.count - other.count or \
                    other.max_value > self.max_value:
                self.max_value = other.max_value
                self.max_trace = other.max_trace
            if not self.count - other.count or \
                    other.min_value < self.min_value:
                self.min_value = other.min_value
        return self

    def quantile(self, q: float) -> float:
        """Value at quantile q in [0, 1] (0.0 for an empty histogram).
        The top occupied bucket reads back the exact max — tails never
        round up past an observation."""
        if self.count <= 0:
            return 0.0
        rank = max(1, math.ceil(min(max(q, 0.0), 1.0) * self.count))
        indices = sorted(self.counts)
        cum = 0
        for idx in indices:
            cum += self.counts[idx]
            if cum >= rank:
                if idx == indices[-1]:
                    return self.max_value
                return bucket_mid(idx)
        return self.max_value

    def fraction_at_most(self, value: float) -> float:
        """Fraction of observations <= value (the SLO good-event
        ratio).  1.0 for an empty histogram — no observations means no
        bad events, not a breach.  The bucket containing `value` counts
        as good, so the answer inherits the ~3% bucket granularity —
        plenty for burn-rate work, and exactly reproducible from any
        merge order."""
        if self.count <= 0:
            return 1.0
        limit = bucket_index(value)
        good = sum(n for idx, n in self.counts.items() if idx <= limit)
        return min(1.0, good / self.count)

    def diff(self, baseline: "LogHistogram") -> "LogHistogram":
        """Self minus a prior snapshot of the SAME histogram (bucket-
        wise, clamped at 0) — how a bench carves its own window out of
        the process-global registry.  The max/exemplar are taken from
        self when any new observation landed (approximate: the true
        window max is unrecoverable from cumulative buckets, but a
        bench window's max is almost always the lifetime max)."""
        out = LogHistogram()
        for idx, n in self.counts.items():
            d = n - baseline.counts.get(idx, 0)
            if d > 0:
                out.counts[idx] = d
        out.count = max(0, self.count - baseline.count)
        out.total = max(0.0, self.total - baseline.total)
        if out.count:
            out.max_value = self.max_value
            out.max_trace = self.max_trace
            out.min_value = self.min_value
        return out

    # -- wire form (obs segments) --------------------------------------------
    def to_json(self) -> dict:
        return {
            "counts": {str(i): n for i, n in sorted(self.counts.items())},
            "count": self.count,
            "total": round(self.total, 9),
            "max": self.max_value,
            "min": self.min_value,
            "max_trace": self.max_trace,
        }

    @classmethod
    def from_json(cls, d: dict) -> "LogHistogram":
        """Tolerant of junk: a torn segment must degrade to an empty
        histogram, never raise into the merge loop."""
        h = cls()
        if not isinstance(d, dict):
            return h
        raw = d.get("counts")
        if isinstance(raw, dict):
            for k, n in raw.items():
                try:
                    idx, cnt = int(k), int(n)
                except (TypeError, ValueError):
                    continue
                if cnt > 0:
                    h.counts[idx] = h.counts.get(idx, 0) + cnt
        try:
            h.count = max(0, int(d.get("count", 0)))
            h.total = float(d.get("total", 0.0))
            h.max_value = float(d.get("max", 0.0))
            h.min_value = float(d.get("min", 0.0))
            h.max_trace = int(d.get("max_trace", 0) or 0)
        except (TypeError, ValueError):
            pass
        if h.count != sum(h.counts.values()):
            # torn counts vs header: trust the buckets (quantiles stay
            # internally consistent; totals are advisory)
            h.count = sum(h.counts.values())
        return h

    def summary(self) -> dict:
        """The pane row: p50/p99/p999 in ms + count + max exemplar."""
        return {
            "count": self.count,
            "p50_ms": round(self.quantile(0.50) * 1000.0, 3),
            "p99_ms": round(self.quantile(0.99) * 1000.0, 3),
            "p999_ms": round(self.quantile(0.999) * 1000.0, 3),
            "max_ms": round(self.max_value * 1000.0, 3),
            "mean_ms": round(
                (self.total / self.count) * 1000.0, 3) if self.count
            else 0.0,
            "max_trace": self.max_trace,
        }


class StageHistograms:
    """Process-global per-stage registry (module singleton STAGES).

    `observe` defaults the exemplar to the active trace context, so the
    max bucket of every exported histogram points at a real span id in
    the merged fleet timeline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[str, LogHistogram] = {}

    def observe(self, stage: str, seconds: float,
                trace_id: Optional[int] = None) -> None:
        if trace_id is None:
            from transferia_tpu.stats import trace

            ctx = trace.current_context()
            trace_id = ctx.trace_id if ctx else 0
        with self._lock:
            h = self._hists.get(stage)
            if h is None:
                h = self._hists[stage] = LogHistogram()
            h.observe(seconds, trace_id)

    def get(self, stage: str) -> LogHistogram:
        """A copy of one stage's histogram (empty when unseen) — safe
        to use as a diff baseline."""
        with self._lock:
            h = self._hists.get(stage)
            return LogHistogram.from_json(h.to_json()) if h \
                else LogHistogram()

    def snapshot(self) -> dict[str, dict]:
        """{stage: histogram json} — the obs-segment payload."""
        with self._lock:
            return {name: h.to_json()
                    for name, h in sorted(self._hists.items())}

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


STAGES = StageHistograms()


def observe(stage: str, seconds: float,
            trace_id: Optional[int] = None) -> None:
    """Module-level convenience: record one latency into the global
    per-stage registry."""
    STAGES.observe(stage, seconds, trace_id)


def merge_stage_maps(maps: list[dict]) -> dict[str, LogHistogram]:
    """Merge N segments' `hists` payloads into live histograms —
    bucket-wise exact, junk-tolerant (a torn map contributes what it
    can)."""
    out: dict[str, LogHistogram] = {}
    for m in maps:
        if not isinstance(m, dict):
            continue
        for name, d in m.items():
            h = out.get(name)
            if h is None:
                h = out[name] = LogHistogram()
            h.merge(LogHistogram.from_json(d))
    return out
