"""Sink factory: assembles the full middleware pipeline.

Reference parity: pkg/sink_factory/sink_factory.go — sync middleware order
(:97-134, innermost first): TargetFallbacks, SourceFallbacks, Statistician,
Filter(system tables), NonRowSeparator, Transformation; async wrap
(:179-197): ErrorTracker(MemThrottler(Bufferer|Synchronizer(sync stack))).

Push flow (outermost -> innermost):

  async_push -> ErrorTracker -> [MemThrottler] -> Bufferer/Synchronizer
    -> [Retrier @snapshot] -> Measurer -> Transformation -> NonRowSeparator
    -> Filter -> Statistician -> SourceFallbacks -> TargetFallbacks -> sink

The Bufferer sits at the async/sync boundary so the transformer chain (and
its jitted kernels) sees large merged batches, not source-sized fragments.
"""

from __future__ import annotations

from typing import Optional

from transferia_tpu.abstract.interfaces import AsyncSink, Sinker
from transferia_tpu.abstract.schema import TableID
from transferia_tpu.middlewares.asynchronizer import (
    Bufferer,
    BuffererConfig,
    ErrorTracker,
    MemThrottler,
    Synchronizer,
)
from transferia_tpu.middlewares.sync import (
    Filter,
    Measurer,
    NonRowSeparator,
    Retrier,
    Statistician,
    Transformation as TransformationMW,
    TypeFallbacks,
)
from transferia_tpu.models.endpoint import capability
from transferia_tpu.providers.registry import get_provider
from transferia_tpu.stats.registry import Metrics, SinkerStats
from transferia_tpu.transform.chain import build_chain
from transferia_tpu.typesystem.fallbacks import fallbacks_for

SYSTEM_TABLE_PREFIX = "__"  # system tables excluded from delivery by default


def _system_table_filter(tid: TableID) -> bool:
    return tid.name.startswith(SYSTEM_TABLE_PREFIX) and \
        tid.name not in ("__test",)


def make_sinker(transfer, metrics: Optional[Metrics] = None,
                snapshot_stage: bool = False,
                stats: Optional[SinkerStats] = None,
                post_transform_wrap=None) -> Sinker:
    """Build the synchronous middleware stack over the provider's raw sink."""
    metrics = metrics or Metrics()
    provider = get_provider(transfer.dst_provider(), transfer, metrics)
    raw: Optional[Sinker] = None
    if snapshot_stage:
        raw = provider.snapshot_sinker()
    if raw is None:
        raw = provider.sinker()
    if raw is None:
        raise ValueError(
            f"provider {transfer.dst_provider()!r} has no sink capability"
        )
    version = transfer.type_system_version
    s: Sinker = raw
    tgt_fb = fallbacks_for(transfer.dst_provider(), "target", version)
    if tgt_fb:
        s = TypeFallbacks(s, tgt_fb)
    src_fb = fallbacks_for(transfer.src_provider(), "source", version)
    if src_fb:
        s = TypeFallbacks(s, src_fb)
    from transferia_tpu.metering.agent import (
        InputMetering,
        OutputMetering,
        metering_agent,
    )

    agent = metering_agent(transfer.id)
    s = OutputMetering(s, agent)
    s = Statistician(s, stats or SinkerStats(metrics),
                     transfer_id=transfer.id)
    s = Filter(s, _system_table_filter)
    s = NonRowSeparator(s)
    if post_transform_wrap is not None:
        # injection point for observers of post-transform data (the
        # snapshot loader's inline fingerprint tap)
        s = post_transform_wrap(s)
    chain = build_chain(transfer.transformation)
    if chain is not None:
        s = TransformationMW(s, chain)
    s = InputMetering(s, agent)
    s = Measurer(s)
    if snapshot_stage:
        s = Retrier(s)
    return s


def make_async_sink(transfer, metrics: Optional[Metrics] = None,
                    snapshot_stage: bool = False,
                    stats: Optional[SinkerStats] = None,
                    post_transform_wrap=None) -> AsyncSink:
    """MakeAsyncSink (sink_factory.go:31): full async pipeline.

    Providers may supply a native AsyncSink (constructBaseAsyncSink:173);
    otherwise the sync stack is wrapped with Bufferer (when the destination
    opts in via `bufferer_config`) or Synchronizer.  A native async sink
    has no sync stack to host post_transform_wrap; inline validation is
    skipped there (the provider owns its own pipeline).
    """
    metrics = metrics or Metrics()
    provider = get_provider(transfer.dst_provider(), transfer, metrics)
    native = provider.async_sink()
    if native is not None:
        return ErrorTracker(native)
    sync_stack = make_sinker(transfer, metrics, snapshot_stage, stats,
                             post_transform_wrap=post_transform_wrap)
    buf_cfg = capability(transfer.dst, "bufferer_config", None)
    if buf_cfg is not None and not isinstance(buf_cfg, BuffererConfig):
        buf_cfg = BuffererConfig(**buf_cfg) if isinstance(buf_cfg, dict) \
            else BuffererConfig()
    a: AsyncSink
    if buf_cfg is not None:
        a = Bufferer(sync_stack, buf_cfg)
    else:
        a = Synchronizer(sync_stack)
    limit = capability(transfer.dst, "memory_limit_bytes", None)
    if limit:
        a = MemThrottler(a, limit)
    return ErrorTracker(a)
