"""Source factory (pkg/source_factory/source_factory.go:13)."""

from __future__ import annotations

from typing import Optional

from transferia_tpu.abstract.interfaces import Source
from transferia_tpu.providers.registry import get_provider
from transferia_tpu.stats.registry import Metrics


def new_source(transfer, metrics: Optional[Metrics] = None,
               coordinator=None) -> Source:
    provider = get_provider(transfer.src_provider(), transfer, metrics,
                            coordinator)
    source = provider.source()
    if source is None:
        raise ValueError(
            f"provider {transfer.src_provider()!r} has no replication "
            f"capability"
        )
    return source
