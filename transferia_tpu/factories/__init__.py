"""Factories resolving providers into pipeline components.

Reference parity: pkg/storage_factory, pkg/source_factory, pkg/sink_factory.
"""

from transferia_tpu.factories.sink import make_async_sink, make_sinker
from transferia_tpu.factories.source import new_source
from transferia_tpu.factories.storage import new_storage

__all__ = ["make_async_sink", "make_sinker", "new_source", "new_storage"]
