"""Storage factory (pkg/storage_factory/storage_factory.go:15)."""

from __future__ import annotations

from typing import Optional

from transferia_tpu.abstract.interfaces import Storage
from transferia_tpu.providers.registry import get_provider
from transferia_tpu.stats.registry import Metrics


def new_storage(transfer, metrics: Optional[Metrics] = None) -> Storage:
    provider = get_provider(transfer.src_provider(), transfer, metrics)
    storage = provider.storage()
    if storage is None:
        raise ValueError(
            f"provider {transfer.src_provider()!r} has no snapshot capability"
        )
    return storage
