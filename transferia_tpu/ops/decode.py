"""Device-side columnar decode kernels.

BASELINE.json config 3's north star is literally "columnar decode on
TPU": the hot shape of parquet decode is bit-unpack of RLE_DICTIONARY
codes followed by a dictionary gather, and both map cleanly onto the
chip — unpack is pure vectorized shift/mask arithmetic (VPU), the gather
rides HBM bandwidth.  ops/placement keeps the END-TO-END decode on the
host whenever the link model says transfers would swamp the chip (the
tunneled dev environment), exactly as with the mask kernel; this module
is the proof-point that the chip itself sustains the decode op, measured
by bench.py as device_decode_rows_per_sec on resident buffers.

Scope mirrors the native decoder's hot path (native/parquetdec.cpp
RleDecoder + dict gather):
  - unpack_bits: n fixed-width (<=32 bit) values from a little-endian
    packed uint32 word stream — the body of a bit-packed RLE run
  - decode_dict_run: unpack + jnp.take through the dictionary pool

Run HEADERS (varint framing) stay on the host: they are a sequential
byte-stream parse of a few bytes per ~hundreds of values, the opposite
of device-shaped work.  The host splits runs; the device does the
per-value arithmetic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(1, 2))
def unpack_bits(words: jax.Array, bit_width: int, n: int) -> jax.Array:
    """values[i] = bits [i*bw, (i+1)*bw) of the packed little-endian
    stream, as int32.  bit_width must be 0 < bw <= 32; a value spans at
    most two 32-bit words ((hi:lo) >> off, shift-by-32 guarded)."""
    if not 0 < bit_width <= 32:
        raise ValueError(f"bit_width {bit_width} outside (0, 32]")
    return _unpack_core(words, bit_width, n)


@functools.partial(jax.jit, static_argnums=(2, 3))
def decode_dict_run(words: jax.Array, pool: jax.Array, bit_width: int,
                    n: int) -> jax.Array:
    """Bit-unpack n dictionary codes and gather their pool values —
    the device half of an RLE_DICTIONARY data page."""
    codes = unpack_bits(words, bit_width, n)
    return jnp.take(pool, codes, axis=0, mode="clip")


def gather_pool_accumulators(accs: jax.Array,
                             codes: jax.Array) -> jax.Array:
    """Dict-native fingerprint gather (ops/rowhash.py device backend):
    per-row lane accumulators from per-POOL-ENTRY accumulators by int32
    code — the reduction plane consuming dict codes directly, the same
    HBM-bandwidth shape as decode_dict_run's value gather.  Traceable
    inline; codes padded past the pool clip to entry 0 (the caller's
    rowmask zeroes those lanes)."""
    return jnp.take(accs, codes, mode="clip")


@functools.partial(jax.jit, static_argnums=(1,))
def unpack_validity(words: jax.Array, n: int) -> jax.Array:
    """Packed little-endian validity bitmap -> (n,) bool.

    The width-1 specialization of unpack_bits: a batch's null bitmap
    ships as n/8 bytes instead of n bool bytes (the compressed-dispatch
    plane's cheapest win — ops/dispatch.py packs, this unpacks)."""
    return _unpack_core(words, 1, n).astype(jnp.bool_)


def delta_prefix_sum(words: jax.Array, base: jax.Array, bit_width: int,
                     n: int) -> jax.Array:
    """Zigzag-delta decode: values[i] = base + Σ deltas[0..i], int32.

    The device half of the delta+bit-pack integer encoding
    (ops/dispatch.encode_delta): deltas arrive zigzag-encoded so the
    unpacked codes are non-negative; the prefix sum reconstructs the
    column exactly (encode rejects widths > 30 bits, so every partial
    sum fits int32 with no wraparound).  Traceable inline — callers
    inside larger jitted programs use this form directly."""
    zz = _unpack_core(words, bit_width, n)
    deltas = (zz >> 1) ^ -(zz & 1)
    return base.astype(jnp.int32) + jnp.cumsum(deltas, dtype=jnp.int32)


delta_decode = jax.jit(delta_prefix_sum, static_argnums=(2, 3))


def for_frame_decode(words: jax.Array, mins: jax.Array, bit_width: int,
                     frame: int, n: int) -> jax.Array:
    """Frame-of-reference decode: values[i] = mins[i // frame] + rel[i].

    The device half of the FOR integer encoding
    (ops/dispatch.encode_for): clustered-but-unsorted ids whose zigzag
    deltas are too wide for the delta wire still pack tightly once each
    static-size frame subtracts its own minimum.  rel values arrive
    bit-packed; int32 adds wrap two's-complement, so a 32-bit rel span
    reconstructs exactly for any value that fits int32 (the encoder
    guards that).  Traceable inline — callers inside larger jitted
    programs use this form directly; n must be a multiple of `frame`
    (row buckets are, for the power-of-two frame sizes the encoder
    emits)."""
    rel = _unpack_core(words, bit_width, n)
    base = jnp.repeat(mins.astype(jnp.int32), frame,
                      total_repeat_length=n)
    return base + rel


for_decode = jax.jit(for_frame_decode, static_argnums=(2, 3, 4))


def pack_mask_words(bits: jax.Array, n: int) -> jax.Array:
    """(n,) bool -> packed little-endian uint32 words (device side).

    The D2H twin of unpack_validity: the fused program returns its keep
    mask as n/8 bytes instead of n bool bytes.  n must be a multiple of
    32 (row buckets are — columnar.batch._BUCKETS).  Traceable inline."""
    b = bits.reshape(n // 32, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return (b * weights).sum(axis=1).astype(jnp.uint32)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def decode_dict_loop(words: jax.Array, pool: jax.Array, bit_width: int,
                     n: int, iters: int) -> jax.Array:
    """`iters` back-to-back decodes in ONE launch (bench helper: a
    tunneled link's ~100ms launch overhead would otherwise swamp an op
    that is pure HBM traffic).  The carry perturbs the input words each
    iteration so XLA cannot hoist or CSE the loop body; returns a
    checksum the caller discards after sync."""
    def body(i, acc):
        w = words ^ (acc & jnp.uint32(1))
        codes = _unpack_core(w, bit_width, n)
        vals = jnp.take(pool, codes, axis=0, mode="clip")
        return acc + vals.sum().astype(jnp.uint32)

    return jax.lax.fori_loop(0, iters, body, jnp.uint32(0))


def _unpack_core(w: jax.Array, bit_width: int, n: int) -> jax.Array:
    """unpack_bits body without the jit wrapper (traced inline).

    TPU gathers run orders of magnitude below HBM speed, so the hot
    shape (n a multiple of 32) avoids them entirely: every group of 32
    values consumes exactly bit_width words, so reshaping to
    (n/32, bit_width) makes each lane's word indices STATIC — the
    unpack becomes column slices + shifts, pure VPU work.  Ragged n
    falls back to the gather form."""
    w = w.astype(jnp.uint32)
    bw = bit_width
    if n % 32 == 0 and len(w.shape) == 1:
        g = n // 32
        need = g * bw
        wg = w[:need].reshape(g, bw)
        mask = (jnp.uint32((1 << bw) - 1) if bw < 32
                else jnp.uint32(0xFFFFFFFF))
        lanes = []
        for j in range(32):
            k, off = divmod(j * bw, 32)
            lo = wg[:, k] >> jnp.uint32(off)
            if off + bw > 32:
                lo = lo | (wg[:, k + 1] << jnp.uint32(32 - off))
            lanes.append(lo & mask)
        return jnp.stack(lanes, axis=1).reshape(n).astype(jnp.int32)
    starts = jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(bw)
    wi = (starts >> 5).astype(jnp.int32)
    off = (starts & 31).astype(jnp.uint32)
    lo = jnp.take(w, wi, mode="clip")
    hi = jnp.take(w, wi + 1, mode="clip")
    upper = jnp.where(off > 0,
                      hi << ((jnp.uint32(32) - off) & jnp.uint32(31)),
                      jnp.uint32(0))
    v = (lo >> off) | upper
    if bw < 32:
        v = v & jnp.uint32((1 << bw) - 1)
    return v.astype(jnp.int32)
