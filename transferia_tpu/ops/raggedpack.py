"""Device-side ragged pack: var-width rows -> padded SHA block matrices.

The fused mask program (ops/fused.py) consumes (N, max_blocks*64) padded
message matrices.  The portable feed path packs them on the host (C++
pack_sha_blocks) and ships the padded matrix to the device — ~2.5x the
bytes of the raw ragged column for short strings.  This module moves the
pack onto the device: the host ships the *flat* byte buffer + offsets,
and the device builds the padded matrix (row gather + SHA padding
arithmetic: 0x80 terminator, big-endian bit length with the virtual HMAC
ipad prefix block accounted).

History, because it is instructive: this was first written as a Pallas
kernel (per-row async DMAs HBM->VMEM, grid over 32-row tiles) on the
theory that XLA lowers ragged byte gathers poorly.  Profiling on a real
v5e falsified both halves: (a) Mosaic cannot express the kernel at all —
rank-1 SMEM blocks must be 128-multiples, and per-row `width`-byte
slices of a 1D buffer violate the (1024)(128) tiling ("Slice shape along
dimension 0 must be aligned to tiling (1024), but is 128"); (b) the
plain XLA formulation below — one `jnp.take` with a computed (N, width)
index matrix plus vectorized padding — runs at sub-millisecond per 131k
rows on the same chip, i.e. at HBM-bandwidth, with byte parity against
the C++ host pack.  Hand-scheduling lost to the compiler; keep the
compiler (it replaced ops/ragged_pallas.py outright).

Opt-in (TRANSFERIA_TPU_PALLAS_PACK=1, historical name): on PCIe-attached
devices it halves H2D traffic for short strings; through a high-latency
tunnel the extra launch costs more than the bytes saved, and the
placement tuner keeps the whole mask on the host anyway.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=(3,))
def _pack_xla(flat, starts, lens, width: int):
    col = jnp.arange(width, dtype=jnp.int32)[None, :]
    idx = starts[:, None] + col
    raw = jnp.take(flat, idx, axis=0)            # (N, width) row gather
    lens2 = lens[:, None]
    msg = jnp.where(col < lens2, raw, 0)
    msg = jnp.where(col == lens2, jnp.uint8(0x80), msg)
    nb = (lens + 9 + 63) // 64
    pos = (nb * 64 - 8)[:, None]                 # length field start
    k = col - pos
    bits = ((lens + 64) * 8)[:, None]            # +64: HMAC ipad prefix
    shift = 8 * (7 - k)
    lenbyte = jnp.where(
        (k >= 0) & (k < 8) & (shift < 32),
        jax.lax.shift_right_logical(
            jnp.broadcast_to(bits, k.shape), jnp.clip(shift, 0, 31),
        ) & 0xFF,
        0,
    )
    msg = jnp.where((k >= 0) & (k < 8), lenbyte.astype(jnp.uint8), msg)
    return msg.astype(jnp.uint8), nb


def pack_blocks_device(flat_padded: np.ndarray, offsets: np.ndarray,
                       n_rows_bucket: int, max_blocks: int):
    """Pack ragged rows into padded SHA blocks on the device.

    flat_padded: (B + >=width slack,) uint8 — row gathers may overread up
    to width bytes past the last row; offsets: (n+1,) int32 for the true
    rows.  Returns device arrays (blocks (bucket, width) uint8, n_blocks
    (bucket,) int32); pad rows' content is garbage-but-valid (they re-read
    the final offset) and must be masked or sliced by the caller.
    """
    width = max_blocks * 64
    n = len(offsets) - 1
    starts = np.empty(n_rows_bucket, dtype=np.int32)
    lens = np.zeros(n_rows_bucket, dtype=np.int32)
    starts[:n] = offsets[:-1]
    starts[n:] = offsets[-1]
    lens[:n] = offsets[1:] - offsets[:-1]
    if n and int(lens[:n].max()) + 9 > width:
        # same contract as the host pack (prepare_padded_blocks): a row
        # that needs more blocks than max_blocks must fail loudly — the
        # padding arithmetic would silently truncate it otherwise
        raise ValueError(
            f"row of {int(lens[:n].max())} bytes needs more than "
            f"{max_blocks} SHA blocks")
    assert len(flat_padded) >= int(offsets[-1]) + width, \
        "flat buffer needs >= width slack bytes for row overreads"
    return _pack_xla(jnp.asarray(flat_padded), jnp.asarray(starts),
                     jnp.asarray(lens), width)
