"""Order-independent table fingerprints (device-reducible checksums).

The checksum task (tasks/checksum.py; reference
pkg/worker/tasks/checksum.go) compares tables by sampling rows and
comparing values host-side.  This module adds the complementary
*fingerprint* method: every row hashes to two 32-bit lanes, and a table's
fingerprint is the order-independent reduction (per-lane sum mod 2^32,
per-lane xor, row count) — O(1) state per table, mergeable across
snapshot shards (each worker fingerprints its parts; the coordinator
merge is `FingerprintAggregate.merge`), and reduction-shaped for the
device: the whole batch ships H2D once and 20 bytes come back.

The hash is NOT cryptographic — it is a table-equality witness, like
pt-table-checksum's CRC aggregation, not a defense against adversarial
collisions.  Two independent lanes (different polynomial bases and
finalizers) put an accidental-collision floor around 2^-64 per table
pair.

Canonicalization (identical in both backends, pinned by parity tests):
- var-width columns: the SHA-style padded block matrix from
  native/hostops.cpp pack_sha_blocks(prefix_len=0) — zero fill, 0x80
  terminator, big-endian bit length — an injective fixed-width encoding;
- fixed-width columns: the 64-bit bit pattern, with -0.0 normalized to
  +0.0 and NaNs to the canonical quiet NaN first (value semantics, not
  representation semantics, for floats);
- NULL values hash to a per-column constant (validity is part of the
  fingerprint);
- each column is seeded by crc32(name) so column swaps change the
  fingerprint even between same-typed columns.

Dict-native reduction (ARCHITECTURE.md "Dict-native reductions"): a
dictionary-encoded column's per-row polynomial accumulator depends only
on the row's own bytes — zero padding contributes nothing and the
column seed mixes in AFTER the accumulator — so the accumulators are
computed once per POOL ENTRY (O(pool bytes), memoized on the shared
DictPool) and per-row lanes are an O(n_rows) int32 gather of
accumulators by code.  Work drops from O(total row bytes) to
O(pool bytes + n_rows), the digests stay byte-identical to the flat
path (pinned by tests), and the column never flattens.
"""

from __future__ import annotations

import functools
import zlib
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from transferia_tpu.columnar.batch import ColumnBatch

M32 = np.uint32(0xFFFFFFFF)
# lane polynomial bases (odd => invertible mod 2^32) and null sentinels
_P1 = np.uint32(0x01000193)   # FNV-1a prime
_P2 = np.uint32(0x8DA6B343)
_NULL1 = np.uint32(0xA5A5A5A5)
_NULL2 = np.uint32(0x5A5A5A5A)


def _mix32_np(x: np.ndarray) -> np.ndarray:
    """xorshift-multiply avalanche (lowbias32); exact u32 wraparound."""
    x = x.astype(np.uint32, copy=True)
    x ^= x >> np.uint32(16)
    x *= np.uint32(0x7FEB352D)
    x ^= x >> np.uint32(15)
    x *= np.uint32(0x846CA68B)
    x ^= x >> np.uint32(16)
    return x


def _col_seed(name: str, lane: int) -> np.uint32:
    crc = zlib.crc32(name.encode("utf-8", errors="surrogatepass"))
    return np.uint32((crc + 0x9E3779B9 * (lane + 1)) & 0xFFFFFFFF)


@functools.lru_cache(maxsize=64)
def _powers(width: int, base: int) -> np.ndarray:
    """P^j mod 2^32 table; cached per (width, base) — do not mutate."""
    out = np.empty(width, dtype=np.uint32)
    acc = 1
    for j in range(width):
        out[j] = acc
        acc = (acc * base) & 0xFFFFFFFF
    out.setflags(write=False)
    return out


@dataclass
class FingerprintAggregate:
    """Mergeable order-independent table digest."""

    sum1: int = 0
    sum2: int = 0
    xor1: int = 0
    xor2: int = 0
    count: int = 0

    def merge(self, other: "FingerprintAggregate") -> None:
        self.sum1 = (self.sum1 + other.sum1) & 0xFFFFFFFF
        self.sum2 = (self.sum2 + other.sum2) & 0xFFFFFFFF
        self.xor1 ^= other.xor1
        self.xor2 ^= other.xor2
        self.count += other.count

    def digest(self) -> str:
        return (f"{self.sum1:08x}{self.sum2:08x}"
                f"{self.xor1:08x}{self.xor2:08x}:{self.count}")

    @classmethod
    def parse(cls, digest: str) -> "FingerprintAggregate":
        """Inverse of digest() — lets per-part digests stored as strings
        (coordinator part records) merge at read time."""
        hexes, _, count = digest.partition(":")
        if len(hexes) != 32 or not count:
            raise ValueError(f"malformed fingerprint digest: {digest!r}")
        return cls(
            sum1=int(hexes[0:8], 16), sum2=int(hexes[8:16], 16),
            xor1=int(hexes[16:24], 16), xor2=int(hexes[24:32], 16),
            count=int(count),
        )

    def __eq__(self, other) -> bool:
        if not isinstance(other, FingerprintAggregate):
            return NotImplemented
        return self.digest() == other.digest()


@dataclass
class _PreppedColumn:
    """Backend-neutral canonical form of one column for one batch.

    Var-width columns keep their (data, offsets) — the host backend
    hashes them in place (native polyhash_varcol never materializes the
    padded matrix); the device backend packs lazily via ensure_blocks().
    Dict-encoded columns keep their int32 codes plus the POOL's per-entry
    accumulators (memoized on the shared DictPool): both backends gather
    accumulators by code instead of touching row bytes.
    """

    name: str
    kind: str                      # "fixed" | "var" | "dict"
    lo: Optional[np.ndarray] = None     # fixed: (N,) u32
    hi: Optional[np.ndarray] = None     # fixed: (N,) u32
    data: Optional[np.ndarray] = None    # var: flat u8
    offsets: Optional[np.ndarray] = None  # var: (N+1,) i32
    blocks: Optional[np.ndarray] = None  # var: (N, W) u8 (lazy)
    width: int = 0
    validity: Optional[np.ndarray] = None
    codes: Optional[np.ndarray] = None   # dict: (N,) i32
    acc1: Optional[np.ndarray] = None    # dict: (n_values,) u32
    acc2: Optional[np.ndarray] = None    # dict: (n_values,) u32

    def ensure_blocks(self) -> np.ndarray:
        if self.blocks is None:
            self.blocks = _pack_var(self.data, self.offsets, self.width)
        return self.blocks


def _pow2_width(max_len: int) -> int:
    """Padded row width for var-width data (>= len + 9, pow2 of 64s)."""
    nb = (max_len + 9 + 63) // 64
    nb = 1 << (nb - 1).bit_length() if nb > 1 else 1
    return nb * 64


def _pack_var(data: np.ndarray, offsets: np.ndarray,
              width: int) -> np.ndarray:
    n = len(offsets) - 1
    from transferia_tpu.native import lib as native_lib

    cdll = native_lib()
    out = np.empty((n, width), dtype=np.uint8)
    nb = np.empty(n, dtype=np.int32)
    if cdll is not None and n:
        cdll.pack_sha_blocks(
            np.ascontiguousarray(data),
            np.ascontiguousarray(offsets, dtype=np.int32),
            n, width, 0, out, nb,
        )
        return out
    # numpy fallback: same layout as the C++ packer
    out[:] = 0
    for i in range(n):
        row = data[offsets[i]:offsets[i + 1]]
        ln = len(row)
        out[i, :ln] = row
        out[i, ln] = 0x80
        blocks = (ln + 9 + 63) // 64
        bits = ln * 8
        out[i, blocks * 64 - 8:blocks * 64] = np.frombuffer(
            int(bits).to_bytes(8, "big"), dtype=np.uint8)
    return out


# per-pool accumulator memo key: the accumulators depend only on the
# pool BYTES (the column seed mixes in after the gather), so one pair
# serves every column, batch, and HMAC key sharing the pool
_ACC_MEMO_KEY = ("rowhash_accs",)


def pool_accumulators(pool) -> tuple[np.ndarray, np.ndarray]:
    """Both lanes' polynomial accumulators, one per pool ENTRY.

    Identical to what `_var_accs_host` computes for a flat row carrying
    the same bytes (zero padding of the canonical block matrix
    contributes nothing to the sum, and the power-table indices a row
    touches depend only on its own length — never on the batch-wide
    padded width).  Memoized on the shared DictPool: batches slicing one
    row group's dictionary hash its values exactly once."""
    memo = pool.memo_get(_ACC_MEMO_KEY)
    if memo is not None:
        return memo
    from transferia_tpu.chaos.failpoints import failpoint
    from transferia_tpu.stats import trace

    failpoint("rowhash.pool_accs")
    # once per shared pool: worth a point event (a chaos fire at the
    # `rowhash.pool_accs` site lands next to it on the active span)
    trace.instant("rowhash_pool_accs", values=pool.n_values)
    n_vals = pool.n_values
    offs = np.ascontiguousarray(pool.values_offsets, dtype=np.int32)
    lens = offs[1:] - offs[:-1]
    width = _pow2_width(int(lens.max()) if n_vals else 0)
    tmp = _PreppedColumn(
        name="", kind="var",
        data=np.ascontiguousarray(pool.values_data),
        offsets=offs, width=width)
    accs = _var_accs_host(tmp, n_vals)
    pool.memo_set(_ACC_MEMO_KEY, accs)
    return accs


def prep_batch(batch: ColumnBatch) -> tuple[list[_PreppedColumn], int]:
    """Canonicalize a batch for either fingerprint backend."""
    from transferia_tpu.stats.trace import TELEMETRY

    cols: list[_PreppedColumn] = []
    for name in batch.schema.names():
        col = batch.column(name)
        if col.is_lazy_dict:
            # dict-native: never touch col.data/col.offsets (that would
            # flatten the pool per row); hash the pool once, gather by
            # code.  Null rows are overridden by the validity constant
            # in _col_lanes_host exactly as on the flat path, so the
            # sentinel entry's accumulator (empty bytes) is only ever a
            # don't-care placeholder there.
            pool = col.dict_enc.pool
            codes = np.ascontiguousarray(col.dict_enc.indices,
                                         dtype=np.int32)
            if len(codes):
                # both backends gather UNCHECKED (native loop / device
                # clip): a corrupt code must raise here, not hash
                # stray memory into a plausible-looking digest
                cmin, cmax = int(codes.min()), int(codes.max())
                if cmin < 0 or cmax >= pool.n_values:
                    raise IndexError(
                        f"column {name}: dict codes [{cmin}, {cmax}] "
                        f"out of range for pool of {pool.n_values} "
                        f"values")
            a1, a2 = pool_accumulators(pool)
            TELEMETRY.record_dict_preserved()
            cols.append(_PreppedColumn(
                name=name, kind="dict",
                codes=codes, acc1=a1, acc2=a2, validity=col.validity))
            continue
        if col.offsets is not None:
            lens = col.offsets[1:] - col.offsets[:-1]
            width = _pow2_width(int(lens.max()) if batch.n_rows else 0)
            cols.append(_PreppedColumn(
                name=name, kind="var",
                data=np.ascontiguousarray(col.data),
                offsets=np.ascontiguousarray(col.offsets,
                                             dtype=np.int32),
                width=width, validity=col.validity))
            continue
        data = col.data
        if data.dtype.kind == "f":
            data = data.astype(np.float64, copy=True)
            data[data == 0.0] = 0.0          # -0.0 -> +0.0
            data[np.isnan(data)] = np.nan    # canonical quiet NaN
            bits = data.view(np.uint64)
        elif data.dtype.kind == "b":
            bits = data.astype(np.uint64)
        else:
            bits = data.astype(np.int64, copy=False).view(np.uint64)
        cols.append(_PreppedColumn(
            name=name, kind="fixed",
            lo=(bits & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            hi=(bits >> np.uint64(32)).astype(np.uint32),
            validity=col.validity))
    return cols, batch.n_rows


def _var_accs_host(col: _PreppedColumn,
                   n_rows: int) -> tuple[np.ndarray, np.ndarray]:
    """Both lanes' polynomial accumulators for a var-width column.

    Native path: one C++ pass over the real bytes (zero padding of the
    canonical block layout contributes nothing to the sum, so it is
    never materialized).  Numpy fallback hashes the packed matrix — the
    identical value, pinned by tests.
    """
    from transferia_tpu.native import lib as native_lib

    cdll = native_lib()
    if cdll is not None and n_rows:
        pw1 = _powers(col.width, int(_P1))
        pw2 = _powers(col.width, int(_P2))
        a1 = np.empty(n_rows, dtype=np.uint32)
        a2 = np.empty(n_rows, dtype=np.uint32)
        cdll.polyhash_varcol(col.data, col.offsets, n_rows, pw1, pw2,
                             a1, a2)
        return a1, a2
    blocks = col.ensure_blocks().astype(np.uint32)
    a1 = (blocks * _powers(col.width, int(_P1))[None, :]).sum(
        axis=1, dtype=np.uint32)
    a2 = (blocks * _powers(col.width, int(_P2))[None, :]).sum(
        axis=1, dtype=np.uint32)
    return a1, a2


def _lanes_lib():
    """The native lib iff it carries the fused lane kernels (a prebuilt
    .so from an older source keeps the numpy chain)."""
    from transferia_tpu.native import lib as native_lib

    cdll = native_lib()
    if cdll is not None and hasattr(cdll, "rowhash_mix_fixed"):
        return cdll
    return None


def _col_lanes_host(col: _PreppedColumn, n_rows: int
                    ) -> tuple[np.ndarray, np.ndarray]:
    seed1, seed2 = _col_seed(col.name, 0), _col_seed(col.name, 1)
    cdll = _lanes_lib() if n_rows else None
    if cdll is not None:
        # fused C++ lane chains: one pass per column instead of the
        # ~30 numpy temporaries the mix cascade walks (byte-identical;
        # pinned by the fallback-parity tests)
        h1 = np.empty(n_rows, dtype=np.uint32)
        h2 = np.empty(n_rows, dtype=np.uint32)
        s1, s2 = int(seed1), int(seed2)
        if col.kind == "fixed":
            cdll.rowhash_mix_fixed(
                np.ascontiguousarray(col.lo),
                np.ascontiguousarray(col.hi), n_rows, s1, s2, h1, h2)
        elif col.kind == "dict":
            cdll.rowhash_dict_lanes(
                np.ascontiguousarray(col.acc1),
                np.ascontiguousarray(col.acc2),
                col.codes, n_rows, s1, s2, h1, h2)
        else:
            a1, a2 = _var_accs_host(col, n_rows)
            cdll.rowhash_mix_var(
                np.ascontiguousarray(a1), np.ascontiguousarray(a2),
                n_rows, s1, s2, h1, h2)
    elif col.kind == "fixed":
        out = []
        for seed in (seed1, seed2):
            h = _mix32_np(col.lo ^ seed)
            h = _mix32_np(h + _mix32_np(
                col.hi ^ np.uint32(~int(seed) & 0xFFFFFFFF)))
            out.append(h)
        h1, h2 = out
    elif col.kind == "dict":
        # O(n_rows) gather of the memoized pool accumulators by code —
        # byte-identical to hashing the materialized rows because the
        # accumulator of a row IS the accumulator of its pool entry
        from transferia_tpu.columnar.batch import _gather_fixed

        h1 = _mix32_np(_gather_fixed(col.acc1, col.codes) ^ seed1)
        h2 = _mix32_np(_gather_fixed(col.acc2, col.codes) ^ seed2)
    else:
        a1, a2 = _var_accs_host(col, n_rows)
        h1 = _mix32_np(a1 ^ seed1)
        h2 = _mix32_np(a2 ^ seed2)
    if col.validity is not None:
        h1 = np.where(col.validity, h1, _NULL1 ^ seed1)
        h2 = np.where(col.validity, h2, _NULL2 ^ seed2)
    return h1, h2


def row_lanes(cols: Sequence[_PreppedColumn],
              n_rows: int) -> tuple[np.ndarray, np.ndarray]:
    """Finalized per-row lane values (u32 each) — the pre-reduction state
    of `fingerprint_host`.  `(r1[i] << 32) | r2[i]` is a 64-bit content
    key for row i under the same canonicalization as the table
    fingerprint; the chaos delivery auditor (chaos/invariants.py) uses
    it to count per-row delivery multiplicities where the aggregate
    alone can only witness set equality."""
    r1 = np.zeros(n_rows, dtype=np.uint32)
    r2 = np.zeros(n_rows, dtype=np.uint32)
    cdll = _lanes_lib() if n_rows else None
    for col in cols:
        h1, h2 = _col_lanes_host(col, n_rows)
        if cdll is not None:
            cdll.rowhash_accum(np.ascontiguousarray(h1),
                               np.ascontiguousarray(h2), n_rows, r1, r2)
        else:
            r1 += _mix32_np(h1)
            r2 += _mix32_np(h2)
    return _mix32_np(r1), _mix32_np(r2)


def fingerprint_host(cols: Sequence[_PreppedColumn],
                     n_rows: int) -> FingerprintAggregate:
    """Host backend (exact twin of the device program)."""
    r1, r2 = row_lanes(cols, n_rows)
    return FingerprintAggregate(
        sum1=int(r1.sum(dtype=np.uint64) & 0xFFFFFFFF),
        sum2=int(r2.sum(dtype=np.uint64) & 0xFFFFFFFF),
        xor1=int(np.bitwise_xor.reduce(r1)) if n_rows else 0,
        xor2=int(np.bitwise_xor.reduce(r2)) if n_rows else 0,
        count=n_rows,
    )


def _device_keys_requested(environ=None) -> bool:
    """TRANSFERIA_TPU_DEDUP_KEYS=device routes dedup-window keys
    through the device program (profitable exactly when the part's
    batches already ride the device — fused chains, device
    fingerprinting — so codes/blocks are hot and the link cost is
    amortized by the reduction plane)."""
    from transferia_tpu.runtime import knobs

    return knobs.env_str("TRANSFERIA_TPU_DEDUP_KEYS", "",
                         environ=environ).lower() == "device"


def _batch_device_resident(batch: ColumnBatch) -> bool:
    """True when the batch's column buffers are already device arrays
    (a jax.Array survived a device-side transform): keys then compute
    where the data lives instead of gathering host-side."""
    try:
        cols = batch.columns.values()
    except AttributeError:
        return False
    for c in cols:
        data = getattr(c, "_data", None)
        # jax arrays report module "jaxlib.xla_extension" (ArrayImpl),
        # tracer types "jax...." — accept either root package
        if data is not None and \
                type(data).__module__.split(".")[0] in ("jax", "jaxlib"):
            return True
    return False


def batch_row_keys(batch: ColumnBatch, backend: str = "auto"
                   ) -> np.ndarray:
    """64-bit content key per row: `(r1 << 32) | r2` of the finalized
    lanes, under the same canonicalization as the table fingerprint.
    Dict columns key code-natively (pool-accumulator gather, no flat
    materialization).  Shared by the chaos delivery auditor (row
    delivery multiplicities) and the staged-commit dedup window
    (providers/staging.py: replayed torn-write prefixes are dropped
    before publish by these keys).

    `backend="device"` (or `auto` with TRANSFERIA_TPU_DEDUP_KEYS=device
    / a device-resident batch) computes the lanes on device through the
    fingerprint plane's kernel family — byte-identical to the host
    path (pinned by tests/unit/test_dict_reduction.py)."""
    if batch.n_rows == 0:
        return np.empty(0, dtype=np.uint64)
    if backend == "auto" and (_device_keys_requested()
                              or _batch_device_resident(batch)):
        backend = "device"
    if backend == "device":
        try:
            return batch_row_keys_device(batch)
        except ImportError:
            pass  # no jax: the host path is always correct
    cols, n = prep_batch(batch)
    r1, r2 = row_lanes(cols, n)
    return (r1.astype(np.uint64) << np.uint64(32)) | r2.astype(np.uint64)


# per-signature jitted row-keys programs (module-global like the
# fingerprint cache: identical schemas re-trace once per process)
_keys_jit_cache: dict = {}


def batch_row_keys_device(batch: ColumnBatch) -> np.ndarray:
    """Device twin of the host key path: the SAME traced lane body as
    DeviceFingerprintProgram (`_device_row_lanes` — one shared
    implementation, so the two entry points cannot drift) but
    returning the finalized per-row lanes instead of their reduction —
    one launch, two u32 vectors D2H, keys assembled host-side
    (padding rows trimmed)."""
    import jax

    cols, n_rows = prep_batch(batch)
    sig = tuple(
        (c.kind, c.width if c.kind == "var" else 0) for c in cols)

    fn = _keys_jit_cache.get(sig)
    if fn is None:
        sig_kinds = [k for k, _ in sig]

        def program(fixed_lo, fixed_hi, var_blocks, dict_codes,
                    dict_accs1, dict_accs2, validities,
                    seeds1, seeds2, nulls1, nulls2, powers1, powers2,
                    n):
            return _device_row_lanes(
                sig_kinds, fixed_lo, fixed_hi, var_blocks, dict_codes,
                dict_accs1, dict_accs2, validities, seeds1, seeds2,
                nulls1, nulls2, powers1, powers2, n)

        fn = jax.jit(program, static_argnames=("n",))
        _keys_jit_cache[sig] = fn

    args, bucket = _pack_device_lane_args(cols, n_rows)
    r1, r2 = fn(*args, bucket)
    r1 = np.asarray(r1)[:n_rows]
    r2 = np.asarray(r2)[:n_rows]
    return (r1.astype(np.uint64) << np.uint64(32)) | r2.astype(np.uint64)


def _device_row_lanes(sig_kinds, fixed_lo, fixed_hi, var_blocks,
                      dict_codes, dict_accs1, dict_accs2, validities,
                      seeds1, seeds2, nulls1, nulls2, powers1, powers2,
                      n):
    """Traced per-row lane body shared by the fingerprint reduction
    program and the dedup-key program — ONE implementation of the
    device lane math (mix chains, var-width polynomial blocks, dict
    accumulator gathers, null constants), so the two entry points
    cannot drift apart.  Returns the finalized (r1, r2) u32 vectors."""
    import jax.numpy as jnp

    def mix(x):
        x = x ^ (x >> jnp.uint32(16))
        x = x * jnp.uint32(0x7FEB352D)
        x = x ^ (x >> jnp.uint32(15))
        x = x * jnp.uint32(0x846CA68B)
        return x ^ (x >> jnp.uint32(16))

    r1 = jnp.zeros(n, dtype=jnp.uint32)
    r2 = jnp.zeros(n, dtype=jnp.uint32)
    fi = vi = di = 0
    for idx, kind in enumerate(sig_kinds):
        for lane in (0, 1):
            seed = (seeds1 if lane == 0 else seeds2)[idx]
            null = (nulls1 if lane == 0 else nulls2)[idx]
            if kind == "fixed":
                lo, hi = fixed_lo[fi], fixed_hi[fi]
                h = mix(lo ^ seed)
                h = mix(h + mix(hi ^ (~seed)))
            elif kind == "dict":
                # codes + per-pool-entry accumulators crossed the
                # link (4 + 4·k/n bytes/row, not the padded block
                # matrix); the reduction consumes codes directly via
                # an HBM-speed gather
                from transferia_tpu.ops.decode import (
                    gather_pool_accumulators,
                )

                acc = (dict_accs1 if lane == 0 else dict_accs2)[di]
                h = mix(gather_pool_accumulators(
                    acc, dict_codes[di]) ^ seed)
            else:
                pw = (powers1 if lane == 0 else powers2)[vi]
                b = var_blocks[vi].astype(jnp.uint32)
                h = mix((b * pw[None, :]).sum(
                    axis=1, dtype=jnp.uint32) ^ seed)
            v = validities[idx]
            if v is not None:
                h = jnp.where(v, h, null ^ seed)
            if lane == 0:
                r1 = r1 + mix(h)
            else:
                r2 = r2 + mix(h)
        if kind == "fixed":
            fi += 1
        elif kind == "dict":
            di += 1
        else:
            vi += 1
    return mix(r1), mix(r2)


def _pack_device_lane_args(cols: Sequence[_PreppedColumn],
                           n_rows: int):
    """Host-side argument packing shared by both device entry points:
    bucket-padded column arrays + per-column seed/null/power vectors.
    Returns (args tuple in _device_row_lanes order minus n, bucket)."""
    import jax.numpy as jnp

    from transferia_tpu.columnar.batch import bucket_rows

    bucket = bucket_rows(n_rows)
    fixed_lo, fixed_hi, var_blocks, validities = [], [], [], []
    dict_codes, dict_accs1, dict_accs2 = [], [], []
    seeds1, seeds2, nulls1, nulls2 = [], [], [], []
    powers1, powers2 = [], []
    pad = bucket - n_rows

    def padded(a, fill=0):
        if pad:
            return np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1),
                          constant_values=fill)
        return a

    for c in cols:
        seeds1.append(_col_seed(c.name, 0))
        seeds2.append(_col_seed(c.name, 1))
        nulls1.append(_NULL1)
        nulls2.append(_NULL2)
        if c.kind == "fixed":
            fixed_lo.append(jnp.asarray(padded(c.lo)))
            fixed_hi.append(jnp.asarray(padded(c.hi)))
        elif c.kind == "dict":
            # accumulators pad to a row bucket too, so pool-size
            # jitter re-traces per bucket, not per distinct pool; pad
            # codes index entry 0 (their lanes are masked or trimmed)
            dict_codes.append(jnp.asarray(padded(c.codes)))
            ab = bucket_rows(max(len(c.acc1), 1))
            apad = ab - len(c.acc1)

            def padded_acc(a):
                return np.pad(a, (0, apad)) if apad else a

            dict_accs1.append(jnp.asarray(padded_acc(c.acc1)))
            dict_accs2.append(jnp.asarray(padded_acc(c.acc2)))
        else:
            var_blocks.append(jnp.asarray(padded(c.ensure_blocks())))
            powers1.append(jnp.asarray(_powers(c.width, int(_P1))))
            powers2.append(jnp.asarray(_powers(c.width, int(_P2))))
        validities.append(
            jnp.asarray(padded(c.validity))
            if c.validity is not None else None)
    args = (tuple(fixed_lo), tuple(fixed_hi), tuple(var_blocks),
            tuple(dict_codes), tuple(dict_accs1), tuple(dict_accs2),
            tuple(validities),
            jnp.asarray(np.array(seeds1, dtype=np.uint32)),
            jnp.asarray(np.array(seeds2, dtype=np.uint32)),
            jnp.asarray(np.array(nulls1, dtype=np.uint32)),
            jnp.asarray(np.array(nulls2, dtype=np.uint32)),
            tuple(powers1), tuple(powers2))
    return args, bucket


class DeviceFingerprintProgram:
    """Jitted device twin of fingerprint_host.

    One launch per (buffered) batch run: H2D moves the canonical columns,
    the reduction happens on device, and 5 scalars come back — the
    profitable shape for high-latency links (ops/linkprobe.py).  Launches
    are dispatched asynchronously; collect() blocks once at the end.
    """

    # compiled programs keyed by column signature — module-global so a
    # fresh instance (one per table scan) reuses prior compilations
    # instead of re-tracing identical schemas
    _jit_cache: dict = {}

    def __init__(self):
        import jax  # presence check at construction, not first dispatch

        self._pending: list = []

    def _program_for(self, sig: tuple):
        fn = self._jit_cache.get(sig)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        def program(fixed_lo, fixed_hi, var_blocks, dict_codes,
                    dict_accs1, dict_accs2, validities, rowmask,
                    seeds1, seeds2, nulls1, nulls2, powers1, powers2):
            r1, r2 = _device_row_lanes(
                sig_kinds, fixed_lo, fixed_hi, var_blocks, dict_codes,
                dict_accs1, dict_accs2, validities, seeds1, seeds2,
                nulls1, nulls2, powers1, powers2, rowmask.shape[0])
            r1 = jnp.where(rowmask, r1, 0)
            r2 = jnp.where(rowmask, r2, 0)
            return (r1.sum(dtype=jnp.uint32), r2.sum(dtype=jnp.uint32),
                    jnp.bitwise_xor.reduce(r1), jnp.bitwise_xor.reduce(r2),
                    rowmask.sum(dtype=jnp.int32))

        sig_kinds = [k for k, _ in sig]
        fn = jax.jit(program)
        DeviceFingerprintProgram._jit_cache[sig] = fn
        return fn

    def dispatch(self, cols: Sequence[_PreppedColumn],
                 n_rows: int) -> None:
        """Async-launch one batch; result lands in collect()."""
        import jax.numpy as jnp

        sig = tuple(
            (c.kind, c.width if c.kind == "var" else 0) for c in cols)
        args, bucket = _pack_device_lane_args(cols, n_rows)
        rowmask = np.zeros(bucket, dtype=np.bool_)
        rowmask[:n_rows] = True
        fn = self._program_for(sig)
        (fixed_lo, fixed_hi, var_blocks, dict_codes, dict_accs1,
         dict_accs2, validities, seeds1, seeds2, nulls1, nulls2,
         powers1, powers2) = args
        out = fn(fixed_lo, fixed_hi, var_blocks, dict_codes,
                 dict_accs1, dict_accs2, validities,
                 jnp.asarray(rowmask), seeds1, seeds2, nulls1, nulls2,
                 powers1, powers2)
        self._pending.append(out)

    def collect(self) -> FingerprintAggregate:
        """Block on every dispatched launch and merge the partials."""
        agg = FingerprintAggregate()
        for out in self._pending:
            s1, s2, x1, x2, cnt = (np.asarray(o) for o in out)
            agg.merge(FingerprintAggregate(
                sum1=int(s1), sum2=int(s2), xor1=int(x1), xor2=int(x2),
                count=int(cnt)))
        self._pending.clear()
        return agg


class TableFingerprinter:
    """Streaming fingerprint over batches, backend chosen by measurement.

    backend="auto" times the host path on the first batch and predicts
    the device path from the link profile (same gating idea as
    transform/fused.py): reduction output is tiny, so the device is
    profitable whenever H2D keeps up and batches amortize the launch.
    """

    # re-evaluate the backend decision periodically: links drift, and a
    # decision pinned off one skewed sample would fix a bad backend for
    # a whole table scan (same policy as DeviceFusedStep.REPROBE_EVERY)
    REPROBE_EVERY = 256

    def __init__(self, backend: str = "auto"):
        self.backend = backend
        self._agg = FingerprintAggregate()
        self._device: Optional[DeviceFingerprintProgram] = None
        self._host_ns_row = -1.0
        self._host_samples = 0
        self._batch_no = 0
        self._decided: Optional[str] = None

    def _accel_available(self) -> bool:
        """A device backend only pays when it is a real accelerator —
        jax-on-CPU shares the host cores and adds jit overhead."""
        try:
            import jax

            return jax.default_backend() not in ("cpu",)
        except Exception:
            return False

    def _choose(self, n_rows: int, row_bytes: int) -> str:
        if self.backend in ("host", "device"):
            return self.backend
        if (self._decided is not None
                and self._batch_no % self.REPROBE_EVERY != 0):
            return self._decided
        # need >=2 host samples: the first carries one-off warmup (native
        # lib build, cold caches) and is never recorded
        if self._host_samples < 2 or not self._accel_available():
            return "host"
        from transferia_tpu.ops.linkprobe import probe_link

        link = probe_link()
        pred_s = (2 * link.launch_overhead_s
                  + n_rows * row_bytes / link.h2d_bytes_per_s
                  + n_rows / 20e6)
        pred_ns = pred_s * 1e9 / max(n_rows, 1)
        self._decided = ("device" if pred_ns < self._host_ns_row
                         else "host")
        return self._decided

    def push(self, batch: ColumnBatch) -> None:
        if batch.n_rows == 0:
            return
        import time as _time

        cols, n = prep_batch(batch)
        row_bytes = sum(
            (c.width if c.kind == "var" else 8) for c in cols)
        self._batch_no += 1
        choice = self._choose(n, row_bytes)
        if choice == "device":
            if self._device is None:
                self._device = DeviceFingerprintProgram()
            self._device.dispatch(cols, n)
            return
        t0 = _time.perf_counter()
        self._agg.merge(fingerprint_host(cols, n))
        ns = (_time.perf_counter() - t0) * 1e9 / n
        self._host_samples += 1
        if self._host_samples == 1:
            return  # warmup-contaminated: measure, don't record
        self._host_ns_row = (ns if self._host_ns_row < 0
                             else 0.7 * self._host_ns_row + 0.3 * ns)

    def result(self) -> FingerprintAggregate:
        if self._device is not None:
            self._agg.merge(self._device.collect())
        return self._agg
