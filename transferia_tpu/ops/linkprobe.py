"""Host↔device link profiling for cost-based op placement.

A TPU data plane's profitability depends on the link as much as the chip:
the same fused mask+filter program that wins on a PCIe-attached v5e loses
badly through a high-latency tunnel (a dev-environment TPU proxied over
the network measures ~70ms per launch and ~10-30 MB/s D2H against
~1 GB/s H2D).  The reference has no analogue — its CUDA path assumes a
local PCIe GPU — but a framework that may run against remote/tunneled
accelerators must measure instead of assume.

probe_link() measures, once per process:
  - launch_overhead_s: wall time of a tiny jitted round trip (median of 3)
  - h2d_bytes_per_s:   device_put of a 4 MiB array
  - d2h_bytes_per_s:   np.asarray of a freshly computed 4 MiB device array
    (a fresh array defeats jax's host-side copy cache)

The result feeds transform/fused.py's placement auto-tuner and
ops/fused.py's chunk sizing.  TRANSFERIA_TPU_LINK="rtt_ms,h2d_mbs,d2h_mbs"
overrides the measurement (tests pin placement decisions with it); on the
CPU backend the "link" is in-process and a constant ideal profile is
returned without measuring.

`interchange/streams.py` prices the worker↔worker WIRE the same way
this module prices the host↔device link: one probe per process, an env
pin (`TRANSFERIA_TPU_STREAM_LINK`), and the identical degraded-profile
re-probe contract (`TRANSFERIA_TPU_STREAM_REPROBE`, mirroring
`TRANSFERIA_TPU_LINK_REPROBE` below) — keep the two contracts in sync
when either changes.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from transferia_tpu.runtime import knobs


@dataclass(frozen=True)
class LinkProfile:
    backend: str
    launch_overhead_s: float
    h2d_bytes_per_s: float
    d2h_bytes_per_s: float
    measured: bool  # False for env-pinned / in-process constants
    degraded: bool = False  # wedged-runtime fallback: re-probed later

    def describe(self) -> str:
        suffix = ""
        if self.degraded:
            suffix = " (degraded)"
        elif not self.measured:
            suffix = " (pinned)"
        return (
            f"backend={self.backend} launch={self.launch_overhead_s * 1e3:.1f}ms "
            f"h2d={self.h2d_bytes_per_s / 1e6:.0f}MB/s "
            f"d2h={self.d2h_bytes_per_s / 1e6:.0f}MB/s"
            f"{suffix}"
        )


_lock = threading.Lock()
_cached: Optional[LinkProfile] = None

# In-process backends (cpu) move "transfers" at memcpy speed and launch in
# tens of microseconds; measuring would only add test latency.
_INPROCESS = dict(launch_overhead_s=100e-6,
                  h2d_bytes_per_s=8e9, d2h_bytes_per_s=8e9)

_PROBE_BYTES = 4 << 20


def _parse_env(backend: str) -> Optional[LinkProfile]:
    env = knobs.env_raw("TRANSFERIA_TPU_LINK")
    if not env:
        return None
    try:
        rtt_ms, h2d_mbs, d2h_mbs = (float(x) for x in env.split(","))
    except ValueError:
        return None
    # clamp: zero/negative bandwidths would divide-by-zero in the cost
    # model; a pinned "dead link" still has to be a number
    return LinkProfile(backend=backend,
                       launch_overhead_s=max(rtt_ms, 0.0) / 1e3,
                       h2d_bytes_per_s=max(h2d_mbs, 1e-3) * 1e6,
                       d2h_bytes_per_s=max(d2h_mbs, 1e-3) * 1e6,
                       measured=False)


def _measure(backend: str) -> LinkProfile:
    import jax

    # launch overhead: tiny jitted op, enqueue + sync
    tiny = jax.jit(lambda x: x + 1)
    x = jax.device_put(np.zeros(8, np.float32))
    tiny(x).block_until_ready()  # compile outside the timed window
    rtts = []
    for _ in range(3):
        t0 = time.perf_counter()
        tiny(x).block_until_ready()
        rtts.append(time.perf_counter() - t0)
    launch = sorted(rtts)[1]

    buf = np.zeros(_PROBE_BYTES, dtype=np.uint8)
    t0 = time.perf_counter()
    dev = jax.device_put(buf)
    dev.block_until_ready()
    h2d_s = time.perf_counter() - t0

    # a derived array defeats the host-copy cache; subtract the launch
    # overhead so the figure is marginal bandwidth, not latency
    bump = jax.jit(lambda a: a + 1)
    fresh = bump(dev)
    fresh.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(fresh)
    d2h_s = time.perf_counter() - t0
    d2h_s = max(d2h_s - launch, 1e-9)

    return LinkProfile(
        backend=backend,
        launch_overhead_s=launch,
        h2d_bytes_per_s=_PROBE_BYTES / max(h2d_s, 1e-9),
        d2h_bytes_per_s=_PROBE_BYTES / d2h_s,
        measured=True,
    )


# a wedged-runtime fallback profile re-measures after this many
# probe_link() reads (≈ batches) — a transiently dead runtime must not
# pin the worst-case link, and with it host placement, forever
_REPROBE_DEFAULT = 256
_degraded_reads = 0


def _reprobe_every() -> int:
    # 0 disables re-probing
    return max(0, knobs.env_int("TRANSFERIA_TPU_LINK_REPROBE",
                                _REPROBE_DEFAULT))


def probe_link(force: bool = False) -> LinkProfile:
    """The process-wide link profile (measured once, then cached).

    A profile born from the wedged-runtime fallback is DEGRADED: it
    re-measures after every TRANSFERIA_TPU_LINK_REPROBE reads (default
    256) so a runtime that was only transiently unreachable regains its
    real link model — and with it device placement eligibility —
    without a process restart."""
    global _cached, _degraded_reads
    if _cached is not None and not force:
        if not _cached.degraded:
            return _cached
        with _lock:
            cur = _cached
            if cur is not None:
                if cur.degraded:
                    _degraded_reads += 1
                    every = _reprobe_every()
                    if every and _degraded_reads >= every:
                        _degraded_reads = 0
                        try:
                            _cached = _measure(cur.backend)
                        except Exception:
                            # still wedged: keep the worst-case
                            # fallback and retry after another window
                            logging.getLogger(__name__).debug(
                                "link re-probe failed; runtime still "
                                "wedged", exc_info=True)
                return _cached
            # raced with reset_link_cache: fall through and re-detect
    with _lock:
        if _cached is not None and not force:
            return _cached
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "none"
        profile = _parse_env(backend)
        if profile is None:
            if backend in ("cpu", "none"):
                profile = LinkProfile(backend=backend, measured=False,
                                      **_INPROCESS)
            else:
                try:
                    profile = _measure(backend)
                except Exception:  # wedged runtime: assume worst-case link
                    profile = LinkProfile(
                        backend=backend, launch_overhead_s=0.1,
                        h2d_bytes_per_s=1e7, d2h_bytes_per_s=1e6,
                        measured=False, degraded=True)
        _cached = profile
        return profile


def reset_link_cache() -> None:
    global _cached, _degraded_reads
    with _lock:
        _cached = None
        _degraded_reads = 0
