"""Batched SHA-256 / HMAC-SHA256 on device (JAX).

The PII mask transformer's device backend (reference:
pkg/transformer/registry/mask/hmac_hasher.go does this per-row on CPU).
Here the whole column hashes in one XLA program: rows are padded to a
static max-block count and a lax.scan over message blocks updates each
row's hash state in parallel on the VPU (SHA-256 is pure uint32
arithmetic — rotations, xors, adds — which vectorizes over the row
dimension; there is no MXU work in this op).

Layout: messages arrive as a (N, max_blocks*64) uint8 matrix (padding
pre-applied, see prepare_padded_blocks) plus a per-row block count.  Rows
whose blocks are exhausted stop updating state via a select — the scan
length is the bucket's max blocks, keeping the compiled shape static.

Output parity with hashlib is pinned by tests (canon contract: the CPU and
TPU mask paths must produce byte-identical digests).
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

# jax is a hard dependency of the device kernels; host-only deployments use
# the hashlib path in transform/plugins/mask.py and never import this module
import jax
import jax.numpy as jnp

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5,
    0x3956c25b, 0x59f111f1, 0x923f82a4, 0xab1c5ed5,
    0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174,
    0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7,
    0xc6e00bf3, 0xd5a79147, 0x06ca6351, 0x14292967,
    0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3,
    0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5,
    0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f, 0x682e6ff3,
    0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress_batch(h, block_words):
    """One SHA-256 compression over a batch.

    h: (N, 8) uint32 states; block_words: (N, 16) uint32 big-endian words.
    Returns new (N, 8) states.  The rounds are lax loops with bounded
    unrolling — a fully unrolled 64-round body makes the XLA graph explode
    under vmap/shard_map (minutes of compile on CPU); fori_loop keeps the
    graph compact while unroll=8 still gives the VPU straight-line work.
    """
    # message schedule: w has shape (64, N)
    w = jnp.zeros((64,) + block_words.shape[:1], dtype=jnp.uint32)
    w = w.at[:16].set(jnp.transpose(block_words))

    def sched(i, w):
        x15 = w[i - 15]
        x2 = w[i - 2]
        s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> 3)
        s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> 10)
        return w.at[i].set(w[i - 16] + s0 + w[i - 7] + s1)

    w = jax.lax.fori_loop(16, 64, sched, w, unroll=8)
    k = jnp.asarray(_K)

    def round_fn(i, state):
        a, b, c, d, e, f, g, hh = state
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = hh + s1 + ch + k[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    state = tuple(h[:, i] for i in range(8))
    a, b, c, d, e, f, g, hh = jax.lax.fori_loop(
        0, 64, round_fn, state, unroll=8
    )
    return jnp.stack([
        h[:, 0] + a, h[:, 1] + b, h[:, 2] + c, h[:, 3] + d,
        h[:, 4] + e, h[:, 5] + f, h[:, 6] + g, h[:, 7] + hh,
    ], axis=1)


def _bytes_to_words(blocks_u8):
    """(N, n_blocks, 64) uint8 -> (N, n_blocks, 16) uint32 big-endian.

    Uses bitcast + byteswap instead of strided byte gathers: slicing the
    minor dim of a uint8 tensor fights the TPU's (32,128) tiling and is
    ~400x slower than a bitcast to u32 followed by elementwise swaps.
    """
    n, nb = blocks_u8.shape[0], blocks_u8.shape[1]
    u32 = jax.lax.bitcast_convert_type(
        blocks_u8.reshape(n, nb, 16, 4), jnp.uint32
    )
    if u32.ndim == 4:  # some backends keep a trailing singleton
        u32 = u32[..., 0]
    # little-endian load -> big-endian SHA word
    return (((u32 & 0xFF) << 24) | ((u32 & 0xFF00) << 8)
            | ((u32 >> 8) & 0xFF00) | (u32 >> 24))


@functools.partial(jax.jit, static_argnums=(2,))
def _sha256_padded(blocks_u8, n_blocks_per_row, max_blocks: int):
    """Hash pre-padded messages.

    blocks_u8: (N, max_blocks*64) uint8; n_blocks_per_row: (N,) int32.
    Returns (N, 8) uint32 digests.
    """
    n = blocks_u8.shape[0]
    words = _bytes_to_words(
        blocks_u8.reshape(n, max_blocks, 64)
    )  # (N, max_blocks, 16)
    h = jnp.broadcast_to(jnp.asarray(_H0), (n, 8))

    def step(h, inputs):
        block_words, idx = inputs
        new_h = _compress_batch(h, block_words)
        active = (idx < n_blocks_per_row)[:, None]
        return jnp.where(active, new_h, h), None

    h, _ = jax.lax.scan(
        step, h,
        (jnp.moveaxis(words, 1, 0), jnp.arange(max_blocks)),
    )
    return h


def prepare_padded_blocks(data: np.ndarray, offsets: np.ndarray,
                          prefix_len: int = 0,
                          max_blocks: Optional[int] = None,
                          ) -> tuple[np.ndarray, np.ndarray, int]:
    """Host-side: flat bytes+offsets -> padded SHA-256 block matrix.

    prefix_len: bytes of a (virtual) prefix already fed to the state — used
    by HMAC where the 64-byte ipad block is compressed separately; lengths
    in the padding must include it.  max_blocks: force the block bucket
    (callers sharing a compiled program across batches); None = derive.

    Returns (blocks (N, max_blocks*64) uint8, n_blocks (N,) int32,
    max_blocks).  Vectorized with numpy gathers — no per-row Python.
    """
    n = len(offsets) - 1
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    total_lens = lens + prefix_len
    # message + 0x80 + 8-byte length, rounded up to 64
    n_blocks = ((lens + 9 + 63) // 64).astype(np.int32)
    needed = int(n_blocks.max()) if n else 1
    if max_blocks is None:
        # bucket to powers of two so XLA compiles once per (rows, block
        # bucket), not once per batch-specific max length
        max_blocks = 1 << (needed - 1).bit_length() if needed > 1 else 1
    elif needed > max_blocks:
        raise ValueError(
            f"rows need {needed} SHA blocks > forced bucket {max_blocks}"
        )
    width = max_blocks * 64
    out = np.zeros((n, width), dtype=np.uint8)
    total = int(lens.sum())
    if total:
        # one flat scatter (no (N, W) index matrices): rows are contiguous
        # in the flat buffer, so source bytes in order are one slice; the
        # destination index of byte k of row i is i*width + k
        row_of = np.repeat(np.arange(n, dtype=np.int64), lens)
        cum = (offsets[:-1] - offsets[0]).astype(np.int64)
        intra = np.arange(total, dtype=np.int64) - np.repeat(cum, lens)
        out.reshape(-1)[row_of * width + intra] = \
            data[offsets[0]:offsets[0] + total]

    # 0x80 terminator
    rows = np.arange(n)
    out[rows, lens] = 0x80
    # 8-byte big-endian bit length at the end of the last block
    bit_lens = (total_lens * 8).astype(np.uint64)
    last = (n_blocks.astype(np.int64) * 64) - 8
    for k in range(8):
        out[rows, last + k] = ((bit_lens >> (8 * (7 - k))) & 0xFF
                               ).astype(np.uint8)
    return out, n_blocks, max_blocks


def sha256_batch(data: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """SHA-256 of each row in a flat bytes+offsets column.

    Returns (N, 32) uint8 digests.  Parity with hashlib pinned by tests.
    """
    blocks, n_blocks, max_blocks = prepare_padded_blocks(data, offsets)
    h = _sha256_padded(jnp.asarray(blocks), jnp.asarray(n_blocks),
                       max_blocks)
    return _words_to_bytes(np.asarray(h))


def _words_to_bytes(h: np.ndarray) -> np.ndarray:
    out = np.zeros((h.shape[0], 32), dtype=np.uint8)
    for i in range(8):
        out[:, 4 * i + 0] = (h[:, i] >> 24) & 0xFF
        out[:, 4 * i + 1] = (h[:, i] >> 16) & 0xFF
        out[:, 4 * i + 2] = (h[:, i] >> 8) & 0xFF
        out[:, 4 * i + 3] = h[:, i] & 0xFF
    return out


@functools.lru_cache(maxsize=64)
def _hmac_key_states(key: bytes) -> tuple[np.ndarray, np.ndarray]:
    """Precompute the per-key inner/outer states (one compression each)."""
    import hashlib

    if len(key) > 64:
        key = hashlib.sha256(key).digest()
    k = np.zeros(64, dtype=np.uint8)
    k[:len(key)] = np.frombuffer(key, dtype=np.uint8)
    ipad = (k ^ 0x36)[None, :]
    opad = (k ^ 0x5C)[None, :]

    @jax.jit
    def one_compress(block):
        # jitted even for this 1-row call: eager lax execution of the
        # compression degrades subsequent dispatch latency on some remote
        # TPU runtimes (observed ~0.03ms -> ~72ms per dispatch after one
        # eager run)
        words = _bytes_to_words(block.reshape(1, 1, 64))
        h = jnp.broadcast_to(jnp.asarray(_H0), (1, 8))
        return _compress_batch(h, words[:, 0])

    return (np.asarray(one_compress(jnp.asarray(ipad))),
            np.asarray(one_compress(jnp.asarray(opad))))


def hmac_device_core(blocks_u8, n_blocks_per_row, inner_state, outer_state,
                     max_blocks: int):
    """Pure-JAX HMAC core (composable inside larger jitted programs —
    the graft entry and the sharded transform step build on this)."""
    return _hmac_inner_outer_impl(
        blocks_u8, n_blocks_per_row, (inner_state, outer_state), max_blocks
    )


def _hmac_inner_outer_impl(blocks_u8, n_blocks_per_row, states,
                           max_blocks: int):
    inner_state, outer_state = states
    n = blocks_u8.shape[0]
    words = _bytes_to_words(blocks_u8.reshape(n, max_blocks, 64))
    h = jnp.broadcast_to(inner_state, (n, 8))

    def step(h, inputs):
        block_words, idx = inputs
        new_h = _compress_batch(h, block_words)
        active = (idx < n_blocks_per_row)[:, None]
        return jnp.where(active, new_h, h), None

    h, _ = jax.lax.scan(
        step, h, (jnp.moveaxis(words, 1, 0), jnp.arange(max_blocks))
    )
    # outer: H(K^opad || inner_digest); inner digest is 32 bytes -> 1 block.
    # Built by concat, not .at[].set — column scatters lower terribly on TPU.
    pad_words = np.zeros(8, dtype=np.uint32)
    pad_words[0] = 0x80000000
    pad_words[7] = (64 + 32) * 8
    outer_block = jnp.concatenate(
        [h, jnp.broadcast_to(jnp.asarray(pad_words), (n, 8))], axis=1
    )
    return _compress_batch(jnp.broadcast_to(outer_state, (n, 8)),
                           outer_block)


_hmac_inner_outer = functools.partial(
    jax.jit, static_argnums=(3,)
)(_hmac_inner_outer_impl)


def hmac_sha256_hex_batch(key: bytes, data: np.ndarray,
                          offsets: np.ndarray,
                          validity: Optional[np.ndarray] = None
                          ) -> tuple[np.ndarray, np.ndarray]:
    """Device backend for the mask transformer (mask.HashBackend signature).

    Returns (hex_data (uint8 flat), hex_offsets (int32)): 64-byte hex
    digests per valid row, empty for invalid rows.
    """
    n = len(offsets) - 1
    if n == 0:
        return np.zeros(0, dtype=np.uint8), np.zeros(1, dtype=np.int32)
    inner, outer = _hmac_key_states(key)
    blocks, n_blocks, max_blocks = prepare_padded_blocks(
        data, offsets, prefix_len=64
    )
    # bucket the row count so partial tail batches reuse the compiled
    # program (pad rows carry n_blocks=0 and never update state)
    from transferia_tpu.columnar.batch import bucket_rows

    bucket = bucket_rows(n)
    if bucket != n:
        blocks = np.pad(blocks, ((0, bucket - n), (0, 0)))
        n_blocks = np.pad(n_blocks, (0, bucket - n))
    h = _hmac_inner_outer(
        jnp.asarray(blocks), jnp.asarray(n_blocks),
        (jnp.asarray(inner), jnp.asarray(outer)), max_blocks,
    )
    from transferia_tpu.columnar.hexcol import (
        digests_to_hex,
        hex_to_varwidth,
    )

    hexes = digests_to_hex(np.asarray(h)[:n])  # (N, 64)

    return hex_to_varwidth(hexes, validity)


def enable_device_mask_backend() -> None:
    """Route MaskField hashing through the device kernel."""
    from transferia_tpu.transform.plugins.mask import set_hash_backend

    set_hash_backend(hmac_sha256_hex_batch)
