"""Fused device transform program: HMAC mask + row predicate in one launch.

Round-1 shape of the device path was one kernel per transformer (mask only)
with a host hop between steps.  This module compiles the whole device-able
run of a transformer plan — every HMAC-SHA256 masked column, hex encoding,
and the row-filter predicate — into ONE jitted XLA program per
(schema fingerprint, row bucket, width buckets), so a mask+filter transfer
does a single H2D/compute/D2H round-trip per batch.

Reference hot loops being displaced: pkg/transformer/transformation.go:22-70
(chain apply) and pkg/transformer/registry/mask/hmac_hasher.go +
registry/filter_rows (per-row Go).

Host side: rows pack into padded SHA block matrices via the C++ hostops
kernel (pack_sha_blocks — memcpy-bound, GIL-free) with a vectorized numpy
fallback; the device side is pure jnp (ops/sha256.py core), so the same
program runs on TPU and on the CPU backend (tests pin byte-parity against
hashlib on the virtual mesh).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from transferia_tpu.columnar.batch import bucket_rows
from transferia_tpu.ops.sha256 import (
    _hmac_key_states,
    hmac_device_core,
    prepare_padded_blocks,
)


def _pallas_pack_enabled() -> bool:
    """Opt-in TPU-side ragged pack (ops/ragged_pallas.py).

    Off by default until the per-row DMA pattern is profiled on real
    hardware; the portable C++/numpy host pack is the default feed path.
    """
    import os

    if os.environ.get("TRANSFERIA_TPU_PALLAS_PACK") != "1":
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def hex_device(h):
    """(N, 8) uint32 digest words -> (N, 64) ascii-hex uint8, on device."""
    shifts = jnp.arange(28, -1, -4, dtype=jnp.uint32)  # 28,24,...,0
    nib = (h[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xF)
    out = jnp.where(nib < 10, nib + 48, nib + 87).astype(jnp.uint8)
    return out.reshape(h.shape[0], 64)


def pow2_blocks(max_len: int) -> int:
    """Block count bucket for a max row length (bytes, before padding)."""
    nb = (max_len + 9 + 63) // 64
    return 1 << (nb - 1).bit_length() if nb > 1 else 1


def pack_hmac_blocks(data: np.ndarray, offsets: np.ndarray,
                     max_blocks: int) -> tuple[np.ndarray, np.ndarray]:
    """Flat bytes+offsets -> (N, max_blocks*64) padded HMAC message blocks.

    The 64-byte ipad prefix block is virtual (compressed separately from the
    cached key state); lengths in the SHA padding include it (prefix_len=64).
    C++ fast path releases the GIL so part threads overlap pack with device
    compute; numpy fallback is prepare_padded_blocks.
    """
    from transferia_tpu.native import lib as native_lib

    n = len(offsets) - 1
    width = max_blocks * 64
    cdll = native_lib()
    if cdll is not None and n:
        out = np.empty((n, width), dtype=np.uint8)
        n_blocks = np.empty(n, dtype=np.int32)
        cdll.pack_sha_blocks(
            np.ascontiguousarray(data),
            np.ascontiguousarray(offsets, dtype=np.int32),
            n, width, 64, out, n_blocks,
        )
        return out, n_blocks
    blocks, n_blocks, mb = prepare_padded_blocks(
        data, offsets, prefix_len=64, max_blocks=max_blocks
    )
    return blocks, n_blocks


from transferia_tpu.columnar.hexcol import hex_to_varwidth  # noqa: F401
# (re-exported: the fused step builds its output columns with it)


class FusedMaskFilterProgram:
    """One jitted program: HMAC+hex every masked column, evaluate the keep
    predicate — recompiled only when a bucket (rows / block width) changes.

    mask_keys: HMAC key per masked column (parallel to the blocks the caller
    passes); pred_node: predicate AST or None; the caller supplies the
    predicate columns as (data, validity) arrays.
    """

    def __init__(self, mask_keys: Sequence[bytes], pred_node=None):
        self._states = []
        for key in mask_keys:
            inner, outer = _hmac_key_states(bytes(key))
            self._states.append((jnp.asarray(inner[0]),
                                 jnp.asarray(outer[0])))
        self._pred_fn = None
        if pred_node is not None:
            from transferia_tpu.predicate.device import compile_mask_jnp

            self._pred_fn = compile_mask_jnp(pred_node)

        def program(blocks_t, nblocks_t, states_t, pred_cols,
                    max_blocks_t):
            hexes = tuple(
                hex_device(hmac_device_core(b, nb, st[0], st[1], mb))
                for b, nb, st, mb in zip(
                    blocks_t, nblocks_t, states_t, max_blocks_t
                )
            )
            if self._pred_fn is not None:
                # bucketed batch length is static under this trace; a
                # fused run always has >= 1 masked column
                keep = self._pred_fn(pred_cols, blocks_t[0].shape[0])
            else:
                keep = jnp.zeros((0,), dtype=jnp.bool_)  # unused sentinel
            return hexes, keep

        self._jit = jax.jit(program, static_argnums=(4,))

    def run(self, mask_cols: Sequence[tuple[np.ndarray, np.ndarray]],
            pred_cols: dict[str, tuple[np.ndarray, Optional[np.ndarray]]],
            n_rows: int) -> tuple[list[np.ndarray], Optional[np.ndarray]]:
        """mask_cols: per masked column (flat uint8 data, int32 offsets).
        pred_cols: name -> (fixed-width data, validity or None).
        Returns ([hex (n_rows, 64) per masked column], keep mask or None).
        """
        use_pallas_pack = _pallas_pack_enabled()
        bucket = bucket_rows(n_rows)
        blocks_t, nblocks_t, mb_t = [], [], []
        for data, offsets in mask_cols:
            lens = offsets[1:] - offsets[:-1]
            max_len = int(lens.max()) if n_rows else 0
            mb = pow2_blocks(max_len)
            if use_pallas_pack:
                from transferia_tpu.ops.ragged_pallas import (
                    pack_blocks_device,
                )

                width = mb * 64
                flat = np.ascontiguousarray(data)
                total = int(offsets[-1])
                if len(flat) < total + width:
                    flat = np.pad(flat, (0, total + width - len(flat)))
                blocks_dev, nblocks_dev = pack_blocks_device(
                    flat, np.ascontiguousarray(offsets, dtype=np.int32),
                    bucket, mb,
                )
                # zero pad rows' block count so they never update state
                if bucket != n_rows:
                    row = jnp.arange(bucket, dtype=jnp.int32)
                    nblocks_dev = jnp.where(row < n_rows, nblocks_dev, 0)
                blocks_t.append(blocks_dev)
                nblocks_t.append(nblocks_dev)
                mb_t.append(mb)
                continue
            blocks, n_blocks = pack_hmac_blocks(data, offsets, mb)
            if bucket != n_rows:
                blocks = np.pad(blocks, ((0, bucket - n_rows), (0, 0)))
                n_blocks = np.pad(n_blocks, (0, bucket - n_rows))
            blocks_t.append(jnp.asarray(blocks))
            nblocks_t.append(jnp.asarray(n_blocks))
            mb_t.append(mb)
        dev_pred = {}
        for name, (data, validity) in pred_cols.items():
            if validity is None:
                validity = np.ones(n_rows, dtype=np.bool_)
            if bucket != n_rows:
                data = np.pad(data, (0, bucket - n_rows))
                validity = np.pad(validity, (0, bucket - n_rows))
            dev_pred[name] = (jnp.asarray(data), jnp.asarray(validity))
        hexes_dev, keep_dev = self._jit(
            tuple(blocks_t), tuple(nblocks_t), tuple(self._states),
            dev_pred, tuple(mb_t),
        )
        hexes = []
        for h in hexes_dev:
            arr = np.asarray(h)
            if arr.shape[0] != n_rows:
                # slice-copy: a view would pin the bucket-padded buffer
                # (up to 4x the live rows) for the batch's lifetime
                arr = arr[:n_rows].copy()
            hexes.append(arr)
        keep = (np.asarray(keep_dev)[:n_rows]
                if self._pred_fn is not None else None)
        return hexes, keep
