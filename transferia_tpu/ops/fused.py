"""Fused device transform program: HMAC mask + row predicate in one launch.

Round-1 shape of the device path was one kernel per transformer (mask only)
with a host hop between steps.  This module compiles the whole device-able
run of a transformer plan — every HMAC-SHA256 masked column, hex encoding,
and the row-filter predicate — into ONE jitted XLA program per
(schema fingerprint, row bucket, width buckets), so a mask+filter transfer
does a single H2D/compute/D2H round-trip per batch.

Reference hot loops being displaced: pkg/transformer/transformation.go:22-70
(chain apply) and pkg/transformer/registry/mask/hmac_hasher.go +
registry/filter_rows (per-row Go).

Host side: rows pack into padded SHA block matrices via the C++ hostops
kernel (pack_sha_blocks — memcpy-bound, GIL-free) with a vectorized numpy
fallback; the device side is pure jnp (ops/sha256.py core), so the same
program runs on TPU and on the CPU backend (tests pin byte-parity against
hashlib on the virtual mesh).
"""

from __future__ import annotations

import functools
import time as _time
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from transferia_tpu.columnar.batch import bucket_rows
from transferia_tpu.ops.decode import pack_mask_words
from transferia_tpu.runtime import knobs
from transferia_tpu.ops.dispatch import (
    decode_pred_device,
    encode_pred_column,
    encoding_enabled,
    stage_h2d,
    unpack_mask_host,
)
from transferia_tpu.ops.sha256 import (
    _hmac_key_states,
    hmac_device_core,
    prepare_padded_blocks,
)
from transferia_tpu.stats import stagetimer, trace
from transferia_tpu.stats.trace import TELEMETRY

trace.install_jit_hooks()  # compile-event telemetry rides jax monitoring

_chunk_rows_cached: Optional[int] = None


def _chunk_rows() -> int:
    """Chunk size for pipelined dispatch; 0 disables chunking.

    Defaults to 32768 rows on an accelerator backend (enough work per
    launch to amortize it, small enough for >=4 chunks per 131k batch);
    0 on the CPU backend, where "device" compute shares the host cores
    and pipelining only adds overhead.  TRANSFERIA_TPU_CHUNK_ROWS
    overrides (0 = off).
    """
    global _chunk_rows_cached
    if _chunk_rows_cached is None:
        env = knobs.env_raw("TRANSFERIA_TPU_CHUNK_ROWS")
        if env is not None:
            _chunk_rows_cached = max(0, int(env))
        else:
            from transferia_tpu.ops.linkprobe import probe_link

            link = probe_link()
            if link.backend in ("cpu", "none"):
                _chunk_rows_cached = 0
            elif link.launch_overhead_s > 0.005:
                # high-latency link (tunneled device): per-chunk launches
                # cost more than the overlap they buy — one launch per
                # batch, overlap rides across batches instead
                _chunk_rows_cached = 0
            else:
                _chunk_rows_cached = 32768
    return _chunk_rows_cached


def set_chunk_rows(n: Optional[int]) -> None:
    """Force the pipelined-dispatch chunk size (None = re-detect)."""
    global _chunk_rows_cached
    _chunk_rows_cached = n


def _dispatch_depth() -> int:
    """Launches kept in flight by the pipelined path (H2D of chunk g+1
    staged while chunk g computes and g-1 drains).
    TRANSFERIA_TPU_DISPATCH_DEPTH overrides; floor 1."""
    return max(1, knobs.env_int("TRANSFERIA_TPU_DISPATCH_DEPTH", 2))


def _pallas_pack_enabled() -> bool:
    """Opt-in device-side ragged pack (ops/raggedpack.py; the env name
    is historical — the implementation is XLA after hardware profiling
    retired the Pallas kernel, see that module's docstring).

    Off by default: it halves H2D bytes for short strings on
    PCIe-attached devices, but costs an extra launch — through a
    high-latency tunnel the host C++ pack + padded H2D wins.
    """
    if knobs.env_str("TRANSFERIA_TPU_PALLAS_PACK", "") != "1":
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def pow2_blocks(max_len: int) -> int:
    """Block count bucket for a max row length (bytes, before padding)."""
    nb = (max_len + 9 + 63) // 64
    return 1 << (nb - 1).bit_length() if nb > 1 else 1


def pack_hmac_blocks(data: np.ndarray, offsets: np.ndarray,
                     max_blocks: int) -> tuple[np.ndarray, np.ndarray]:
    """Flat bytes+offsets -> (N, max_blocks*64) padded HMAC message blocks.

    The 64-byte ipad prefix block is virtual (compressed separately from the
    cached key state); lengths in the SHA padding include it (prefix_len=64).
    C++ fast path releases the GIL so part threads overlap pack with device
    compute; numpy fallback is prepare_padded_blocks.
    """
    from transferia_tpu.native import lib as native_lib

    n = len(offsets) - 1
    width = max_blocks * 64
    cdll = native_lib()
    if cdll is not None and n:
        out = np.empty((n, width), dtype=np.uint8)
        n_blocks = np.empty(n, dtype=np.int32)
        cdll.pack_sha_blocks(
            np.ascontiguousarray(data),
            np.ascontiguousarray(offsets, dtype=np.int32),
            n, width, 64, out, n_blocks,
        )
        return out, n_blocks
    blocks, n_blocks, mb = prepare_padded_blocks(
        data, offsets, prefix_len=64, max_blocks=max_blocks
    )
    return blocks, n_blocks


from transferia_tpu.columnar.hexcol import hex_to_varwidth  # noqa: F401
# (re-exported: the fused step builds its output columns with it)


class FusedMaskFilterProgram:
    """One jitted program: HMAC+hex every masked column, evaluate the keep
    predicate — recompiled only when a bucket (rows / block width) changes.

    mask_keys: HMAC key per masked column (parallel to the blocks the caller
    passes); pred_node: predicate AST or None; the caller supplies the
    predicate columns as (data, validity) arrays.
    """

    # jitted wrappers shared across instances, keyed by the predicate
    # AST repr (frozen dataclasses — the repr is the full content).  The
    # HMAC key states are traced ARGUMENTS, not closure constants, so
    # every per-part sink chain (the snapshot loader builds one chain
    # per part) reuses the same compiled program instead of paying an
    # XLA compile per part.  Bounded FIFO: a long-lived worker cycling
    # through transfers with distinct predicate constants must not pin
    # an executable per constant forever.
    _jit_cache: dict = {}
    _JIT_CACHE_MAX = 64

    def __init__(self, mask_keys: Sequence[bytes], pred_node=None):
        self._states = []
        for key in mask_keys:
            inner, outer = _hmac_key_states(bytes(key))
            self._states.append((jnp.asarray(inner[0]),
                                 jnp.asarray(outer[0])))
        self._pred_fn = None
        if pred_node is not None:
            from transferia_tpu.predicate.device import compile_mask_jnp

            self._pred_fn = compile_mask_jnp(pred_node)
        cache_key = repr(pred_node)
        cached = FusedMaskFilterProgram._jit_cache.get(cache_key)
        if cached is not None:
            self._jit = cached
            return
        # bind the closure to THIS instance's pred_fn (an equal AST
        # compiles to an identical mask fn, so cache sharing is sound)
        pred_fn = self._pred_fn

        def program(blocks_t, nblocks_t, states_t, pred_arrays, spec):
            # spec (static): (bucket, max_blocks per column, PredEnc per
            # predicate column, pack_keep).  Raw (N, 8) u32 digests leave
            # the device — 32 bytes/row vs 64 for hex; the host
            # LUT-expands (columnar/hexcol.py).  On bandwidth-starved
            # links (see ops/linkprobe.py) the return payload is kept
            # minimal: with pack_keep the keep mask returns bit-packed.
            bucket, max_blocks_t, pred_specs, pack_keep = spec
            digests = tuple(
                hmac_device_core(b, nb, st[0], st[1], mb)
                for b, nb, st, mb in zip(
                    blocks_t, nblocks_t, states_t, max_blocks_t
                )
            )
            if pred_fn is not None:
                # predicate columns arrive in their dispatch encodings
                # (bit-packed validity, delta ints) and decode on device
                cols = {
                    ps.name: decode_pred_device(ps, arrs, bucket)
                    for ps, arrs in zip(pred_specs, pred_arrays)
                }
                keep = pred_fn(cols, bucket)
                if pack_keep:
                    keep = pack_mask_words(keep, bucket)
            else:
                keep = jnp.zeros((0,), dtype=jnp.bool_)  # unused sentinel
            return digests, keep

        self._jit = jax.jit(program, static_argnums=(4,))
        cache = FusedMaskFilterProgram._jit_cache
        while len(cache) >= FusedMaskFilterProgram._JIT_CACHE_MAX:
            cache.pop(next(iter(cache)))
        cache[cache_key] = self._jit

    def run(self, mask_cols: Sequence[tuple[np.ndarray, np.ndarray]],
            pred_cols: dict[str, tuple[np.ndarray, Optional[np.ndarray]]],
            n_rows: int, states: Optional[list] = None
            ) -> tuple[list[np.ndarray], Optional[np.ndarray]]:
        """mask_cols: per masked column (flat uint8 data, int32 offsets).
        pred_cols: name -> (fixed-width data, validity or None).
        states: HMAC key states parallel to mask_cols (defaults to the
        constructor's full set — callers that peeled dict columns off to
        the pool route pass the surviving subset).
        Returns ([hex (n_rows, 64) per masked column], keep mask or None).

        On an accelerator backend, large batches run as a chunked
        double-buffered pipeline: the host packs+stages chunk k+1's H2D
        while the device computes chunk k and the host drains chunk k-1
        (D2H), so H2D / compute / D2H / pack overlap instead of
        serializing per batch.  One chunk size -> one compiled program.
        """
        from transferia_tpu.chaos.failpoints import failpoint

        failpoint("device.dispatch")
        # one parent span per batch run: pack / device_dispatch /
        # device_wait nest under it, so a chunked pipelined run reads
        # as one causally-grouped unit in the timeline
        with trace.span("fused_run", rows=n_rows):
            chunk = _chunk_rows()
            if chunk and n_rows > chunk and not _pallas_pack_enabled():
                return self._run_pipelined(mask_cols, pred_cols, n_rows,
                                           chunk, states=states)
            return self._run_single(mask_cols, pred_cols, n_rows, states)

    def _stage(self, mask_cols, pred_cols, n_rows, bucket, states=None):
        """Pack + encode on host and enqueue the (async) H2D for one
        chunk — compute does NOT launch here, so a pipelined caller can
        overlap this chunk's transfer with the previous chunk's
        kernels.  Returns the staged device handles."""
        states = self._states if states is None else list(states)
        use_pallas_pack = _pallas_pack_enabled()
        blocks_t, nblocks_t, mb_t = [], [], []
        pack_t0 = _time.perf_counter()
        with trace.span("pack"):
            self._pack_inputs(mask_cols, n_rows, bucket,
                              use_pallas_pack, blocks_t, nblocks_t, mb_t)
            pred_specs, pred_arrays, pred_raw = self._encode_pred(
                pred_cols, n_rows, bucket)
        stagetimer.add("pack", _time.perf_counter() - pack_t0)
        blocks_raw = sum(int(b.nbytes) + int(nb.nbytes)
                         for b, nb in zip(blocks_t, nblocks_t))
        dev_blocks, dev_nblocks, dev_pred = stage_h2d(
            (tuple(blocks_t), tuple(nblocks_t), tuple(pred_arrays)),
            raw_equiv_bytes=blocks_raw + pred_raw)
        h2d = (blocks_raw
               + sum(int(a.nbytes) for arrs in pred_arrays for a in arrs))
        TELEMETRY.record_h2d(h2d)
        pack_keep = self._pred_fn is not None and encoding_enabled()
        spec = (bucket, tuple(mb_t), tuple(pred_specs), pack_keep)
        return (dev_blocks, dev_nblocks, dev_pred, tuple(states), spec,
                n_rows, h2d)

    def _launch(self, staged):
        """Launch the jitted program over a staged chunk (async);
        returns the device handles without blocking on the result."""
        dev_blocks, dev_nblocks, dev_pred, states, spec, rows, h2d = staged
        TELEMETRY.record_launch()
        with stagetimer.stage("device_dispatch"), \
                trace.span("device_dispatch", bytes=h2d, rows=rows):
            hexes_dev, keep_dev = self._jit(
                dev_blocks, dev_nblocks, states, dev_pred, spec,
            )
        return hexes_dev, keep_dev, rows, spec[3]

    def _dispatch(self, mask_cols, pred_cols, n_rows, bucket,
                  states=None):
        staged = self._stage(mask_cols, pred_cols, n_rows, bucket,
                             states)
        hexes_dev, keep_dev, _rows, packed = self._launch(staged)
        return hexes_dev, keep_dev, packed

    def _encode_pred(self, pred_cols, n_rows, bucket):
        """Per-column dispatch encodings (ops/dispatch.py): bit-packed
        validity + delta ints when they shrink, raw otherwise."""
        enc = encoding_enabled()
        specs, arrays, raw_total = [], [], 0
        for name, (data, validity) in pred_cols.items():
            spec, arrs, raw = encode_pred_column(
                name, data, validity, n_rows, bucket, enc)
            specs.append(spec)
            arrays.append(arrs)
            raw_total += raw
        return specs, arrays, raw_total

    def _pack_inputs(self, mask_cols, n_rows, bucket,
                     use_pallas_pack, blocks_t, nblocks_t, mb_t):
        for data, offsets in mask_cols:
            lens = offsets[1:] - offsets[:-1]
            max_len = int(lens.max()) if n_rows else 0
            mb = pow2_blocks(max_len)
            if use_pallas_pack:
                from transferia_tpu.ops.raggedpack import (
                    pack_blocks_device,
                )

                width = mb * 64
                flat = np.ascontiguousarray(data)
                total = int(offsets[-1])
                if len(flat) < total + width:
                    flat = np.pad(flat, (0, total + width - len(flat)))
                blocks_dev, nblocks_dev = pack_blocks_device(
                    flat, np.ascontiguousarray(offsets, dtype=np.int32),
                    bucket, mb,
                )
                # zero pad rows' block count so they never update state
                if bucket != n_rows:
                    row = jnp.arange(bucket, dtype=jnp.int32)
                    nblocks_dev = jnp.where(row < n_rows, nblocks_dev, 0)
                blocks_t.append(blocks_dev)
                nblocks_t.append(nblocks_dev)
                mb_t.append(mb)
                continue
            blocks, n_blocks = pack_hmac_blocks(data, offsets, mb)
            if bucket != n_rows:
                blocks = np.pad(blocks, ((0, bucket - n_rows), (0, 0)))
                n_blocks = np.pad(n_blocks, (0, bucket - n_rows))
            # numpy here — the H2D is staged explicitly by stage_h2d so
            # the pipelined path controls when the transfer enqueues
            blocks_t.append(blocks)
            nblocks_t.append(n_blocks)
            mb_t.append(mb)

    def _collect(self, digests_dev, keep_dev, n_rows, packed_keep=False
                 ) -> tuple[list[np.ndarray], Optional[np.ndarray]]:
        """Block on D2H, trim bucket padding, hex-expand on host."""
        from transferia_tpu.columnar.hexcol import digests_to_hex

        hexes = []
        t0 = _time.perf_counter()
        with stagetimer.stage("device_wait"), \
                trace.span("device_wait") as sp:
            for h in digests_dev:
                # digests_to_hex allocates fresh output, so the sliced
                # view never pins the bucket-padded transfer buffer
                arr = np.asarray(h)[:n_rows]
                hexes.append(digests_to_hex(arr))
            if self._pred_fn is None:
                keep = None
            elif packed_keep:
                keep = unpack_mask_host(np.asarray(keep_dev), n_rows)
            else:
                keep = np.asarray(keep_dev)[:n_rows]
            d2h = sum(int(h.nbytes) for h in digests_dev)
            if keep_dev is not None and self._pred_fn is not None:
                d2h += int(keep_dev.nbytes)
            if sp:  # args must attach before the span ends
                sp.add(bytes=d2h, rows=n_rows)
        TELEMETRY.record_d2h(d2h)
        TELEMETRY.record_kernel(_time.perf_counter() - t0)
        return hexes, keep

    def _run_single(self, mask_cols, pred_cols, n_rows, states=None):
        hexes_dev, keep_dev, packed = self._dispatch(
            mask_cols, pred_cols, n_rows, bucket_rows(n_rows), states)
        return self._collect(hexes_dev, keep_dev, n_rows, packed)

    def _run_pipelined(self, mask_cols, pred_cols, n_rows, chunk,
                       depth: Optional[int] = None, states=None):
        """Split the batch into fixed-size chunks and keep `depth` device
        launches in flight, with one chunk's H2D always staged AHEAD of
        the compute launches: stage(k+1) overlaps compute(k) and
        D2H(k-1), so the link and the chip work simultaneously."""
        from collections import deque

        if depth is None:
            depth = _dispatch_depth()
        staged_q: deque = deque()
        inflight: deque = deque()
        hex_parts: list[list[np.ndarray]] = []
        keep_parts: list[np.ndarray] = []

        def launch_oldest():
            h_dev, k_dev, rows, packed = self._launch(staged_q.popleft())
            inflight.append((h_dev, k_dev, rows, packed))

        def drain_one():
            h_dev, k_dev, rows, packed = inflight.popleft()
            hexes, keep = self._collect(h_dev, k_dev, rows, packed)
            hex_parts.append(hexes)
            if keep is not None:
                keep_parts.append(keep)

        for lo in range(0, n_rows, chunk):
            hi = min(lo + chunk, n_rows)
            rows = hi - lo
            sub_mask = []
            for data, offsets in mask_cols:
                base = int(offsets[lo])
                sub_off = (offsets[lo:hi + 1] - base).astype(
                    offsets.dtype, copy=False)
                sub_mask.append(
                    (data[base:int(offsets[hi])], sub_off))
            sub_pred = {}
            for name, (data, validity) in pred_cols.items():
                sub_pred[name] = (
                    data[lo:hi],
                    validity[lo:hi] if validity is not None else None,
                )
            staged_q.append(self._stage(sub_mask, sub_pred, rows,
                                        bucket_rows(rows), states))
            # launch all but the freshest chunk: its H2D streams while
            # the previous chunk's kernels run (double-buffered H2D)
            while len(staged_q) > 1:
                launch_oldest()
            while len(inflight) > depth:
                drain_one()
        while staged_q:
            launch_oldest()
        while inflight:
            drain_one()
        n_mask = len(mask_cols)
        hexes = [
            np.concatenate([p[i] for p in hex_parts])
            if hex_parts else np.empty((0, 64), dtype=np.uint8)
            for i in range(n_mask)
        ]
        keep = (np.concatenate(keep_parts)
                if self._pred_fn is not None and keep_parts else
                (np.empty(0, dtype=np.bool_)
                 if self._pred_fn is not None else None))
        return hexes, keep
