"""Fused device transform program: HMAC mask + row predicate in one launch.

Round-1 shape of the device path was one kernel per transformer (mask only)
with a host hop between steps.  This module compiles the whole device-able
run of a transformer plan — every HMAC-SHA256 masked column, hex encoding,
and the row-filter predicate — into ONE jitted XLA program per
(schema fingerprint, row bucket, width buckets), so a mask+filter transfer
does a single H2D/compute/D2H round-trip per batch.

Reference hot loops being displaced: pkg/transformer/transformation.go:22-70
(chain apply) and pkg/transformer/registry/mask/hmac_hasher.go +
registry/filter_rows (per-row Go).

Host side: rows pack into padded SHA block matrices via the C++ hostops
kernel (pack_sha_blocks — memcpy-bound, GIL-free) with a vectorized numpy
fallback; the device side is pure jnp (ops/sha256.py core), so the same
program runs on TPU and on the CPU backend (tests pin byte-parity against
hashlib on the virtual mesh).
"""

from __future__ import annotations

import functools
import time as _time
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from transferia_tpu.columnar.batch import bucket_rows
from transferia_tpu.ops.sha256 import (
    _hmac_key_states,
    hmac_device_core,
    prepare_padded_blocks,
)
from transferia_tpu.stats import stagetimer, trace
from transferia_tpu.stats.trace import TELEMETRY

trace.install_jit_hooks()  # compile-event telemetry rides jax monitoring

_chunk_rows_cached: Optional[int] = None


def _chunk_rows() -> int:
    """Chunk size for pipelined dispatch; 0 disables chunking.

    Defaults to 32768 rows on an accelerator backend (enough work per
    launch to amortize it, small enough for >=4 chunks per 131k batch);
    0 on the CPU backend, where "device" compute shares the host cores
    and pipelining only adds overhead.  TRANSFERIA_TPU_CHUNK_ROWS
    overrides (0 = off).
    """
    global _chunk_rows_cached
    if _chunk_rows_cached is None:
        import os

        env = os.environ.get("TRANSFERIA_TPU_CHUNK_ROWS")
        if env is not None:
            _chunk_rows_cached = max(0, int(env))
        else:
            from transferia_tpu.ops.linkprobe import probe_link

            link = probe_link()
            if link.backend in ("cpu", "none"):
                _chunk_rows_cached = 0
            elif link.launch_overhead_s > 0.005:
                # high-latency link (tunneled device): per-chunk launches
                # cost more than the overlap they buy — one launch per
                # batch, overlap rides across batches instead
                _chunk_rows_cached = 0
            else:
                _chunk_rows_cached = 32768
    return _chunk_rows_cached


def set_chunk_rows(n: Optional[int]) -> None:
    """Force the pipelined-dispatch chunk size (None = re-detect)."""
    global _chunk_rows_cached
    _chunk_rows_cached = n


def _pallas_pack_enabled() -> bool:
    """Opt-in device-side ragged pack (ops/raggedpack.py; the env name
    is historical — the implementation is XLA after hardware profiling
    retired the Pallas kernel, see that module's docstring).

    Off by default: it halves H2D bytes for short strings on
    PCIe-attached devices, but costs an extra launch — through a
    high-latency tunnel the host C++ pack + padded H2D wins.
    """
    import os

    if os.environ.get("TRANSFERIA_TPU_PALLAS_PACK") != "1":
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def pow2_blocks(max_len: int) -> int:
    """Block count bucket for a max row length (bytes, before padding)."""
    nb = (max_len + 9 + 63) // 64
    return 1 << (nb - 1).bit_length() if nb > 1 else 1


def pack_hmac_blocks(data: np.ndarray, offsets: np.ndarray,
                     max_blocks: int) -> tuple[np.ndarray, np.ndarray]:
    """Flat bytes+offsets -> (N, max_blocks*64) padded HMAC message blocks.

    The 64-byte ipad prefix block is virtual (compressed separately from the
    cached key state); lengths in the SHA padding include it (prefix_len=64).
    C++ fast path releases the GIL so part threads overlap pack with device
    compute; numpy fallback is prepare_padded_blocks.
    """
    from transferia_tpu.native import lib as native_lib

    n = len(offsets) - 1
    width = max_blocks * 64
    cdll = native_lib()
    if cdll is not None and n:
        out = np.empty((n, width), dtype=np.uint8)
        n_blocks = np.empty(n, dtype=np.int32)
        cdll.pack_sha_blocks(
            np.ascontiguousarray(data),
            np.ascontiguousarray(offsets, dtype=np.int32),
            n, width, 64, out, n_blocks,
        )
        return out, n_blocks
    blocks, n_blocks, mb = prepare_padded_blocks(
        data, offsets, prefix_len=64, max_blocks=max_blocks
    )
    return blocks, n_blocks


from transferia_tpu.columnar.hexcol import hex_to_varwidth  # noqa: F401
# (re-exported: the fused step builds its output columns with it)


class FusedMaskFilterProgram:
    """One jitted program: HMAC+hex every masked column, evaluate the keep
    predicate — recompiled only when a bucket (rows / block width) changes.

    mask_keys: HMAC key per masked column (parallel to the blocks the caller
    passes); pred_node: predicate AST or None; the caller supplies the
    predicate columns as (data, validity) arrays.
    """

    # jitted wrappers shared across instances, keyed by the predicate
    # AST repr (frozen dataclasses — the repr is the full content).  The
    # HMAC key states are traced ARGUMENTS, not closure constants, so
    # every per-part sink chain (the snapshot loader builds one chain
    # per part) reuses the same compiled program instead of paying an
    # XLA compile per part.  Bounded FIFO: a long-lived worker cycling
    # through transfers with distinct predicate constants must not pin
    # an executable per constant forever.
    _jit_cache: dict = {}
    _JIT_CACHE_MAX = 64

    def __init__(self, mask_keys: Sequence[bytes], pred_node=None):
        self._states = []
        for key in mask_keys:
            inner, outer = _hmac_key_states(bytes(key))
            self._states.append((jnp.asarray(inner[0]),
                                 jnp.asarray(outer[0])))
        self._pred_fn = None
        if pred_node is not None:
            from transferia_tpu.predicate.device import compile_mask_jnp

            self._pred_fn = compile_mask_jnp(pred_node)
        cache_key = repr(pred_node)
        cached = FusedMaskFilterProgram._jit_cache.get(cache_key)
        if cached is not None:
            self._jit = cached
            return
        # bind the closure to THIS instance's pred_fn (an equal AST
        # compiles to an identical mask fn, so cache sharing is sound)
        pred_fn = self._pred_fn

        def program(blocks_t, nblocks_t, states_t, pred_cols,
                    max_blocks_t):
            # raw (N, 8) u32 digests leave the device — 32 bytes/row vs
            # 64 for hex; the host LUT-expands (columnar/hexcol.py).  On
            # bandwidth-starved links (see ops/linkprobe.py) D2H is the
            # bottleneck stage, so the return payload is kept minimal.
            digests = tuple(
                hmac_device_core(b, nb, st[0], st[1], mb)
                for b, nb, st, mb in zip(
                    blocks_t, nblocks_t, states_t, max_blocks_t
                )
            )
            if pred_fn is not None:
                # bucketed batch length is static under this trace; a
                # fused run always has >= 1 masked column
                keep = pred_fn(pred_cols, blocks_t[0].shape[0])
            else:
                keep = jnp.zeros((0,), dtype=jnp.bool_)  # unused sentinel
            return digests, keep

        self._jit = jax.jit(program, static_argnums=(4,))
        cache = FusedMaskFilterProgram._jit_cache
        while len(cache) >= FusedMaskFilterProgram._JIT_CACHE_MAX:
            cache.pop(next(iter(cache)))
        cache[cache_key] = self._jit

    def run(self, mask_cols: Sequence[tuple[np.ndarray, np.ndarray]],
            pred_cols: dict[str, tuple[np.ndarray, Optional[np.ndarray]]],
            n_rows: int) -> tuple[list[np.ndarray], Optional[np.ndarray]]:
        """mask_cols: per masked column (flat uint8 data, int32 offsets).
        pred_cols: name -> (fixed-width data, validity or None).
        Returns ([hex (n_rows, 64) per masked column], keep mask or None).

        On an accelerator backend, large batches run as a chunked
        double-buffered pipeline: the host packs+dispatches chunk k+1
        while the device computes chunk k and the host drains chunk k-1
        (D2H), so H2D / compute / D2H / pack overlap instead of
        serializing per batch.  One chunk size -> one compiled program.
        """
        from transferia_tpu.chaos.failpoints import failpoint

        failpoint("device.dispatch")
        chunk = _chunk_rows()
        if chunk and n_rows > chunk and not _pallas_pack_enabled():
            return self._run_pipelined(mask_cols, pred_cols, n_rows,
                                       chunk)
        return self._run_single(mask_cols, pred_cols, n_rows)

    def _dispatch(self, mask_cols, pred_cols, n_rows, bucket):
        """Pack on host and launch the jitted program (async); returns
        the device handles without blocking on the result."""
        use_pallas_pack = _pallas_pack_enabled()
        blocks_t, nblocks_t, mb_t = [], [], []
        pack_t0 = _time.perf_counter()
        with trace.span("pack"):
            self._pack_inputs(mask_cols, n_rows, bucket,
                              use_pallas_pack, blocks_t, nblocks_t, mb_t)
            dev_pred = self._pack_pred(pred_cols, n_rows, bucket)
        stagetimer.add("pack", _time.perf_counter() - pack_t0)
        h2d = (sum(int(b.nbytes) + int(nb.nbytes)
                   for b, nb in zip(blocks_t, nblocks_t))
               + sum(int(d.nbytes) + int(v.nbytes)
                     for d, v in dev_pred.values()))
        TELEMETRY.record_h2d(h2d)
        TELEMETRY.record_launch()
        with stagetimer.stage("device_dispatch"), \
                trace.span("device_dispatch", bytes=h2d, rows=n_rows):
            hexes_dev, keep_dev = self._jit(
                tuple(blocks_t), tuple(nblocks_t), tuple(self._states),
                dev_pred, tuple(mb_t),
            )
        return hexes_dev, keep_dev

    def _pack_inputs(self, mask_cols, n_rows, bucket,
                     use_pallas_pack, blocks_t, nblocks_t, mb_t):
        for data, offsets in mask_cols:
            lens = offsets[1:] - offsets[:-1]
            max_len = int(lens.max()) if n_rows else 0
            mb = pow2_blocks(max_len)
            if use_pallas_pack:
                from transferia_tpu.ops.raggedpack import (
                    pack_blocks_device,
                )

                width = mb * 64
                flat = np.ascontiguousarray(data)
                total = int(offsets[-1])
                if len(flat) < total + width:
                    flat = np.pad(flat, (0, total + width - len(flat)))
                blocks_dev, nblocks_dev = pack_blocks_device(
                    flat, np.ascontiguousarray(offsets, dtype=np.int32),
                    bucket, mb,
                )
                # zero pad rows' block count so they never update state
                if bucket != n_rows:
                    row = jnp.arange(bucket, dtype=jnp.int32)
                    nblocks_dev = jnp.where(row < n_rows, nblocks_dev, 0)
                blocks_t.append(blocks_dev)
                nblocks_t.append(nblocks_dev)
                mb_t.append(mb)
                continue
            blocks, n_blocks = pack_hmac_blocks(data, offsets, mb)
            if bucket != n_rows:
                blocks = np.pad(blocks, ((0, bucket - n_rows), (0, 0)))
                n_blocks = np.pad(n_blocks, (0, bucket - n_rows))
            blocks_t.append(jnp.asarray(blocks))
            nblocks_t.append(jnp.asarray(n_blocks))
            mb_t.append(mb)

    def _pack_pred(self, pred_cols, n_rows, bucket) -> dict:
        dev_pred = {}
        for name, (data, validity) in pred_cols.items():
            if validity is None:
                validity = np.ones(n_rows, dtype=np.bool_)
            if bucket != n_rows:
                data = np.pad(data, (0, bucket - n_rows))
                validity = np.pad(validity, (0, bucket - n_rows))
            dev_pred[name] = (jnp.asarray(data), jnp.asarray(validity))
        return dev_pred

    def _collect(self, digests_dev, keep_dev, n_rows
                 ) -> tuple[list[np.ndarray], Optional[np.ndarray]]:
        """Block on D2H, trim bucket padding, hex-expand on host."""
        from transferia_tpu.columnar.hexcol import digests_to_hex

        hexes = []
        t0 = _time.perf_counter()
        with stagetimer.stage("device_wait"), \
                trace.span("device_wait") as sp:
            for h in digests_dev:
                # digests_to_hex allocates fresh output, so the sliced
                # view never pins the bucket-padded transfer buffer
                arr = np.asarray(h)[:n_rows]
                hexes.append(digests_to_hex(arr))
            keep = (np.asarray(keep_dev)[:n_rows]
                    if self._pred_fn is not None else None)
            d2h = sum(int(h.nbytes) for h in digests_dev)
            if keep_dev is not None and self._pred_fn is not None:
                d2h += int(keep_dev.nbytes)
            if sp:  # args must attach before the span ends
                sp.add(bytes=d2h, rows=n_rows)
        TELEMETRY.record_d2h(d2h)
        TELEMETRY.record_kernel(_time.perf_counter() - t0)
        return hexes, keep

    def _run_single(self, mask_cols, pred_cols, n_rows):
        hexes_dev, keep_dev = self._dispatch(mask_cols, pred_cols,
                                             n_rows, bucket_rows(n_rows))
        return self._collect(hexes_dev, keep_dev, n_rows)

    def _run_pipelined(self, mask_cols, pred_cols, n_rows, chunk,
                       depth: int = 2):
        """Split the batch into fixed-size chunks and keep `depth` device
        launches in flight: pack(k+1) overlaps compute(k) and D2H(k-1)."""
        from collections import deque

        inflight: deque = deque()
        hex_parts: list[list[np.ndarray]] = []
        keep_parts: list[np.ndarray] = []

        def drain_one():
            h_dev, k_dev, rows = inflight.popleft()
            hexes, keep = self._collect(h_dev, k_dev, rows)
            hex_parts.append(hexes)
            if keep is not None:
                keep_parts.append(keep)

        for lo in range(0, n_rows, chunk):
            hi = min(lo + chunk, n_rows)
            rows = hi - lo
            sub_mask = []
            for data, offsets in mask_cols:
                base = int(offsets[lo])
                sub_off = (offsets[lo:hi + 1] - base).astype(
                    offsets.dtype, copy=False)
                sub_mask.append(
                    (data[base:int(offsets[hi])], sub_off))
            sub_pred = {}
            for name, (data, validity) in pred_cols.items():
                sub_pred[name] = (
                    data[lo:hi],
                    validity[lo:hi] if validity is not None else None,
                )
            h_dev, k_dev = self._dispatch(sub_mask, sub_pred, rows,
                                          bucket_rows(rows))
            inflight.append((h_dev, k_dev, rows))
            while len(inflight) > depth:
                drain_one()
        while inflight:
            drain_one()
        n_mask = len(mask_cols)
        hexes = [
            np.concatenate([p[i] for p in hex_parts])
            if hex_parts else np.empty((0, 64), dtype=np.uint8)
            for i in range(n_mask)
        ]
        keep = (np.concatenate(keep_parts)
                if self._pred_fn is not None and keep_parts else
                (np.empty(0, dtype=np.bool_)
                 if self._pred_fn is not None else None))
        return hexes, keep
