"""Pallas TPU kernel: ragged var-width rows -> padded SHA block matrices.

The fused mask program (ops/fused.py) consumes (N, max_blocks*64) padded
message matrices.  The portable feed path packs them on the host (C++
pack_sha_blocks) and ships the padded matrix over PCIe — ~2.5x the bytes of
the raw ragged column for short strings.  This kernel moves the pack onto
the TPU: the host ships the *flat* byte buffer + offsets, and each grid
step DMAs its rows' byte ranges from HBM into the output block in VMEM,
then applies the SHA-256 padding (0x80 terminator + big-endian bit length,
HMAC ipad prefix accounted) as vectorized VPU ops.

This is the var-width byte-gather XLA is weak at: a gather of ragged byte
ranges lowers to per-element dynamic-slices, while the DMA engine copies
ranges natively.  Per-row DMAs are small (tens of bytes); the win is
halving H2D traffic and freeing the host core, not DMA efficiency — so the
kernel is opt-in (TRANSFERIA_TPU_PALLAS_PACK=1) until profiled on real
hardware, and correctness is pinned by interpret-mode parity tests against
the C++/numpy host pack.

Layout contract (caller: ops/fused.py):
- flat buffer padded with >= width slack bytes (row DMAs may overread);
- offsets padded to the row bucket by repeating the final offset (pad rows
  read garbage, produce n_blocks for a zero-length row, and are sliced off
  on the host);
- row bucket is a multiple of TILE (=32, the int8 sublane tile).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 32  # rows per grid step; int8 min tile is (32, 128)


def _pack_kernel(width: int, starts_ref, lens_ref, flat_ref,
                 out_ref, nb_ref, sems):
    # 1) DMA each row's byte range HBM -> VMEM output block
    for r in range(TILE):
        pltpu.make_async_copy(
            flat_ref.at[pl.ds(starts_ref[r], width)],
            out_ref.at[r],
            sems.at[r],
        ).start()
    for r in range(TILE):
        pltpu.make_async_copy(
            flat_ref.at[pl.ds(starts_ref[r], width)],
            out_ref.at[r],
            sems.at[r],
        ).wait()

    # 2) vectorized SHA padding on the (TILE, width) block
    col = jax.lax.broadcasted_iota(jnp.int32, (TILE, width), 1)
    lens = lens_ref[:]  # (TILE, 1) int32 in VMEM (vector operand)
    data = out_ref[:].astype(jnp.int32)
    msg = jnp.where(col < lens, data, 0)
    msg = jnp.where(col == lens, 0x80, msg)
    nb = (lens + 9 + 63) // 64
    pos = nb * 64 - 8  # first byte of the 8-byte big-endian length
    k = col - pos
    bits = (lens + 64) * 8  # +64: virtual HMAC ipad prefix block
    shift = 8 * (7 - k)
    lenbyte = jnp.where(
        (k >= 0) & (k < 8) & (shift < 32),
        jax.lax.shift_right_logical(
            jnp.broadcast_to(bits, col.shape),
            jnp.clip(shift, 0, 31),
        ) & 0xFF,
        0,
    )
    msg = jnp.where((k >= 0) & (k < 8), lenbyte, msg)
    out_ref[:] = msg.astype(jnp.uint8)
    nb_ref[:] = nb


@functools.partial(jax.jit, static_argnums=(3, 4))
def _pack_blocks_call(flat, starts, lens, width: int, interpret: bool):
    n = starts.shape[0]
    grid = n // TILE
    kernel = functools.partial(_pack_kernel, width)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((TILE,), lambda i: (i,),
                         memory_space=pltpu.SMEM),  # starts: DMA scalars
            pl.BlockSpec((TILE, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),  # lens: vector operand
            pl.BlockSpec(memory_space=pl.ANY),  # flat stays in HBM
        ],
        out_specs=[
            pl.BlockSpec((TILE, width), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, width), jnp.uint8),
            jax.ShapeDtypeStruct((n, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.SemaphoreType.DMA((TILE,))],
        interpret=interpret,
    )(starts, lens, flat)


def pack_blocks_device(flat_padded: np.ndarray, offsets: np.ndarray,
                       n_rows_bucket: int, max_blocks: int,
                       interpret: bool = False):
    """Host wrapper: pad/shape inputs per the layout contract and invoke.

    flat_padded: (B + >=width slack,) uint8; offsets: (n+1,) int32 for the
    true rows.  Returns device arrays (blocks (bucket, width) uint8,
    n_blocks (bucket,) int32) — pad rows' content is garbage-but-valid and
    must be masked/sliced by the caller.
    """
    width = max_blocks * 64
    n = len(offsets) - 1
    assert n_rows_bucket % TILE == 0, "bucket must be a TILE multiple"
    starts = np.empty(n_rows_bucket, dtype=np.int32)
    lens = np.zeros((n_rows_bucket, 1), dtype=np.int32)
    starts[:n] = offsets[:-1]
    starts[n:] = offsets[-1]
    lens[:n, 0] = offsets[1:] - offsets[:-1]
    assert len(flat_padded) >= int(offsets[-1]) + width, \
        "flat buffer needs >= width slack bytes for row overreads"
    blocks, nb = _pack_blocks_call(
        jnp.asarray(flat_padded), jnp.asarray(starts), jnp.asarray(lens),
        width, interpret,
    )
    return blocks, nb.reshape(-1)
