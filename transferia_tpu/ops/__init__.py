"""Device (JAX/XLA/Pallas) kernels for the hot data-plane ops.

These replace the reference's hand-optimized Go hot loops (SURVEY.md §3
"hot loops": generic parser, serializer batch loops, CH marshaller, mask
hasher) with batched device kernels.  Everything here is shape-static:
callers bucket row counts (columnar.bucket_rows) and byte widths so XLA
compiles once per (schema fingerprint, bucket).
"""

from transferia_tpu.ops.sha256 import (
    hmac_sha256_hex_batch,
    sha256_batch,
)
from transferia_tpu.ops.device_batch import (
    pack_varwidth_matrix,
    pad_to_bucket,
)

__all__ = [
    "hmac_sha256_hex_batch",
    "sha256_batch",
    "pack_varwidth_matrix",
    "pad_to_bucket",
]
