"""Compressed device dispatch: ship encoded columns, decode on device.

The slow host↔device link is the governing bottleneck of the device
plane (BENCH_r03/r04: h2d ≈ 21 MB/s, every fused step link-gated to
`placement=host` while the mask kernel idles at 12-14M rows/s).  The
fix is not a faster kernel — it is fewer bytes: columns cross the link
in their compact encodings and the decode kernels in ops/decode.py
reconstruct them on device, byte-identical to host decode.

Encodings (selection is per column, per batch, host-side):

- **dict pools** — a `DictEnc` masked column never ships row bytes at
  all: the value pool uploads ONCE per (pool, HMAC key), hashes on
  device, and the (k, 8) digest matrix comes back to become a hexed
  `DictPool` memoized on the shared pool (`device_hmac_dict_pool`).
  Batches slicing the same row group then mask for free — the row
  codes never leave the host.
- **validity bitmaps** — bit-packed to n/8 bytes (`encode_validity` /
  `ops.decode.unpack_validity`); the keep mask returns the same way
  (`ops.decode.pack_mask_words`), shrinking the predicate's D2H 8x.
- **delta + bit-pack integers** — predicate columns whose zigzag'd
  deltas fit <= 30 bits ship as base + packed deltas and reconstruct
  via `ops.decode.delta_prefix_sum` (sorted ids, timestamps, dates).
- **bool data** — bit-packed like validity.

`TRANSFERIA_TPU_DISPATCH_ENCODING` picks the mode: `auto` (default —
encode whenever it shrinks) or `raw` (the pre-compression wire, kept
as the fallback and the A side of `bench.py --dispatch`).

Grounding: Zerrow (PAPERS.md) keeps data in its compact columnar
encoding across plane boundaries; Thallus shows transport cost, not
compute, is what columnar pipelines must engineer around.  This module
is host-side only (numpy packers + staging); the traced decode lives
in ops/decode.py, and ops/fused.py composes both into the fused
program.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

from transferia_tpu.runtime import knobs
from transferia_tpu.stats import trace
from transferia_tpu.stats.trace import TELEMETRY

_mode_cached: Optional[str] = None

# serializes dict-pool device hashing: concurrent part threads sharing
# one DictPool must not each pay the pool upload the memo exists to
# amortize.  One process-wide lock is enough — uploads happen once per
# (pool, key), so contention is a startup transient, not steady state.
_pool_hash_lock = threading.Lock()

# zigzag'd deltas wider than this fall back to raw: the device prefix
# sum runs in int32 and must never wrap (30 bits of |delta| keeps every
# partial sum an exact int32), and past ~30 bits the shrink is gone
_DELTA_MAX_BITS = 30
# below this many rows the encode/decode round trip costs more than the
# handful of saved bytes
_DELTA_MIN_ROWS = 256

_for_frame_cached: Optional[int] = None


def for_frame() -> int:
    """Frame size of the frame-of-reference integer encoding
    (TRANSFERIA_TPU_FOR_FRAME; default 256 — every row bucket is a
    multiple; 0 disables FOR).  Static for jit: one frame size -> one
    compiled decode."""
    global _for_frame_cached
    if _for_frame_cached is None:
        _for_frame_cached = max(
            0, knobs.env_int("TRANSFERIA_TPU_FOR_FRAME", 256))
    return _for_frame_cached


def set_for_frame(n: Optional[int]) -> None:
    """Force the FOR frame size (None = re-read the env)."""
    global _for_frame_cached
    _for_frame_cached = n


def dispatch_encoding() -> str:
    """auto (encode whenever it shrinks, default) | raw."""
    global _mode_cached
    if _mode_cached is None:
        mode = knobs.env_str(
            "TRANSFERIA_TPU_DISPATCH_ENCODING", "auto").lower()
        _mode_cached = mode if mode in ("auto", "raw") else "auto"
    return _mode_cached


def set_dispatch_encoding(mode: Optional[str]) -> None:
    """Force the dispatch encoding mode (None = re-read the env)."""
    global _mode_cached
    _mode_cached = mode


def encoding_enabled() -> bool:
    return dispatch_encoding() != "raw"


# -- host-side packers -------------------------------------------------------

def pack_bits_host(values: np.ndarray, bit_width: int) -> np.ndarray:
    """Non-negative values -> the little-endian packed uint32 word
    stream ops/decode.unpack_bits consumes (value i occupies bits
    [i*bw, (i+1)*bw) of the stream)."""
    n = len(values)
    if n == 0:
        return np.zeros(0, dtype=np.uint32)
    shifts = np.arange(bit_width, dtype=np.uint64)
    bits = ((values.astype(np.uint64)[:, None] >> shifts) & 1).astype(
        np.uint8)
    packed = np.packbits(bits.reshape(-1), bitorder="little")
    pad = (-len(packed)) % 4
    if pad:
        packed = np.pad(packed, (0, pad))
    return packed.view(np.uint32)


def encode_validity(validity: np.ndarray) -> np.ndarray:
    """(n,) bool -> packed little-endian uint32 bitmap words."""
    packed = np.packbits(np.ascontiguousarray(validity, dtype=np.uint8),
                         bitorder="little")
    pad = (-len(packed)) % 4
    if pad:
        packed = np.pad(packed, (0, pad))
    return packed.view(np.uint32)


def unpack_mask_host(words: np.ndarray, n: int) -> np.ndarray:
    """Packed uint32 keep-mask words (D2H) -> (n,) bool, host side."""
    bits = np.unpackbits(np.ascontiguousarray(words).view(np.uint8),
                         bitorder="little")
    return bits[:n].astype(np.bool_)


def _delta_plan(values: np.ndarray
                ) -> Optional[tuple[np.ndarray, np.ndarray, int]]:
    """THE delta-encoding guard chain, shared by the single-device and
    mesh wires (values: (n_shards, per) — one row per independently
    decoded chunk).  Returns (bases int32 (n_shards,), zigzag'd deltas
    uint64 (n_shards, per), bit_width) or None when any guard rejects.

    Guards: the device prefix sum reconstructs VALUES in int32, not
    just deltas — every value (and the base) must fit int32 exactly,
    or a 64-bit column would decode wrapped; zigzag widths past
    _DELTA_MAX_BITS could wrap a partial sum; and the packed form must
    actually shrink the raw dtype.  Keep these HERE only — a guard
    tweaked in one wire but not the other would silently diverge the
    single-device and mesh decodes."""
    n_shards, per = values.shape
    if values.dtype.kind not in "iu" or per < _DELTA_MIN_ROWS:
        return None
    v = values.astype(np.int64)
    if int(v.min()) < -2**31 or int(v.max()) > 2**31 - 1:
        return None
    bases = v[:, :1]
    deltas = np.diff(v, axis=1, prepend=bases)
    zz = ((deltas << 1) ^ (deltas >> 63)).astype(np.uint64)
    bw = max(1, int(zz.max()).bit_length())
    if bw > _DELTA_MAX_BITS:
        return None
    if bw * per >= values.dtype.itemsize * 8 * per:
        return None  # no shrink over the raw dtype
    return bases[:, 0].astype(np.int32), zz, bw


def encode_delta(data: np.ndarray
                 ) -> Optional[tuple[int, np.ndarray, int]]:
    """Delta+bit-pack an integer array: (base, packed words, bit_width),
    or None when the encoding would not shrink the transfer."""
    if data.ndim != 1:
        return None
    plan = _delta_plan(data.reshape(1, -1))
    if plan is None:
        return None
    bases, zz, bw = plan
    return int(bases[0]), pack_bits_host(zz[0], bw), bw


def _for_plan(values: np.ndarray
              ) -> Optional[tuple[np.ndarray, np.ndarray, int, int]]:
    """THE frame-of-reference guard chain, shared by the single-device
    and mesh wires (values: (n_shards, per)).  Returns (mins int32
    (n_shards, n_frames), rel uint64 (n_shards, per), bit_width, frame)
    or None when any guard rejects.

    FOR closes the delta wire's "unprofitable reject" gap for
    clustered-but-unsorted ints: per static-size frame, subtract the
    frame minimum and bit-pack the non-negative remainders with ONE
    uniform width (the max across frames/shards — the decode program
    stays static).  Guards: every value must fit int32 exactly (the
    device reconstructs in int32; wraparound adds are exact only
    then), the frame size must divide the padded row count (buckets
    are multiples of every power-of-two frame <= 256), and packed
    remainders + per-frame mins must genuinely shrink the raw dtype."""
    frame = for_frame()
    n_shards, per = values.shape
    if (frame <= 0 or values.dtype.kind not in "iu"
            or per < _DELTA_MIN_ROWS or per % frame):
        return None
    v = values.astype(np.int64)
    if int(v.min()) < -2**31 or int(v.max()) > 2**31 - 1:
        return None
    framed = v.reshape(n_shards, per // frame, frame)
    mins = framed.min(axis=2)
    rel = (framed - mins[:, :, None]).reshape(n_shards, per) \
        .astype(np.uint64)
    bw = max(1, int(rel.max()).bit_length())
    if bw > 32:
        return None
    n_frames = per // frame
    if bw * per + n_frames * 32 >= values.dtype.itemsize * 8 * per:
        return None  # no shrink over the raw dtype
    return mins.astype(np.int32), rel, bw, frame


def encode_for(data: np.ndarray
               ) -> Optional[tuple[np.ndarray, np.ndarray, int, int]]:
    """FOR-encode an integer array: (mins (n_frames,) int32, packed
    words, bit_width, frame), or None when the guards reject."""
    if data.ndim != 1:
        return None
    plan = _for_plan(data.reshape(1, -1))
    if plan is None:
        return None
    mins, rel, bw, frame = plan
    return mins[0], pack_bits_host(rel[0], bw), bw, frame


# -- per-column dispatch encodings ------------------------------------------

@dataclass(frozen=True)
class PredEnc:
    """Static half of one predicate column's dispatch encoding (a jit
    static argument — the traced program's structure hangs off it).

    kind: raw (dtype bytes as-is) | delta (base + packed zigzag deltas,
    sorted-ish integer dtypes) | for (per-frame mins + packed
    remainders, clustered-but-unsorted integers) | bits (bit-packed
    boolean data).
    valid_mode: none (all-valid, synthesized on device) | bits
    (bit-packed bitmap) | raw (bool bytes, the uncompressed wire).
    frame: FOR frame size (0 for every other kind).
    """

    name: str
    dtype: str
    kind: str
    bit_width: int
    valid_mode: str
    frame: int = 0


def encode_pred_column(name: str, data: np.ndarray,
                       validity: Optional[np.ndarray], n_rows: int,
                       bucket: int, encoded: bool
                       ) -> tuple[PredEnc, tuple, int]:
    """Encode one predicate column for dispatch.

    Returns (spec, host arrays ready for H2D, raw_equiv_bytes — what the
    uncompressed wire would have shipped).  Data pads to the bucket with
    its edge value (keeps delta widths narrow); validity pads False, so
    padded rows never pass the predicate regardless of data padding.
    """
    raw_equiv = bucket * data.dtype.itemsize + bucket  # data + bool bitmap
    if bucket != n_rows:
        data = np.pad(data, (0, bucket - n_rows),
                      mode="edge" if n_rows else "constant")
        if validity is not None:
            validity = np.pad(validity, (0, bucket - n_rows))
    if not encoded:
        if validity is None:
            validity = np.ones(bucket, dtype=np.bool_)
        spec = PredEnc(name, str(data.dtype), "raw", 0, "raw")
        return spec, (data, validity), raw_equiv
    if validity is None:
        valid_mode, val_arrays = "none", ()
    else:
        valid_mode, val_arrays = "bits", (encode_validity(validity),)
    if data.dtype == np.bool_:
        spec = PredEnc(name, str(data.dtype), "bits", 1, valid_mode)
        return (spec, (encode_validity(data),) + val_arrays, raw_equiv)
    delta = encode_delta(data)
    if delta is not None:
        base, words, bw = delta
        spec = PredEnc(name, str(data.dtype), "delta", bw, valid_mode)
        return (spec, (words, np.int32(base)) + val_arrays, raw_equiv)
    forenc = encode_for(data)
    if forenc is not None:
        mins, words, bw, frame = forenc
        spec = PredEnc(name, str(data.dtype), "for", bw, valid_mode,
                       frame)
        return (spec, (words, mins) + val_arrays, raw_equiv)
    spec = PredEnc(name, str(data.dtype), "raw", 0, valid_mode)
    return spec, (data,) + val_arrays, raw_equiv


def decode_pred_device(spec: PredEnc, arrays, bucket: int):
    """Traced device decode of one encoded predicate column — runs
    INSIDE the fused jitted program (spec is static there), emitting
    the (data, validity) pair predicate/device.compile_mask_jnp eats."""
    import jax.numpy as jnp

    from transferia_tpu.ops.decode import (
        delta_prefix_sum,
        for_frame_decode,
        unpack_validity,
    )

    if spec.kind == "raw":
        data = arrays[0]
    elif spec.kind == "bits":
        data = unpack_validity(arrays[0], bucket)
    elif spec.kind == "for":
        data = for_frame_decode(arrays[0], arrays[1], spec.bit_width,
                                spec.frame, bucket
                                ).astype(np.dtype(spec.dtype))
    else:  # delta
        data = delta_prefix_sum(arrays[0], arrays[1], spec.bit_width,
                                bucket).astype(np.dtype(spec.dtype))
    if spec.valid_mode == "none":
        valid = jnp.ones(bucket, dtype=jnp.bool_)
    elif spec.valid_mode == "bits":
        valid = unpack_validity(arrays[-1], bucket)
    else:
        valid = arrays[-1]
    return data, valid


# -- per-shard (mesh) dispatch encodings -------------------------------------
#
# The mesh wire ships every array with a leading device axis: each
# device's contiguous row chunk encodes INDEPENDENTLY (a delta prefix
# sum or a packed bitmap cannot span a shard boundary — each shard
# decodes alone inside shard_map), with one uniform bit width across
# shards so the decode program stays static.  parallel/fusedmesh.py is
# the only consumer.

def encode_validity_sharded(valid2d: np.ndarray) -> np.ndarray:
    """(n_dev, per_dev) bool -> (n_dev, W) packed little-endian uint32
    bitmap words, each shard packed independently."""
    packed = np.packbits(np.ascontiguousarray(valid2d, dtype=np.uint8),
                         axis=1, bitorder="little")
    pad = (-packed.shape[1]) % 4
    if pad:
        packed = np.pad(packed, ((0, 0), (0, pad)))
    return np.ascontiguousarray(packed).view(np.uint32)


def _encode_delta_sharded(d2: np.ndarray
                          ) -> Optional[tuple[np.ndarray, np.ndarray,
                                              int]]:
    """Per-shard delta+bit-pack: (bases (n_dev,) int32, words (n_dev,
    W), bit_width) or None when the shared `_delta_plan` guards reject
    (int32-exact values, <= 30-bit zigzag deltas, must shrink) — one
    uniform bit width across shards keeps the decode program static."""
    plan = _delta_plan(d2)
    if plan is None:
        return None
    bases, zz, bw = plan
    words = np.stack([pack_bits_host(row, bw) for row in zz])
    return bases, words, bw


def _encode_for_sharded(d2: np.ndarray
                        ) -> Optional[tuple[np.ndarray, np.ndarray,
                                            int, int]]:
    """Per-shard frame-of-reference pack: (mins (n_dev, n_frames)
    int32, words (n_dev, W), bit_width, frame) or None when the shared
    `_for_plan` guards reject — frames never span a shard boundary
    (per_dev is a bucket, a multiple of the frame size) and one
    uniform bit width across shards keeps the decode static."""
    plan = _for_plan(d2)
    if plan is None:
        return None
    mins, rel, bw, frame = plan
    words = np.stack([pack_bits_host(row, bw) for row in rel])
    return mins, words, bw, frame


def encode_pred_column_sharded(name: str, data: np.ndarray,
                               validity: Optional[np.ndarray],
                               n_rows: int, n_dev: int, per_dev: int,
                               encoded: bool
                               ) -> tuple[PredEnc, tuple, int]:
    """Encode one predicate column for the mesh wire.

    Returns (spec, arrays each with a leading (n_dev, ...) device
    axis, raw_equiv_bytes).  Padding matches encode_pred_column: data
    pads with its edge value (keeps delta widths narrow), validity
    pads False so padded rows never pass the predicate."""
    total = n_dev * per_dev
    raw_equiv = total * data.dtype.itemsize + total  # data + bool map
    if total != n_rows:
        data = np.pad(data, (0, total - n_rows),
                      mode="edge" if n_rows else "constant")
        if validity is not None:
            validity = np.pad(validity, (0, total - n_rows))
    d2 = data.reshape(n_dev, per_dev)
    v2 = (validity.reshape(n_dev, per_dev)
          if validity is not None else None)
    if not encoded:
        if v2 is None:
            v2 = np.ones((n_dev, per_dev), dtype=np.bool_)
        return (PredEnc(name, str(data.dtype), "raw", 0, "raw"),
                (d2, v2), raw_equiv)
    if v2 is None:
        valid_mode: str = "none"
        val_arrays: tuple = ()
    else:
        valid_mode = "bits"
        val_arrays = (encode_validity_sharded(v2),)
    if data.dtype == np.bool_:
        spec = PredEnc(name, str(data.dtype), "bits", 1, valid_mode)
        return spec, (encode_validity_sharded(d2),) + val_arrays, \
            raw_equiv
    delta = _encode_delta_sharded(d2)
    if delta is not None:
        bases, words, bw = delta
        spec = PredEnc(name, str(data.dtype), "delta", bw, valid_mode)
        return spec, (words, bases) + val_arrays, raw_equiv
    forenc = _encode_for_sharded(d2)
    if forenc is not None:
        mins, words, bw, frame = forenc
        spec = PredEnc(name, str(data.dtype), "for", bw, valid_mode,
                       frame)
        return spec, (words, mins) + val_arrays, raw_equiv
    spec = PredEnc(name, str(data.dtype), "raw", 0, valid_mode)
    return spec, (d2,) + val_arrays, raw_equiv


def decode_pred_device_sharded(spec: PredEnc, arrays, bucket: int):
    """Per-device decode of one mesh-encoded predicate column — runs
    inside shard_map, where every array arrives as the local (1, ...)
    shard; strip the shard axis and reuse the single-device decode."""
    return decode_pred_device(spec, tuple(a[0] for a in arrays), bucket)


# -- H2D staging -------------------------------------------------------------

def stage_h2d(arrays, raw_equiv_bytes: int, what: str = "batch",
              put: bool = True):
    """Stage a pytree of host arrays for upload — THE single H2D point
    of the compressed dispatch plane: chaos's `dispatch.h2d` failpoint
    and the encoded-vs-raw byte accounting both live here, so every
    encoded transfer is injectable and audited.

    put=True (default) device_puts eagerly (async) so a pipelined
    caller controls when the transfer enqueues; put=False returns the
    host arrays unchanged for callers whose jit does its own placement
    (the mesh-sharded program: an eager put would land everything on
    one device and force a reshard hop)."""
    import jax

    from transferia_tpu.chaos.failpoints import failpoint

    failpoint("dispatch.h2d")
    leaves = jax.tree_util.tree_leaves(arrays)
    encoded = sum(int(getattr(a, "nbytes", 0)) for a in leaves)
    with trace.span("device_decode", encoded_bytes=encoded,
                    raw_equiv_bytes=int(raw_equiv_bytes), what=what):
        dev = (jax.tree_util.tree_map(jax.device_put, arrays)
               if put else arrays)
    TELEMETRY.record_dispatch(encoded, int(raw_equiv_bytes))
    return dev


# -- device-resident dict-pool masking --------------------------------------

def device_hmac_dict_pool(key: bytes, pool, n_rows: int):
    """HMAC a DictPool's values ON DEVICE, once per (pool, key).

    Returns the hexed pool (a DictPool of 64-char hex digests with the
    null sentinel emptied), memoized on the shared pool under the SAME
    memo key as the host path (transform/plugins/mask.mask_dict_column)
    — whichever strategy touches a pool first pays; the other rides the
    memo.  Row codes never cross the link: the caller keeps them and
    rebinds them to the hexed pool.

    Returns None when the pool is too large to pay for itself on this
    batch (mirrors the host-path economics) — the caller then hashes
    the referenced value subset on the HOST (mask_dict_column), still
    dict-encoded: zero link bytes either way, which is exactly what
    DeviceFusedStep._estimate_link_bytes charges for a rejected pool.
    """
    memo_key = ("hmac_hex", key)
    hexed = pool.memo_get(memo_key)
    if hexed is not None:
        TELEMETRY.record_pool_hit()
        _record_avoided_batch_bytes(pool, n_rows)
        return hexed
    if pool.n_values > 2 * max(n_rows, 1):
        return None
    with _pool_hash_lock:
        return _hash_pool_locked(key, pool, n_rows, memo_key)


def _hash_pool_locked(key: bytes, pool, n_rows: int, memo_key):
    # double-checked: a racing part thread may have hashed this pool
    # while we waited on the lock
    hexed = pool.memo_get(memo_key)
    if hexed is not None:
        TELEMETRY.record_pool_hit()
        _record_avoided_batch_bytes(pool, n_rows)
        return hexed
    from transferia_tpu.columnar.hexcol import (
        digests_to_hex,
        hex_to_varwidth,
    )
    from transferia_tpu.transform.plugins.mask import hexed_pool_from_flat

    digest_rows = _pool_digest_rows_locked(key, pool)
    hex_mat = digests_to_hex(digest_rows)
    flat, flat_off = hex_to_varwidth(hex_mat, None)
    hexed = hexed_pool_from_flat(pool, flat, flat_off)
    pool.memo_set(memo_key, hexed)
    _record_avoided_batch_bytes(pool, n_rows)
    return hexed


def _pool_digest_rows_locked(key: bytes, pool) -> np.ndarray:
    """The (n_values, 8) uint32 HMAC digest matrix of a pool's values,
    computed ON DEVICE once per (pool, key) and memoized on the shared
    pool — the common substrate of the hexed pool (single-device mask
    route) and the mesh dict route's per-device digest gather.  Caller
    holds `_pool_hash_lock`."""
    memo_key = ("hmac_digest_rows", bytes(key))
    rows = pool.memo_get(memo_key)
    if rows is not None:
        return rows
    import jax.numpy as jnp

    from transferia_tpu.columnar.batch import bucket_rows
    from transferia_tpu.ops.fused import pack_hmac_blocks, pow2_blocks
    from transferia_tpu.ops.sha256 import (
        _hmac_inner_outer,
        _hmac_key_states,
    )

    n_vals = pool.n_values
    offsets = pool.values_offsets
    lens = offsets[1:] - offsets[:-1]
    max_len = int(lens.max()) if n_vals else 0
    mb = pow2_blocks(max_len)
    blocks, n_blocks = pack_hmac_blocks(pool.values_data, offsets, mb)
    bucket = bucket_rows(max(n_vals, 1))
    if bucket != n_vals:
        blocks = np.pad(blocks, ((0, bucket - n_vals), (0, 0)))
        n_blocks = np.pad(n_blocks, (0, bucket - n_vals))
    inner, outer = _hmac_key_states(bytes(key))
    with trace.span("pool_upload", values=n_vals,
                    bytes=int(blocks.nbytes)):
        dev_blocks, dev_nblocks = stage_h2d(
            (blocks, n_blocks),
            raw_equiv_bytes=int(blocks.nbytes) + int(n_blocks.nbytes),
            what="dict_pool")
        TELEMETRY.record_h2d(int(blocks.nbytes) + int(n_blocks.nbytes))
        digests = _hmac_inner_outer(
            dev_blocks, dev_nblocks,
            (jnp.asarray(inner[0]), jnp.asarray(outer[0])), mb)
        TELEMETRY.record_launch()
        digest_rows = np.ascontiguousarray(np.asarray(digests)[:n_vals])
    TELEMETRY.record_d2h(int(digests.nbytes))
    TELEMETRY.record_pool_upload()
    pool.memo_set(memo_key, digest_rows)
    return digest_rows


def device_hmac_pool_digests(key: bytes, pool, n_rows: int
                             ) -> Optional[np.ndarray]:
    """The memoized (n_values, 8) uint32 digest matrix for the MESH
    dict route (parallel/fusedmesh.py): the sharded program gathers
    per-row digest words from it by int32 code — byte-identical to
    HMAC'ing each row's flat bytes, because equal bytes hash equal and
    the pool's null sentinel is the same empty-bytes entry the flat
    wire ships for null rows.  None when the pool is too large to pay
    for itself on this batch (same economics as the hexed-pool route:
    the caller falls back to the flat block wire)."""
    memo_key = ("hmac_digest_rows", bytes(key))
    rows = pool.memo_get(memo_key)
    if rows is not None:
        TELEMETRY.record_pool_hit()
        return rows
    if pool.n_values > 2 * max(n_rows, 1):
        return None
    with _pool_hash_lock:
        return _pool_digest_rows_locked(bytes(key), pool)


def _record_avoided_batch_bytes(pool, n_rows: int) -> None:
    """Credit the dispatch accounting with the per-batch bytes the raw
    wire WOULD have shipped for a pool-routed column: the bucket-padded
    SHA block matrix plus per-row block counts.  (Block width estimated
    from the pool's longest value — the per-row materialized max the
    raw path would use is bounded by it.)"""
    from transferia_tpu.columnar.batch import bucket_rows
    from transferia_tpu.ops.fused import pow2_blocks

    offs = pool.values_offsets
    lens = offs[1:] - offs[:-1]
    max_len = int(lens.max()) if pool.n_values else 0
    mb = pow2_blocks(max_len)
    bucket = bucket_rows(max(n_rows, 1))
    TELEMETRY.record_dispatch(0, (mb * 64 + 4) * bucket)
