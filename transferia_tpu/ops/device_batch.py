"""Device-side batch utilities: bucketing, padding, var-width packing.

Shape discipline: XLA compiles one program per distinct shape.  The batch
row count is padded up to a standard bucket (columnar.bucket_rows) with a
validity tail-mask, and var-width columns pack into fixed-width byte
matrices bucketed by max row length — so the number of compiled programs is
bounded by (schema fingerprint x row bucket x width bucket).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from transferia_tpu.columnar.batch import Column, ColumnBatch, bucket_rows

_WIDTH_BUCKETS = (8, 16, 32, 64, 128, 256, 1024, 4096)


def bucket_width(w: int) -> int:
    for b in _WIDTH_BUCKETS:
        if w <= b:
            return b
    top = _WIDTH_BUCKETS[-1]
    return ((w + top - 1) // top) * top


def pad_to_bucket(arr: np.ndarray, n_rows: int
                  ) -> tuple[np.ndarray, int]:
    """Pad axis 0 to the row bucket; returns (padded, bucket)."""
    bucket = bucket_rows(n_rows)
    if arr.shape[0] == bucket:
        return arr, bucket
    pad = [(0, bucket - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad), bucket


def pack_varwidth_matrix(col: Column,
                         width: Optional[int] = None
                         ) -> tuple[np.ndarray, np.ndarray]:
    """Flat bytes+offsets -> (N, W) fixed-width byte matrix + (N,) lengths.

    Rows longer than W are truncated (callers pick W >= max length via
    bucket_width).  Vectorized gather, no per-row Python.
    """
    n = col.n_rows
    offsets = col.offsets
    lens = (offsets[1:] - offsets[:-1]).astype(np.int64)
    max_len = int(lens.max()) if n else 0
    w = width if width is not None else bucket_width(max(max_len, 1))
    out = np.zeros((n, w), dtype=np.uint8)
    if len(col.data) and n:
        cols = np.arange(w)
        mask = cols[None, :] < np.minimum(lens, w)[:, None]
        src = (offsets[:-1, None].astype(np.int64) + cols[None, :]) * mask
        out = np.where(mask, col.data[src], 0).astype(np.uint8)
    return out, np.minimum(lens, w).astype(np.int32)


def unpack_varwidth_matrix(matrix: np.ndarray, lens: np.ndarray) -> Column:
    """Inverse of pack_varwidth_matrix (for round-trips in tests/sinks)."""
    from transferia_tpu.abstract.schema import CanonicalType
    from transferia_tpu.columnar.batch import _offsets_from_lengths

    n = matrix.shape[0]
    offsets = _offsets_from_lengths(lens.astype(np.int64))
    out = np.zeros(int(offsets[-1]), dtype=np.uint8)
    cols = np.arange(matrix.shape[1])
    mask = cols[None, :] < lens[:, None]
    flat_dst = (offsets[:-1, None] + cols[None, :])[mask]
    out[flat_dst] = matrix[mask]
    return Column("packed", CanonicalType.STRING, out, offsets)
