"""Event model v2 (reference: pkg/abstract2/ "Base" — transfer.go:14-263).

The reference grew a second, event-typed dataplane (Event/EventBatch,
EventSource/EventTarget, Snapshot/ReplicationProvider) used by its delta
and CH a2 providers.  Here that surface is a thin, typed veneer over the
primary currency — columnar batches ARE the event batches — so a2-style
providers plug in without a parallel pipeline:

  InsertBatchEvent        one ColumnBatch of inserts
  RowEvents               heterogeneous ChangeItem runs
  TableLoadEvent          Init/Done control markers
  EventSource/EventTarget pipeline contracts (pipeline.py) with v1
                          bridges both ways, Snapshot/Replication
                          providers, progressable sources
"""

from transferia_tpu.events.pipeline import (
    AsyncSinkOverEventTarget,
    DataObjectPart,
    DataProvider,
    EventSource,
    EventSourceProgress,
    EventTarget,
    EventTargetOverAsyncSink,
    LogPosition,
    ProgressableEventSource,
    ReplicationProvider,
    SnapshotProvider,
    StorageSnapshotSource,
)
from transferia_tpu.events.model import (
    Event,
    EventBatch,
    InsertBatchEvent,
    RawItems,
    RowEvents,
    TableLoadEvent,
    batch_to_events,
    events_to_batches,
)

__all__ = [
    "AsyncSinkOverEventTarget",
    "DataObjectPart",
    "DataProvider",
    "Event",
    "EventSource",
    "EventSourceProgress",
    "EventTarget",
    "EventTargetOverAsyncSink",
    "LogPosition",
    "ProgressableEventSource",
    "ReplicationProvider",
    "SnapshotProvider",
    "StorageSnapshotSource",
    "EventBatch",
    "InsertBatchEvent",
    "RawItems",
    "RowEvents",
    "TableLoadEvent",
    "batch_to_events",
    "events_to_batches",
]
