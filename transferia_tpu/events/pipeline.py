"""Event-model-v2 pipeline contracts: sources, targets, data providers.

Reference parity: pkg/abstract2/transfer.go —
- EventSource / ProgressableEventSource / EventSourceProgress (:151-196),
- EventTarget (:201, the a2 AsyncSink),
- DataProvider / SnapshotProvider / ReplicationProvider (:206-263),
- DataObjectPart and the legacy bridges (SupportsOldChangeItem,
  DataObjectsToTableParts at :225).

Both directions bridge to the v1 dataplane so every existing middleware,
sink, and storage composes with a2 components:
`EventTargetOverAsyncSink` makes any v1 sink pipeline an a2 target, and
`AsyncSinkOverEventTarget` mounts a native a2 target at the end of the v1
middleware stack.
"""

from __future__ import annotations

import abc
import concurrent.futures
import threading
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

from transferia_tpu.abstract.interfaces import AsyncSink, resolve_all
from transferia_tpu.abstract.schema import TableID, TableSchema
from transferia_tpu.abstract.table import TableDescription
from transferia_tpu.events.model import (
    Event,
    InsertBatchEvent,
    RawItems,
    RowEvents,
    batch_to_events,
    events_to_batches,
)


def _event_rows(ev: Event) -> int:
    """Row count of one event, across all event shapes."""
    if isinstance(ev, InsertBatchEvent):
        return ev.row_count()
    if isinstance(ev, (RowEvents, RawItems)):
        return sum(1 for it in ev.items if it.is_row_event())
    return 0


@dataclass(frozen=True)
class LogPosition:
    """Comparable replication position (transfer.go:116 LogPosition; the
    SupportsOldLSN bridge is the `lsn` field)."""

    lsn: int = 0
    label: str = ""

    def __str__(self) -> str:
        return self.label or str(self.lsn)

    def compare(self, other: "LogPosition") -> int:
        return (self.lsn > other.lsn) - (self.lsn < other.lsn)


@dataclass
class EventSourceProgress:
    """transfer.go:168 EventSourceProgress."""

    done: bool = False
    current: int = 0
    total: int = 0


class EventTarget(abc.ABC):
    """a2 sink (transfer.go:201): async push of typed event batches."""

    @abc.abstractmethod
    def async_push(self, events: Sequence[Event]
                   ) -> "concurrent.futures.Future[None]":
        ...

    def close(self) -> None:
        ...


class EventSource(abc.ABC):
    """transfer.go:151: a running producer feeding one EventTarget."""

    @abc.abstractmethod
    def start(self, target: EventTarget) -> None:
        """Run to completion (snapshot) or until stop() (replication)."""

    def stop(self) -> None:
        ...

    def running(self) -> bool:
        return False


class ProgressableEventSource(EventSource):
    """transfer.go:163: finite sources report progress."""

    @abc.abstractmethod
    def progress(self) -> EventSourceProgress:
        ...


@dataclass(frozen=True)
class DataObjectPart:
    """One loadable slice of a data object (a file, a shard, a range).

    `to_table_part` is the legacy bridge (part -> v1 TableDescription);
    the `filter` carries the part identity so the reverse mapping
    (TablePartToDataObjectPart) is lossless."""

    table: TableID
    part_key: str = ""
    eta_rows: int = 0

    def to_table_part(self) -> TableDescription:
        return TableDescription(id=self.table, filter=self.part_key,
                                eta_rows=self.eta_rows)


class DataProvider(abc.ABC):
    """transfer.go:206."""

    def init(self) -> None:
        ...

    def ping(self) -> None:
        ...

    def close(self) -> None:
        ...


class SnapshotProvider(DataProvider):
    """transfer.go:212: snapshot via data objects and per-part sources."""

    def begin_snapshot(self) -> None:
        ...

    @abc.abstractmethod
    def data_objects(self, include: Optional[list[TableID]] = None
                     ) -> dict[TableID, list[DataObjectPart]]:
        ...

    @abc.abstractmethod
    def table_schema(self, part: DataObjectPart) -> TableSchema:
        ...

    @abc.abstractmethod
    def create_snapshot_source(self, part: DataObjectPart
                               ) -> ProgressableEventSource:
        ...

    def end_snapshot(self) -> None:
        ...

    # -- legacy bridges (transfer.go:224-231) -------------------------------
    def data_objects_to_table_parts(
            self, include: Optional[list[TableID]] = None
    ) -> list[TableDescription]:
        return [
            part.to_table_part()
            for parts in self.data_objects(include).values()
            for part in parts
        ]

    def table_part_to_data_object_part(
            self, td: TableDescription) -> DataObjectPart:
        return DataObjectPart(table=td.id, part_key=td.filter,
                              eta_rows=td.eta_rows)


class ReplicationProvider(DataProvider):
    """transfer.go:263."""

    @abc.abstractmethod
    def create_replication_source(self) -> EventSource:
        ...


# ---------------------------------------------------------------------------
# v1 <-> v2 bridges


class EventTargetOverAsyncSink(EventTarget):
    """Any v1 async sink pipeline as an a2 target: events lower to the
    primary batch currency in order, one future resolves them all.

    A single shared waiter thread services every push — per-push waiter
    threads would pile up against buffered sinks whose futures only
    resolve at flush."""

    def __init__(self, sink: AsyncSink):
        self.sink = sink
        self._waiter = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="a2-bridge-wait")

    def async_push(self, events: Sequence[Event]
                   ) -> "concurrent.futures.Future[None]":
        futures = [self.sink.async_push(b)
                   for b in events_to_batches(events)]
        if not futures:
            done: concurrent.futures.Future = concurrent.futures.Future()
            done.set_result(None)
            return done
        if len(futures) == 1:
            return futures[0]
        return self._waiter.submit(resolve_all, futures)

    def close(self) -> None:
        try:
            self.sink.close()
        finally:
            self._waiter.shutdown(wait=True)


class AsyncSinkOverEventTarget(AsyncSink):
    """A native a2 target mounted at the end of the v1 middleware stack:
    batches lift to typed events (transfer.go SupportsOldChangeItem in
    reverse)."""

    def __init__(self, target: EventTarget):
        self.target = target

    def async_push(self, batch):
        return self.target.async_push(batch_to_events(batch))

    def close(self) -> None:
        self.target.close()


class StorageSnapshotSource(ProgressableEventSource):
    """A v1 Storage part read as a ProgressableEventSource — the default
    a2 snapshot source for providers whose native currency is the v1
    Storage contract."""

    def __init__(self, storage, part: DataObjectPart,
                 total_rows: int = 0):
        self.storage = storage
        self.part = part
        self._progress = EventSourceProgress(
            total=total_rows or part.eta_rows)
        self._running = False
        self._stop = threading.Event()

    def start(self, target: EventTarget) -> None:
        self._running = True
        futures = []
        try:
            def pusher(batch):
                if self._stop.is_set():
                    raise RuntimeError("snapshot source stopped")
                events = batch_to_events(batch)
                futures.append(target.async_push(events))
                self._progress.current += sum(
                    _event_rows(e) for e in events)

            self.storage.load_table(self.part.to_table_part(), pusher)
            resolve_all(futures)
            self._progress.done = True
        finally:
            self._running = False

    def stop(self) -> None:
        self._stop.set()

    def running(self) -> bool:
        return self._running

    def progress(self) -> EventSourceProgress:
        return self._progress
