"""Event-typed veneer over the columnar dataplane."""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Iterable, Iterator, Sequence, Union

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.interfaces import Batch, is_columnar
from transferia_tpu.abstract.kinds import Kind
from transferia_tpu.abstract.schema import TableID
from transferia_tpu.columnar.batch import ColumnBatch


class Event(abc.ABC):
    """One typed dataplane event (abstract2/transfer.go:14)."""

    @abc.abstractmethod
    def table(self) -> TableID:
        ...


@dataclass
class InsertBatchEvent(Event):
    """A columnar block of inserts — the snapshot hot-path event."""

    batch: ColumnBatch

    def table(self) -> TableID:
        return self.batch.table_id

    def row_count(self) -> int:
        return self.batch.n_rows


@dataclass
class RowEvents(Event):
    """Heterogeneous CDC rows sharing a table."""

    items: list[ChangeItem]

    def table(self) -> TableID:
        return self.items[0].table_id if self.items else TableID("", "")


@dataclass
class TableLoadEvent(Event):
    """Init/Done table-load control marker (carries the table schema so
    sinks can create tables from Init events)."""

    table_id: TableID
    kind: Kind
    part_id: str = ""
    schema: object = None  # Optional[TableSchema]

    def table(self) -> TableID:
        return self.table_id

    @property
    def is_done(self) -> bool:
        return self.kind in (Kind.DONE_TABLE_LOAD,
                             Kind.DONE_SHARDED_TABLE_LOAD)


@dataclass
class RawItems(Event):
    """Non-row, non-control items (DDL/truncate/provider-specific kinds) —
    passed through verbatim so the veneer round trip is lossless."""

    items: list[ChangeItem]

    def table(self) -> TableID:
        return self.items[0].table_id if self.items else TableID("", "")


# EventBatch = ordered sequence of events (abstract2 EventBatch iterator)
EventBatch = Sequence[Event]


def batch_to_events(batch: Batch) -> list[Event]:
    """Primary-currency batch -> typed events."""
    if is_columnar(batch):
        return [InsertBatchEvent(batch)]
    out: list[Event] = []
    run: list[ChangeItem] = []
    for it in batch:
        if it.is_row_event():
            run.append(it)
            continue
        if run:
            out.append(RowEvents(run))
            run = []
        if it.kind.is_control:
            out.append(TableLoadEvent(it.table_id, it.kind, it.part_id,
                                      it.table_schema))
        else:
            # DDL/truncate/provider kinds: lossless passthrough
            out.append(RawItems([it]))
    if run:
        out.append(RowEvents(run))
    return out


def events_to_batches(events: Iterable[Event]) -> Iterator[Batch]:
    """Typed events -> pushable batches (order preserved)."""
    from transferia_tpu.abstract.change_item import _control

    for ev in events:
        if isinstance(ev, InsertBatchEvent):
            yield ev.batch
        elif isinstance(ev, RowEvents):
            yield ev.items
        elif isinstance(ev, RawItems):
            yield ev.items
        elif isinstance(ev, TableLoadEvent):
            yield [_control(ev.kind, ev.table_id, ev.schema, ev.part_id)]
        else:
            raise TypeError(f"unknown event {type(ev).__name__}")
