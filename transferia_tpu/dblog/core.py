"""DBLog watermark snapshot engine."""

from __future__ import annotations

import abc
import enum
import logging
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.interfaces import Batch, is_columnar
from transferia_tpu.abstract.schema import TableID
from transferia_tpu.columnar.batch import ColumnBatch

logger = logging.getLogger(__name__)


class WatermarkKind(str, enum.Enum):
    LOW = "low"
    HIGH = "high"
    SUCCESS = "success"   # snapshot finished
    BAD = "bad"           # snapshot aborted


@dataclass(frozen=True)
class Watermark:
    id: str
    kind: WatermarkKind


class SignalTable(abc.ABC):
    """Writes watermarks into the source so they appear in its CDC stream
    (dblog/signal_table.go:32).  The caller generates the Watermark (and
    registers it as expected) BEFORE the write — a fast CDC echo must not
    race the registration."""

    @abc.abstractmethod
    def write_watermark(self, wm: Watermark) -> None:
        ...

    @abc.abstractmethod
    def is_watermark(self, item: ChangeItem) -> Optional[Watermark]:
        """Recognize a CDC event as one of our watermarks."""


class ChunkIterator(abc.ABC):
    """PK-ordered chunk reader (dblog/incremental_iterator.go:209-320)."""

    @abc.abstractmethod
    def next_chunk(self) -> Optional[ColumnBatch]:
        """Next chunk past the internal cursor; None when exhausted."""


class DBLogSnapshot:
    """Drives chunked snapshot concurrent with a replication stream.

    The replication pipeline pushes through `filter_cdc`; the snapshot
    loop calls `run`.  Between a LOW and HIGH watermark pair, primary keys
    seen in CDC events mark chunk rows stale (the live event supersedes the
    chunk copy) — dblog/incremental_async_sink.go:14-207.

    ALL data (chunks included) reaches the target through the CDC
    pipeline's pushes of `filter_cdc` output: each chunk is emitted inline
    at its HIGH watermark's stream position, so a chunk row can never
    trail a newer CDC event for the same key into an arrival-ordered sink.
    The snapshot thread itself pushes nothing.
    """

    def __init__(self, signal: SignalTable, chunks: ChunkIterator,
                 key_columns: Sequence[str]):
        self.signal = signal
        self.chunks = chunks
        self.key_columns = list(key_columns)
        self._lock = threading.Lock()
        self._window_open = False
        self._touched: set[tuple] = set()
        self._expected: dict[str, WatermarkKind] = {}
        self._pending_chunk: Optional[ColumnBatch] = None
        self._emitted_rows = 0
        self._events = {
            WatermarkKind.LOW: threading.Event(),
            WatermarkKind.HIGH: threading.Event(),
        }

    # -- CDC side -----------------------------------------------------------
    def filter_cdc(self, batch: Batch) -> Batch:
        """Intercept the replication stream: consume watermarks, record
        touched PKs while a chunk window is open.  Returns the batch minus
        watermark rows, PLUS the deduped pending chunk emitted inline at
        the HIGH watermark's stream position — chunk rows must never trail
        a newer CDC event for the same key into an arrival-ordered sink
        (reference: incremental_async_sink.go:167 serializes exactly so)."""
        items = batch.to_rows() if is_columnar(batch) else list(batch)
        # fast path: a lone HIGH watermark with an untouched chunk (the
        # common quiet-table case) passes the chunk through columnar
        if len(items) == 1 and items[0].is_row_event():
            wm = self.signal.is_watermark(items[0])
            if wm is not None:
                emit = self._on_watermark(wm)
                return emit if emit is not None else []
        modified = False
        out = []
        for it in items:
            wm = self.signal.is_watermark(it) if it.is_row_event() else None
            if wm is not None:
                modified = True  # watermark removed (and maybe chunk added)
                emit = self._on_watermark(wm)
                if emit is None:
                    continue
                out.extend(emit.to_rows() if is_columnar(emit) else emit)
                continue
            with self._lock:
                if self._window_open and it.is_row_event():
                    self._touched.add(
                        (it.table_id, it.effective_key())
                    )
            out.append(it)
        if is_columnar(batch) and not modified:
            return batch  # untouched: keep columnar
        return out

    def _on_watermark(self, wm: Watermark):
        """Consume a watermark; on HIGH, return the pending chunk (deduped
        against keys touched inside the window) for the caller to emit
        inline at this stream position.  Returns None (nothing to emit),
        a ColumnBatch (untouched chunk — columnar fast path), or a list of
        ChangeItems (deduped rows)."""
        expected = self._expected.pop(wm.id, None)
        if expected is None or expected != wm.kind:
            logger.warning("unexpected watermark %s", wm)
            return None
        emit = None
        with self._lock:
            if wm.kind == WatermarkKind.LOW:
                self._window_open = True
                self._touched.clear()
            elif wm.kind == WatermarkKind.HIGH:
                self._window_open = False
                chunk = self._pending_chunk
                self._pending_chunk = None
                if chunk is not None and chunk.n_rows:
                    if not self._touched:
                        emit = chunk  # columnar, no per-row work
                        self._emitted_rows = chunk.n_rows
                    else:
                        rows = chunk.to_rows()
                        emit = [
                            it for it in rows
                            if (it.table_id, it.effective_key())
                            not in self._touched
                        ]
                        if len(emit) < len(rows):
                            logger.info(
                                "dblog chunk: %d rows deduped against "
                                "live events", len(rows) - len(emit),
                            )
                        self._emitted_rows = len(emit)
                else:
                    self._emitted_rows = 0
        self._events[wm.kind].set()
        return emit

    # -- snapshot side ------------------------------------------------------
    def _write_and_wait(self, kind: WatermarkKind,
                        timeout: float = 30.0) -> None:
        self._events[kind].clear()
        wm = Watermark(id=uuid.uuid4().hex, kind=kind)
        self._expected[wm.id] = kind   # register BEFORE writing
        self.signal.write_watermark(wm)
        if not self._events[kind].wait(timeout):
            raise TimeoutError(
                f"{kind.value} watermark {wm.id} not observed in the CDC "
                f"stream within {timeout}s — is replication running?"
            )

    def run(self, chunk_timeout: float = 30.0) -> int:
        """Snapshot all chunks; returns rows pushed.

        Chunks are not pushed from this thread: each chunk is parked as
        pending BEFORE its HIGH watermark is written, and the CDC pipeline
        emits it inline when it consumes that watermark (filter_cdc) —
        serializing chunk rows against newer live events."""
        total = 0
        try:
            while True:
                self._write_and_wait(WatermarkKind.LOW, chunk_timeout)
                chunk = self.chunks.next_chunk()
                with self._lock:
                    self._pending_chunk = chunk
                self._write_and_wait(WatermarkKind.HIGH, chunk_timeout)
                if chunk is None or chunk.n_rows == 0:
                    break
                with self._lock:
                    total += self._emitted_rows
            self.signal.write_watermark(
                Watermark(uuid.uuid4().hex, WatermarkKind.SUCCESS)
            )
            return total
        except BaseException:
            self.signal.write_watermark(
                Watermark(uuid.uuid4().hex, WatermarkKind.BAD)
            )
            raise


# ---------------------------------------------------------------------------
# Generic implementations
# ---------------------------------------------------------------------------

SIGNAL_TABLE = TableID("", "__transferia_signal")


class StorageSignalTable(SignalTable):
    """Signal table over a writer callback (DB providers supply an INSERT
    into their __transferia_signal table; the CDC stream echoes it)."""

    def __init__(self, write_fn: Callable[[str, str], None],
                 table: TableID = SIGNAL_TABLE):
        self.write_fn = write_fn
        self.table = table

    def write_watermark(self, wm: Watermark) -> None:
        self.write_fn(wm.id, wm.kind.value)

    def is_watermark(self, item: ChangeItem) -> Optional[Watermark]:
        if item.table_id != self.table:
            return None
        vals = item.as_dict()
        try:
            return Watermark(id=vals["mark_id"],
                             kind=WatermarkKind(vals["kind"]))
        except (KeyError, ValueError):
            return None


class PagedChunkIterator(ChunkIterator):
    """Cursor-paged chunks over a load callback.

    load_fn(last_key or None, limit) -> ColumnBatch (PK-ordered).
    """

    def __init__(self, load_fn, key_column: str, chunk_rows: int = 10_000):
        self.load_fn = load_fn
        self.key_column = key_column
        self.chunk_rows = chunk_rows
        self._cursor: Optional[Any] = None
        self._done = False

    def next_chunk(self) -> Optional[ColumnBatch]:
        if self._done:
            return None
        chunk = self.load_fn(self._cursor, self.chunk_rows)
        if chunk is None or chunk.n_rows == 0:
            self._done = True
            return None
        col = chunk.column(self.key_column)
        self._cursor = col.value(chunk.n_rows - 1)
        if chunk.n_rows < self.chunk_rows:
            self._done = True
        return chunk
