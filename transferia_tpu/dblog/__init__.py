"""DBLog-style incremental snapshot: chunked reads interleaved with live CDC.

Reference parity: pkg/dblog/ — SignalTable watermarks (signal_table.go:32,
354-375), IncrementalAsyncSink chunk-vs-WAL dedup
(incremental_async_sink.go:14-207), PK-paged IncrementalIterator
(incremental_iterator.go:209-320).  Used when a huge table must snapshot
while its replication stream keeps flowing, without a long-held consistent
read transaction.

Algorithm (per chunk):
  1. write LOW watermark to the signal table (appears in the WAL stream)
  2. SELECT the next chunk by PK order (past the last cursor)
  3. write HIGH watermark
  4. rows whose PKs were touched by WAL events observed between LOW and
     HIGH are dropped from the chunk (the live event is newer); the rest
     push as snapshot inserts
"""

from transferia_tpu.dblog.core import (
    ChunkIterator,
    DBLogSnapshot,
    SignalTable,
    Watermark,
    WatermarkKind,
)

__all__ = [
    "ChunkIterator",
    "DBLogSnapshot",
    "SignalTable",
    "Watermark",
    "WatermarkKind",
]
