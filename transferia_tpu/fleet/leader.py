"""Scheduler-replica leader election over the durable ticket queue.

N scheduler replicas may run the preemption/autoscale tick against one
coordinator; the decisions are idempotent but duplicated (every replica
lists the queue, every replica may race a revoke).  `LeaderLease`
elects ONE ticker by reusing the ticket lease/epoch machinery
(abstract/ticket.py) instead of inventing a lock primitive: leadership
is a claim on a well-known ticket (`__leader__`) in a side queue
(`<queue>.leader`), so

- acquisition is `claim_ticket` — atomic on all three backends (memory
  per-queue lock, filestore flock, s3 If-Match CAS), and an expired
  leader's claim is stealable exactly like a crashed worker's ticket
  (`ticket_claimable`), which IS the automatic failover;
- tenure is the ticket lease, renewed by the replica's own tick; a
  renewal scoped by (ticket, epoch) cannot resurrect a lost claim —
  after a steal the old leader's renew matches nothing, it observes 0
  and demotes itself (the "non-leaders fall back on lease expiry"
  half);
- the leader ticket is never completed: it cycles
  claimed -> (lease expiry) -> claimed forever, and the ticket-queue
  GC ignores non-terminal tickets, so election state never ages out.
"""

from __future__ import annotations

import logging
import os
import socket
import uuid
from typing import Optional

from transferia_tpu.abstract.ticket import FleetTicket
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.stats import trace

logger = logging.getLogger(__name__)

LEADER_TICKET_ID = "__leader__"


def default_replica_id() -> str:
    return (f"{socket.gethostname()}-{os.getpid()}-"
            f"{uuid.uuid4().hex[:6]}")


class LeaderLease:
    """One replica's handle on the leader election for a fleet queue.

    `ensure()` is the only call sites need: invoked once per tick, it
    renews a held lease or tries to (re)acquire, and returns whether
    THIS replica is the leader for the tick.  Not thread-safe by
    design — one lease object belongs to one tick loop."""

    def __init__(self, coordinator: Coordinator, queue: str = "fleet",
                 replica_id: Optional[str] = None):
        if not coordinator.supports_ticket_queue():
            raise ValueError(
                f"coordinator {type(coordinator).__name__} has no "
                f"durable ticket queue; leader election needs "
                f"memory/filestore/s3")
        self.cp = coordinator
        self.queue = f"{queue}.leader"
        self.replica_id = replica_id or default_replica_id()
        self._ticket: Optional[FleetTicket] = None

    # -- the per-tick decision ----------------------------------------------
    def ensure(self) -> bool:
        """Renew-or-acquire; True = this replica leads this tick."""
        if self._ticket is not None:
            if self._renew_held():
                return True
            # lease lost (expired + stolen, or a no-TTL backend where
            # renew is a no-op): fall back to the durable truth
            logger.info("replica %s lost the leader lease for %r",
                        self.replica_id, self.queue)
            trace.instant("leader_lost", queue=self.queue,
                          replica=self.replica_id)
            self._ticket = None
        return self._try_acquire()

    def _renew_held(self) -> bool:
        try:
            renewed = self.cp.renew_ticket_leases(
                self.queue, self.replica_id,
                ticket_id=LEADER_TICKET_ID,
                claim_epoch=self._ticket.claim_epoch)
        except Exception as e:
            # transient RPC fault: the lease TTL absorbs it — stay
            # leader for this tick rather than flapping the election
            logger.warning("leader lease renew failed (TTL absorbs "
                           "it): %s", e)
            return True
        if renewed:
            return True
        # a no-TTL coordinator (lease_seconds=0) renews nothing; the
        # claim itself never expires — confirm against the queue
        cur = self._current()
        return (cur is not None and cur.state == "claimed"
                and cur.claimed_by == self.replica_id
                and cur.claim_epoch == self._ticket.claim_epoch)

    def _try_acquire(self) -> bool:
        try:
            self.cp.enqueue_ticket(self.queue, FleetTicket(
                ticket_id=LEADER_TICKET_ID, transfer_id=LEADER_TICKET_ID,
                tenant="__system__", qos="interactive",
                payload={"kind": "leader_lease"}))
            got = self.cp.claim_ticket(self.queue, LEADER_TICKET_ID,
                                       self.replica_id)
        except Exception as e:
            logger.warning("leader acquisition failed: %s", e)
            return False
        if got is None:
            return False  # another live replica holds the lease
        self._ticket = got
        logger.info("replica %s is now the leader for %r (epoch %d%s)",
                    self.replica_id, self.queue, got.claim_epoch,
                    f", stolen from {got.stolen_from}"
                    if got.stolen_from else "")
        trace.instant("leader_acquired", queue=self.queue,
                      replica=self.replica_id, epoch=got.claim_epoch,
                      stolen_from=got.stolen_from or "")
        return True

    # -- introspection / shutdown -------------------------------------------
    def _current(self) -> Optional[FleetTicket]:
        try:
            return next((t for t in self.cp.list_tickets(self.queue)
                         if t.ticket_id == LEADER_TICKET_ID), None)
        except Exception as e:
            logger.warning("leader ticket read failed: %s", e)
            return None

    def is_leader(self) -> bool:
        """Advisory (as of the last ensure())."""
        return self._ticket is not None

    def leader_id(self) -> Optional[str]:
        """Who holds the lease right now (any replica may ask)."""
        cur = self._current()
        return cur.claimed_by if cur is not None \
            and cur.state == "claimed" else None

    def release(self) -> None:
        """Graceful step-down: another replica can acquire on its next
        tick instead of waiting out the lease TTL."""
        if self._ticket is None:
            return
        try:
            self.cp.release_ticket(self.queue, self._ticket)
        except Exception as e:
            logger.warning("leader release failed (lease will "
                           "expire): %s", e)
        trace.instant("leader_released", queue=self.queue,
                      replica=self.replica_id)
        self._ticket = None
