"""Fleet worker: a supervised process (or thread) that claims and runs
tickets from a durable coordinator queue.

`trtpu worker` (cli/main.py) runs one of these per process: the worker
pulls its next ticket with the shared WDRR pick (fleet/distributed.py),
claims it through the coordinator's fenced `claim_ticket`, runs the
described transfer through the REAL engine (SnapshotLoader — whose part
claims go through the part-lease machinery unchanged), and reports the
fenced completion.  Liveness is the same lease design as parts:

- a heartbeat thread renews the ticket lease every interval and folds
  the worker's phase into coordinator health; a crash (kill -9) stops
  the renewals, the lease expires, and a SURVIVOR reclaims the ticket —
  the transfer resumes from its committed parts;
- a renewal that comes back 0 while a ticket is held means the lease
  was REVOKED (preemption, fleet/distributed.py) — the worker yields at
  its next part boundary (`TransferPreemptedError` out of the loader)
  and moves on to the next pick, which is exactly the higher-priority
  arrival the revoke made room for;
- SIGTERM requests a graceful drain: same part-boundary yield, then the
  claim is released back to the queue and the process exits clean.

Tickets carry a JSON payload instead of a closure (callables can't
cross a process boundary); `RUNNERS` maps payload kinds to builders —
`sample_snapshot` (self-contained sample→memory transfers: benches,
chaos, smokes) and `transfer_yaml` (a transfer config on shared
storage).  `WorkerSupervisor` spawns/supervises workers in either
`thread` mode (tests, chaos determinism) or `process` mode (real
`trtpu worker` subprocesses) and is the actuator the elastic
autoscaler (fleet/autoscaler.py) drives.
"""

from __future__ import annotations

import logging
import subprocess
import sys
import threading
import time
from typing import Callable, Optional

from transferia_tpu.abstract.errors import (
    is_preemption,
    is_worker_kill,
)
from transferia_tpu.abstract.ticket import FleetTicket, ticket_claimable
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.fleet.distributed import (
    DEFAULT_QUEUE,
    TICKET_TRACE_KEY,
    WdrrPicker,
)
from transferia_tpu.stats import fleetobs, trace
from transferia_tpu.stats.ledger import LEDGER
from transferia_tpu.stats.registry import DistributedFleetStats, Metrics

logger = logging.getLogger(__name__)

DEFAULT_HEARTBEAT_INTERVAL = 1.0
DEFAULT_TICKET_ATTEMPTS = 3   # claims before a failing ticket is failed
COMPLETE_RPC_ATTEMPTS = 5     # retries of the fenced completion RPC


class TicketRunContext:
    """What a payload runner gets next to the ticket: the coordinator
    (part claims, state), a preemption probe the snapshot loader polls
    at part boundaries, and whether this claim is a RESUME (the ticket
    ran before — reuse the committed part queue instead of recreating
    it)."""

    def __init__(self, coordinator: Coordinator, metrics: Metrics,
                 preempted: Callable[[], bool], resume: bool,
                 worker_id: str, queue: str):
        self.coordinator = coordinator
        self.metrics = metrics
        self.preempted = preempted
        self.resume = resume
        self.worker_id = worker_id
        self.queue = queue


def _run_sample_snapshot(ticket: FleetTicket,
                         ctx: TicketRunContext) -> None:
    """Built-in payload: a sample→memory snapshot described entirely by
    the payload (rows/preset/sink/transformation) — the workload of the
    fleet bench, the chaos fleet_distributed mode, and the worker e2e
    smoke; no external services, runnable in any worker process."""
    from transferia_tpu.models import Transfer, TransferType
    from transferia_tpu.providers.memory import MemoryTargetParams
    from transferia_tpu.providers.sample import SampleSourceParams
    from transferia_tpu.tasks.snapshot import SnapshotLoader

    p = ticket.payload
    rows = int(p.get("rows", 1024))
    transfer = Transfer(
        id=ticket.transfer_id or ticket.ticket_id,
        type=TransferType.SNAPSHOT_ONLY,
        src=SampleSourceParams(
            preset=p.get("preset", "iot"),
            table=p.get("table", "events"),
            rows=rows,
            batch_rows=int(p.get("batch_rows", max(64, rows // 8))),
            shard_parts=int(p.get("shard_parts", 4))),
        dst=MemoryTargetParams(sink_id=p.get("sink_id",
                                             ticket.ticket_id)),
        transformation=p.get("transformation"),
        validation=p.get("validation"),
    )
    transfer.runtime.sharding.process_count = int(
        p.get("process_count", 1))
    SnapshotLoader(
        transfer, ctx.coordinator,
        operation_id=p.get("operation_id") or None,
        metrics=ctx.metrics, preempted=ctx.preempted,
        resume=ctx.resume,
    ).upload_tables()


def _run_transfer_yaml(ticket: FleetTicket,
                       ctx: TicketRunContext) -> None:
    """Payload: a transfer.yaml on storage every worker can reach.
    Snapshot-only — replication is an open-ended process, not a
    drainable queue item (run it under `trtpu replicate`)."""
    from transferia_tpu.cli.config import load_transfer
    from transferia_tpu.tasks.snapshot import SnapshotLoader

    transfer = load_transfer(ticket.payload["path"])
    if transfer.type.has_replication:
        raise ValueError(
            f"ticket {ticket.ticket_id}: fleet tickets run snapshot "
            f"transfers; {transfer.id} has a replication phase")
    SnapshotLoader(
        transfer, ctx.coordinator,
        operation_id=ticket.payload.get("operation_id") or None,
        metrics=ctx.metrics, preempted=ctx.preempted,
        resume=ctx.resume,
    ).upload_tables()


def _run_mvcc_compact(ticket: FleetTicket,
                      ctx: TicketRunContext) -> None:
    """Payload: `{"scope", "table", "watermark"}` (mvcc/compact.py).
    SCAVENGER maintenance over an MVCC staging store — the scope
    resolves through the process-local registry, and a miss REBUILDS
    it from the spill manifest through this worker's coordinator
    (mvcc/spill.py): any fleet worker can run the ticket.  Only when
    nothing was ever spilled does the miss raise, so the lease hands
    the ticket to the worker holding the layers."""
    from transferia_tpu.mvcc.compact import make_compact_runner
    from transferia_tpu.mvcc.store import resolve_store

    make_compact_runner(resolve_store)(ticket, ctx)


RUNNERS: dict[str, Callable[[FleetTicket, TicketRunContext], None]] = {
    "sample_snapshot": _run_sample_snapshot,
    "transfer_yaml": _run_transfer_yaml,
    "mvcc_compact": _run_mvcc_compact,
}


class FleetWorker:
    """One worker: claim loop + lease heartbeat + graceful drain."""

    def __init__(self, coordinator: Coordinator,
                 queue: str = DEFAULT_QUEUE,
                 worker_index: int = 0,
                 metrics: Optional[Metrics] = None,
                 runners: Optional[dict] = None,
                 tenant_weights: Optional[dict[str, float]] = None,
                 quantum: float = 1.0,
                 heartbeat_interval: float = DEFAULT_HEARTBEAT_INTERVAL,
                 max_attempts: int = DEFAULT_TICKET_ATTEMPTS,
                 max_tickets: int = 0,
                 idle_exit_seconds: float = 0.0,
                 part_boundary_hook: "Optional[Callable[[FleetTicket, int], None]]" = None):
        self.cp = coordinator
        self.queue = queue
        self.worker_index = worker_index
        self.worker_id = f"w{worker_index}"
        self.metrics = metrics or Metrics()
        self.stats = DistributedFleetStats(self.metrics)
        self.runners = dict(RUNNERS if runners is None else runners)
        self.picker = WdrrPicker(tenant_weights, quantum)
        self.heartbeat_interval = heartbeat_interval
        self.max_attempts = max_attempts
        self.max_tickets = max_tickets          # 0 = unbounded
        self.idle_exit_seconds = idle_exit_seconds  # 0 = run forever
        # chaos/test instrumentation: called at every part boundary of
        # the running ticket with (ticket, boundary index) BEFORE the
        # preemption probe — lets a trial fire a revoke at an exact,
        # replayable boundary instead of racing a wall clock
        self._part_boundary_hook = part_boundary_hook
        self._health_scope = f"fleet:{queue}"
        # lease-less mode (lease_seconds=0: claims never expire) makes
        # every renewal legitimately return 0 — that must not read as
        # a revocation or every ticket would false-yield each beat.
        # The coordinator may be wrapped (chaos AuditingCoordinator);
        # walk `.inner` to find the knob, defaulting to enabled.
        self._leases_enabled = True
        obj = coordinator
        for _ in range(4):
            ls = getattr(obj, "lease_seconds", None)
            if ls is not None:
                self._leases_enabled = ls > 0
                break
            obj = getattr(obj, "inner", None)
            if obj is None:
                break
        self._lock = threading.Lock()
        self._current: Optional[FleetTicket] = None
        self._revoked = False
        self._boundaries = 0
        self._draining = False
        self._dead = False
        self.tickets_run = 0
        # replay surface: (ticket_id, claim_epoch, stolen_from)
        self.claim_log: list[tuple] = []
        # fleet observability export stream (stats/fleetobs.py): this
        # worker AND every SnapshotLoader it runs (via the ambient
        # exporter around _run_ticket) share one (worker, seq) stream
        import os as _os

        self._obs = fleetobs.exporter_for(
            coordinator,
            worker=f"fleet.{self.worker_id}.{_os.getpid()}")

    # -- drain / liveness ----------------------------------------------------
    def request_drain(self) -> None:
        """SIGTERM path: yield the running transfer at its next part
        boundary, release the claim, exit the loop."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def dead(self) -> bool:
        return self._dead

    def _should_yield(self) -> bool:
        """The preemption probe the snapshot loader polls between
        parts: revoked lease (preemption / zombie fencing) or a drain
        request."""
        with self._lock:
            cur = self._current
            self._boundaries += 1
            boundary = self._boundaries
        if cur is not None and self._part_boundary_hook is not None:
            try:
                self._part_boundary_hook(cur, boundary)
            except Exception:
                logger.exception("part boundary hook failed")
        return self._revoked or self._draining

    # -- heartbeat -----------------------------------------------------------
    def _heartbeat_loop(self, stop: threading.Event) -> None:
        """Renew the held ticket's lease and report worker health.
        Transient failures are absorbed by the lease TTL; a
        WorkerKilledError kills the heartbeat — the worker becomes a
        zombie whose ticket a survivor reclaims after expiry.  A
        renewal of 0 for the held ticket means the lease was revoked
        or stolen: flag the yield.

        The renewal is scoped to the ticket captured BEFORE the RPC:
        (a) renewing by worker id alone would also renew a claim
        stranded by a dead predecessor that reused this index, wedging
        that ticket un-reclaimable forever; (b) comparing the result
        against a ticket claimed AFTER the RPC returned would flag a
        fresh claim as revoked."""
        while not stop.wait(self.heartbeat_interval):
            try:
                failpoint("worker.heartbeat")
                with self._lock:
                    held = self._current
                sp = trace.span("worker_heartbeat",
                                worker=self.worker_id)
                with sp:
                    renewed = 0
                    if held is not None:
                        # ticket AND epoch scoped: a same-id twin
                        # (pid-1 containers) must not renew this
                        # worker's claim nor have its own renewed here
                        renewed = self.cp.renew_ticket_leases(
                            self.queue, self.worker_id,
                            ticket_id=held.ticket_id,
                            claim_epoch=held.claim_epoch)
                if sp:
                    sp.add(renewed=renewed)
                with self._lock:
                    if held is not None and renewed == 0 \
                            and self._leases_enabled \
                            and self._current is held:
                        self._revoked = True
                self.cp.operation_health(
                    self._health_scope, self.worker_index, {
                        "state": ("draining" if self._draining else
                                  "running" if held is not None
                                  else "idle"),
                        "ticket": held.ticket_id if held else "",
                        "tickets_run": self.tickets_run,
                    })
                # observability export at heartbeat cadence: a kill -9
                # between beats loses at most one export interval
                self._obs.export("periodic")
            except Exception as e:
                if is_worker_kill(e):
                    logger.error(
                        "worker %s heartbeat killed: lease renewals "
                        "stop, the ticket will be reclaimed after "
                        "expiry", self.worker_id)
                    return
                logger.warning("worker %s heartbeat failed (lease TTL "
                               "absorbs it): %s", self.worker_id, e)

    # -- claim ---------------------------------------------------------------
    def _claim_next(self) -> Optional[FleetTicket]:
        """WDRR pick + fenced claim.  A lost claim race (another worker
        won the CAS) silently moves to the next candidate; a claim RPC
        fault (`fleet.claim`) is absorbed — the ticket stays claimable
        and this worker re-picks on its next loop."""
        sp = trace.span("fleet_claim_pick", worker=self.worker_id)
        with sp:
            tickets = self.cp.list_tickets(self.queue)
            now = time.time()
            claimable = [t for t in tickets
                         if ticket_claimable(t.to_json(), now)]
            excluded: set = set()
            while True:
                pool = [t for t in claimable
                        if t.ticket_id not in excluded]
                cand = self.picker.pick(pool)
                if cand is None:
                    return None
                try:
                    failpoint("fleet.claim")
                    won = self.cp.claim_ticket(
                        self.queue, cand.ticket_id, self.worker_id)
                except Exception as e:
                    logger.warning(
                        "worker %s claim of %s faulted (absorbed; "
                        "re-picking next loop): %s", self.worker_id,
                        cand.ticket_id, e)
                    return None
                if won is None:
                    excluded.add(cand.ticket_id)  # lost the race
                    continue
                self.picker.charge(won)
                if won.attempts == 1 and won.enqueued_at:
                    # distributed dispatch latency (enqueue → first
                    # claim, wall clock — the only shared axis across
                    # processes) into the mergeable histogram the obs
                    # segments export; re-claims after crash/preempt
                    # are recovery, not dispatch, and are excluded
                    from transferia_tpu.stats import hdr

                    hdr.observe("fleet_dispatch",
                                max(0.0, time.time() - won.enqueued_at))
                self.stats.claimed.inc()
                if won.stolen_from:
                    self.stats.steals.inc()
                with self._lock:
                    self.claim_log.append(
                        (won.ticket_id, won.claim_epoch,
                         won.stolen_from))
                if sp:
                    sp.add(ticket=won.ticket_id,
                           epoch=won.claim_epoch,
                           stolen_from=won.stolen_from or "")
                return won

    # -- completion ----------------------------------------------------------
    def _complete(self, ticket: FleetTicket, error: str = "") -> bool:
        """Fenced completion with RPC-fault retries (`fleet.complete`):
        re-asking under the same epoch is idempotent; False means the
        fence rejected a zombie completion."""
        sp = trace.span("fleet_ticket_complete",
                        ticket_id=ticket.ticket_id,
                        epoch=ticket.claim_epoch, error=error or "")
        with sp:
            last: Optional[BaseException] = None
            for _ in range(COMPLETE_RPC_ATTEMPTS):
                try:
                    failpoint("fleet.complete")
                    ok = self.cp.complete_ticket(self.queue, ticket,
                                                 error=error)
                except Exception as e:
                    last = e
                    time.sleep(0.02)
                    continue
                if not ok:
                    self.stats.fenced.inc()
                    logger.warning(
                        "completion of %s (epoch %d) fenced: the "
                        "ticket was reclaimed or revoked",
                        ticket.ticket_id, ticket.claim_epoch)
                elif error:
                    self.stats.failed.inc()
                else:
                    self.stats.completed.inc()
                if sp:
                    sp.add(accepted=bool(ok))
                return bool(ok)
            logger.error("completion RPC for %s kept failing: %s",
                         ticket.ticket_id, last)
            return False

    def _release(self, ticket: FleetTicket,
                 failed: bool = False) -> None:
        try:
            ok = self.cp.release_ticket(self.queue, ticket,
                                        failed=failed)
        except Exception as e:
            # the lease TTL is the backstop: an unreleased claim is
            # reclaimed after expiry
            logger.warning("release of %s faulted (lease TTL will "
                           "reclaim): %s", ticket.ticket_id, e)
            return
        if ok:
            self.stats.released.inc()
        # not ok = already revoked/reclaimed: it is someone else's now

    # -- run -----------------------------------------------------------------
    def _run_ticket(self, ticket: FleetTicket) -> None:
        runner = self.runners.get(
            ticket.payload.get("kind", "sample_snapshot"))
        if runner is None:
            raise ValueError(
                f"ticket {ticket.ticket_id}: unknown payload kind "
                f"{ticket.payload.get('kind')!r}")
        ctx = TicketRunContext(
            coordinator=self.cp, metrics=self.metrics,
            preempted=self._should_yield,
            # a re-claim (crash reclaim, preemption, retry) RESUMES the
            # operation from its committed parts instead of recreating
            # the part queue
            resume=ticket.attempts > 1 or ticket.preemptions > 0,
            worker_id=self.worker_id, queue=self.queue)
        # cross-process causal link: the admitting scheduler stamped
        # its span context into the payload (fleet/distributed.py
        # TRACE_KEY) — adopting it parents this worker's run span onto
        # the SAME trace, so the merged fleet timeline shows admission
        # and run as one causally-linked story even across processes
        wctx = trace.parse_wire(
            ticket.payload.get(TICKET_TRACE_KEY, ""))
        sp = trace.span("fleet_ticket_run", ticket_id=ticket.ticket_id,
                        tenant=ticket.tenant, qos=ticket.qos,
                        worker=self.worker_id, epoch=ticket.claim_epoch,
                        attempt=ticket.attempts, resume=ctx.resume,
                        transfer_id=ticket.transfer_id
                        or ticket.ticket_id)
        with trace.adopted(wctx), sp, LEDGER.context(
                transfer_id=ticket.transfer_id or ticket.ticket_id,
                tenant=ticket.tenant), \
                fleetobs.ambient_exporter(self._obs):
            runner(ticket, ctx)

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """The worker main loop: claim → run → complete, until drained
        or stopped.  A WorkerKilledError anywhere kills the WORKER
        (claims left leased for reclamation); everything else is
        handled per ticket."""
        stop = stop or threading.Event()
        hb_stop = threading.Event()
        hb = threading.Thread(target=self._heartbeat_loop,
                              args=(hb_stop,),
                              name=f"fleet-hb-{self.worker_id}",
                              daemon=True)
        hb.start()
        idle_since: Optional[float] = None
        try:
            while not stop.is_set() and not self._draining:
                if self.max_tickets and \
                        self.tickets_run >= self.max_tickets:
                    return
                ticket = self._claim_next()
                if ticket is None:
                    now = time.monotonic()
                    idle_since = idle_since or now
                    if self.idle_exit_seconds and \
                            now - idle_since >= self.idle_exit_seconds:
                        logger.info("worker %s idle %.1fs; exiting",
                                    self.worker_id,
                                    now - idle_since)
                        return
                    stop.wait(0.05)
                    continue
                idle_since = None
                with self._lock:
                    self._current = ticket
                    self._revoked = False
                    self._boundaries = 0
                try:
                    self._run_ticket(ticket)
                except BaseException as e:
                    if is_worker_kill(e):
                        # the worker dies WITH its claim: the lease
                        # strands and a survivor reclaims the ticket
                        self._dead = True
                        logger.error(
                            "worker %s killed running %s; ticket left "
                            "for reclamation", self.worker_id,
                            ticket.ticket_id)
                        return
                    if is_preemption(e):
                        # scheduler-initiated yield: NOT a failure —
                        # it must not burn the retry budget
                        self.stats.preempt_yields.inc()
                        trace.instant("fleet_preempt_yield",
                                      ticket_id=ticket.ticket_id,
                                      worker=self.worker_id)
                        self._release(ticket)
                    elif ticket.failures + 1 >= self.max_attempts:
                        logger.error(
                            "ticket %s failed %d time(s) over %d "
                            "claim(s): %s", ticket.ticket_id,
                            ticket.failures + 1, ticket.attempts, e)
                        self._complete(ticket, error=str(e) or
                                       type(e).__name__)
                    else:
                        logger.warning(
                            "ticket %s failure %d/%d (%s); releasing "
                            "for retry", ticket.ticket_id,
                            ticket.failures + 1, self.max_attempts, e)
                        self._release(ticket, failed=True)
                else:
                    self.tickets_run += 1
                    self._complete(ticket)
                finally:
                    with self._lock:
                        self._current = None
                        self._revoked = False
                    # ticket boundary export: the finished (or failed/
                    # yielded) ticket's spend is durable before the
                    # next claim
                    self._obs.export("ticket")
            # graceful drain: nothing claimed at this point (the yield
            # path released before we got here)
        finally:
            hb_stop.set()
            hb.join(timeout=5.0)
            if not self._dead:
                # SIGTERM-drain / idle-exit flush; a KILLED worker
                # deliberately does NOT flush — that is the crash whose
                # last heartbeat-cadence export the plane survives on
                self._obs.export("final")
            self.stats.worker_exits.inc()


def queue_busy_probe(coordinator: Coordinator,
                     queue: str) -> Callable[[int], bool]:
    """A WorkerSupervisor `busy_probe` answered from the durable
    queue: worker index N is busy iff some claimed ticket names it —
    the only view of a subprocess's state the supervisor has."""
    def probe(index: int) -> bool:
        wid = f"w{index}"
        return any(t.state == "claimed" and t.claimed_by == wid
                   for t in coordinator.list_tickets(queue))

    return probe


# -- supervision --------------------------------------------------------------

class _Handle:
    __slots__ = ("index", "worker", "thread", "stop", "proc",
                 "draining")

    def __init__(self, index, worker=None, thread=None, stop=None,
                 proc=None):
        self.index = index
        self.worker = worker
        self.thread = thread
        self.stop = stop
        self.proc = proc
        self.draining = False

    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return bool(self.thread and self.thread.is_alive())


def worker_argv(coordinator_args: list[str], queue: str,
                worker_index: int,
                idle_exit_seconds: float = 0.0,
                log_level: str = "warning") -> list[str]:
    """The `trtpu worker` command line for a supervised subprocess.
    `coordinator_args` are the global --coordinator* flags (a memory
    coordinator cannot cross a process boundary — use filestore/s3)."""
    argv = [sys.executable, "-m", "transferia_tpu.cli.main",
            "--log-level", log_level, *coordinator_args,
            "worker", "--queue", queue,
            "--worker-index", str(worker_index)]
    if idle_exit_seconds:
        argv += ["--idle-exit", str(idle_exit_seconds)]
    return argv


class WorkerSupervisor:
    """Spawn/supervise fleet workers; the autoscaler's actuator.

    `thread` mode runs FleetWorker instances on daemon threads (tests,
    chaos determinism, single-host fleets over a memory coordinator);
    `process` mode spawns real `trtpu worker` subprocesses from
    `spawn_argv(index)` (filestore/s3 coordinator required).  `reap()`
    collects exited workers; `scale_to(n)` spawns or drains toward a
    target; a crashed (not drained) worker is replaced on the next
    `scale_to`/`ensure` because it no longer counts as live.
    """

    def __init__(self, mode: str = "thread",
                 worker_factory: "Optional[Callable[[int], FleetWorker]]" = None,
                 spawn_argv: "Optional[Callable[[int], list[str]]]" = None,
                 busy_probe: "Optional[Callable[[int], bool]]" = None,
                 metrics: Optional[Metrics] = None,
                 name: str = "fleet-sup"):
        if mode == "thread" and worker_factory is None:
            raise ValueError("thread mode needs worker_factory")
        if mode == "process" and spawn_argv is None:
            raise ValueError("process mode needs spawn_argv")
        self.mode = mode
        self.name = name
        self.worker_factory = worker_factory
        self.spawn_argv = spawn_argv
        # process mode can't see a subprocess's in-memory state; the
        # probe answers "is worker index N running a ticket?" from the
        # durable queue (any claimed ticket with claimed_by == wN) so
        # scale-down drains an IDLE worker there too.  None = process
        # mode retires the newest worker regardless (its SIGTERM drain
        # is still graceful — part-boundary yield + release).
        self.busy_probe = busy_probe
        self.metrics = metrics or Metrics()
        self.stats = DistributedFleetStats(self.metrics)
        self._lock = threading.Lock()
        self._handles: list[_Handle] = []
        self._next_index = 0
        self.spawn_log: list[int] = []

    # -- spawn / retire ------------------------------------------------------
    def spawn(self) -> int:
        """Start one worker; returns its index.  The `worker.spawn`
        fault surfaces to the caller — the autoscaler logs and retries
        on its next step."""
        with self._lock:
            index = self._next_index
            self._next_index += 1
        sp = trace.span("worker_spawn", worker=index, mode=self.mode)
        with sp:
            failpoint("worker.spawn")
            if self.mode == "thread":
                worker = self.worker_factory(index)
                stop = threading.Event()
                th = threading.Thread(
                    target=worker.run, args=(stop,),
                    name=f"{self.name}-w{index}", daemon=True)
                handle = _Handle(index, worker=worker, thread=th,
                                 stop=stop)
                th.start()
            else:
                proc = subprocess.Popen(self.spawn_argv(index))
                handle = _Handle(index, proc=proc)
            with self._lock:
                self._handles.append(handle)
                self.spawn_log.append(index)
            self.stats.worker_spawns.inc()
            logger.info("supervisor %s spawned worker %d (%s)",
                        self.name, index, self.mode)
            return index

    def retire_one(self) -> Optional[int]:
        """Drain the newest idle live worker (scale-down).  Returns its
        index, or None when every live worker is busy."""
        with self._lock:
            candidates = [h for h in self._handles
                          if h.alive() and not h.draining]
        for h in reversed(candidates):
            if self.mode == "thread" and h.worker is not None:
                if h.worker._current is not None:
                    continue  # busy: drain an idle one instead
                h.worker.request_drain()
                h.stop.set()
            else:
                if self.busy_probe is not None:
                    try:
                        if self.busy_probe(h.index):
                            continue  # busy: drain an idle one instead
                    except Exception as e:
                        logger.warning("busy probe for worker %d "
                                       "failed (retiring anyway): %s",
                                       h.index, e)
                import signal as _signal

                try:
                    h.proc.send_signal(_signal.SIGTERM)
                except OSError:
                    continue
            h.draining = True
            trace.instant("worker_retire", worker=h.index)
            logger.info("supervisor %s draining worker %d",
                        self.name, h.index)
            return h.index
        return None

    def reap(self) -> int:
        """Drop exited workers from the live set; returns how many were
        reaped."""
        with self._lock:
            dead = [h for h in self._handles if not h.alive()]
            self._handles = [h for h in self._handles if h.alive()]
        for h in dead:
            logger.info("supervisor %s reaped worker %d%s", self.name,
                        h.index,
                        " (drained)" if h.draining else " (crashed)")
        return len(dead)

    def scale_to(self, target: int) -> None:
        """Move live worker count toward `target`: spawn up, drain
        down.  One drain per call (scale-down is deliberately gradual);
        spawn failures stop the scale-up for this call."""
        target = max(0, target)
        self.reap()
        while self.live_workers() < target:
            try:
                self.spawn()
            except Exception as e:
                logger.warning("supervisor %s spawn failed (autoscaler "
                               "retries next step): %s", self.name, e)
                return
        if self.live_workers() > target:
            self.retire_one()

    # -- introspection -------------------------------------------------------
    def live_workers(self) -> int:
        with self._lock:
            return sum(1 for h in self._handles
                       if h.alive() and not h.draining)

    def draining_workers(self) -> int:
        with self._lock:
            return sum(1 for h in self._handles
                       if h.alive() and h.draining)

    def handles(self) -> list[_Handle]:
        with self._lock:
            return list(self._handles)

    def snapshot(self) -> dict:
        with self._lock:
            workers = []
            for h in self._handles:
                running = ""
                if self.mode == "thread" and h.worker is not None:
                    # single read: _current can flip to None under us
                    cur = h.worker._current
                    running = cur.ticket_id if cur is not None else ""
                workers.append({"index": h.index, "alive": h.alive(),
                                "draining": h.draining,
                                "running": running})
        return {
            "mode": self.mode,
            "live": sum(1 for w in workers
                        if w["alive"] and not w["draining"]),
            "draining": sum(1 for w in workers
                            if w["alive"] and w["draining"]),
            "spawned": len(self.spawn_log),
            "workers": workers,
        }

    def shutdown(self, timeout: float = 30.0) -> None:
        """Drain everything and wait."""
        with self._lock:
            handles = list(self._handles)
        for h in handles:
            if self.mode == "thread":
                if h.worker is not None:
                    h.worker.request_drain()
                if h.stop is not None:
                    h.stop.set()
            elif h.proc is not None and h.proc.poll() is None:
                import signal as _signal

                try:
                    h.proc.send_signal(_signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + timeout
        for h in handles:
            remain = max(0.1, deadline - time.monotonic())
            if h.thread is not None:
                h.thread.join(timeout=remain)
            elif h.proc is not None:
                try:
                    h.proc.wait(timeout=remain)
                except subprocess.TimeoutExpired:
                    h.proc.kill()
        self.reap()
