"""`trtpu fleet bench` / `bench.py --fleet`: the scheduler under load.

Drives 100+ concurrent sample->memory snapshot transfers through
FleetScheduler with a deliberately skewed tenant mix (one tenant
submits ~10x the others) and reports what the ISSUE tracks:

- p50/p99 scheduler dispatch latency (admission -> dispatch decision)
  plus the raw pick overhead (time inside the DRR decision);
- the Jain fairness index over weighted per-tenant service during the
  contention window (the dispatch prefix in which EVERY tenant still
  has queued work — after a light tenant drains, the heavy tenant
  rightfully takes the slack, so post-drain service is excluded);
- delivery invariants: every transfer completes, every target store
  holds exactly the expected rows, no transfer is lost, shed without
  reason, or double-admitted.

Each transfer runs the REAL engine (SnapshotLoader against one shared
MemoryCoordinator, so 100+ operations hammer the per-operation part
locks concurrently) — the scheduler never shortcuts the data path.
"""

from __future__ import annotations

import logging
import random
import time

from transferia_tpu.coordinator.memory import MemoryCoordinator
from transferia_tpu.fleet.scheduler import (
    FleetScheduler,
    FleetTransfer,
    QosClass,
    percentile,
)
from transferia_tpu.models import Transfer, TransferType
from transferia_tpu.stats import hdr, watermark
from transferia_tpu.stats.registry import Metrics

logger = logging.getLogger(__name__)

# submission skew: tenant-a floods, the rest trickle (10:1); weights
# are EQUAL, so fair share during contention is equal service — which
# is exactly what the Jain index then measures.  Every bench ticket is
# BATCH class: uniform deficit charge keeps the fairness signal clean
# (QoS priority effects are pinned by tests/unit/test_fleet.py).
TENANT_SKEW = {"tenant-a": 10, "tenant-b": 1, "tenant-c": 1,
               "tenant-d": 1}


def tenant_mix(transfers: int, seed: int) -> list[tuple[str, QosClass]]:
    """Deterministic (tenant, qos) assignment for `transfers` tickets:
    counts proportional to TENANT_SKEW (light tenants floored at 4 so
    the contention window has statistics even at smoke sizes), order
    seed-shuffled (the same seed + mix must yield the identical
    admission order — pinned by tests/unit/test_fleet.py)."""
    total_share = sum(TENANT_SKEW.values())
    out: list[tuple[str, QosClass]] = []
    counts: dict[str, int] = {}
    for name, share in sorted(TENANT_SKEW.items()):
        counts[name] = max(4, (transfers * share) // total_share)
    # pad/trim to the exact requested count, heavy tenant absorbs
    heavy = max(TENANT_SKEW, key=lambda k: TENANT_SKEW[k])
    counts[heavy] = max(4, counts[heavy]
                        + transfers - sum(counts.values()))
    for name in sorted(counts):
        for _ in range(counts[name]):
            out.append((name, QosClass.BATCH))
    random.Random(seed).shuffle(out)
    return out


def _bench_transfer(idx: int, rows: int, sink_id: str) -> Transfer:
    from transferia_tpu.providers.memory import MemoryTargetParams
    from transferia_tpu.providers.sample import SampleSourceParams

    t = Transfer(
        id=f"fleet-t{idx:04d}",
        type=TransferType.SNAPSHOT_ONLY,
        src=SampleSourceParams(preset="iot", table="events", rows=rows,
                               batch_rows=max(64, rows)),
        dst=MemoryTargetParams(sink_id=sink_id),
    )
    t.runtime.sharding.process_count = 1
    return t


def jain_index(shares: list[float]) -> float:
    """Jain's fairness index: 1.0 = perfectly proportional shares."""
    if not shares:
        return 1.0
    s = sum(shares)
    sq = sum(x * x for x in shares)
    if sq <= 0:
        return 1.0
    return (s * s) / (len(shares) * sq)


def contention_fairness(sched: FleetScheduler,
                        tickets: dict[str, FleetTransfer]) -> float:
    """Jain over weighted per-tenant service across the dispatch
    prefix where every tenant still had undispatched tickets."""
    remaining: dict[str, int] = {}
    for t in tickets.values():
        remaining[t.tenant] = remaining.get(t.tenant, 0) + 1
    service: dict[str, float] = {name: 0.0 for name in remaining}
    weights = {name: sched._tenants[name].weight
               for name in remaining if name in sched._tenants}
    seen: set[str] = set()
    for tid in sched.dispatch_log:
        if any(v <= 0 for v in remaining.values()):
            break
        t = tickets.get(tid)
        if t is None:
            continue
        service[t.tenant] += t.charged_cost
        if tid not in seen:  # rebalance re-dispatches don't drain
            seen.add(tid)
            remaining[t.tenant] -= 1
    shares = [service[name] / max(weights.get(name, 1.0), 1e-9)
              for name in sorted(service)]
    return jain_index(shares)


def run_fleet_bench(transfers: int = 120, workers: int = 8,
                    lanes: int = 2, rows: int = 256,
                    seed: int = 7) -> dict:
    from transferia_tpu.providers.memory import get_store

    mix = tenant_mix(transfers, seed)
    transfers = len(mix)  # light-tenant floors can round up tiny runs
    cp = MemoryCoordinator()
    metrics = Metrics()
    # backpressure=True: the controller shares the scheduler's metrics
    # registry, so the fleet_queue_depth signal is live (the lax
    # default watermark of 4096 never trips at bench sizes — the wiring
    # is what this exercises, not a shed)
    sched = FleetScheduler(workers=workers,
                           max_inflight_per_worker=lanes,
                           tenant_queue_quota=max(transfers, 1024),
                           backpressure=True,
                           metrics=metrics, name="fleet-bench")
    tickets: dict[str, FleetTransfer] = {}
    sink_ids: dict[str, str] = {}
    for i, (tenant, qos) in enumerate(mix):
        sink_id = f"fleet-bench-{i:04d}"
        get_store(sink_id).clear()
        transfer = _bench_transfer(i, rows, sink_id)

        def run(t=transfer):
            from transferia_tpu.tasks.snapshot import SnapshotLoader

            SnapshotLoader(t, cp, metrics=Metrics()).upload_tables()

        ticket = FleetTransfer(transfer_id=transfer.id, tenant=tenant,
                               run=run, qos=qos)
        tickets[ticket.transfer_id] = ticket
        sink_ids[ticket.transfer_id] = sink_id
    # pre-load the queue, THEN start the workers: fairness is a
    # property of the scheduler's picks under contention, and a cold
    # pool draining tickets in arrival order before the backlog forms
    # would measure submission timing instead
    for tid in sorted(tickets):
        decision = sched.submit(tickets[tid])
        if decision != "admitted":
            logger.error("fleet bench: %s not admitted: %s",
                         tid, decision)
    # baseline for the mergeable dispatch-latency histogram
    # (stats/hdr.py): the registry is process-global, so the bench
    # carves its own window out of it with a bucket-wise diff
    h0 = hdr.STAGES.get("fleet_dispatch")
    l0 = hdr.STAGES.get(watermark.STAGE_LAG)
    t0 = time.perf_counter()
    sched.start()
    try:
        drained = sched.drain(timeout=600.0)
        wall = time.perf_counter() - t0
    finally:
        sched.shutdown()
    hwin = hdr.STAGES.get("fleet_dispatch").diff(h0)
    hdr_summary = hwin.summary()
    lag_summary = hdr.STAGES.get(watermark.STAGE_LAG).diff(l0).summary()

    # -- delivery audit ------------------------------------------------------
    lost: list[str] = []
    bad_rows: list[str] = []
    for tid, t in tickets.items():
        if t.state != "done":
            lost.append(f"{tid}:{t.state}")
            continue
        got = get_store(sink_ids[tid]).row_count()
        if got != rows:
            bad_rows.append(f"{tid}:{got}/{rows}")
    for sink_id in sink_ids.values():
        get_store(sink_id).clear()

    lats_ms = [v * 1000.0 for v in sched.dispatch_latencies]
    picks_us = [v * 1e6 for v in sched.pick_seconds if v > 0]
    fairness = contention_fairness(sched, tickets)
    counts = sched.counts()
    ok = (drained and not lost and not bad_rows
          and not sched.double_admissions and fairness >= 0.9)
    return {
        "metric": "fleet_transfers_per_sec",
        "unit": "transfers/sec",
        "value": round(transfers / max(wall, 1e-9), 1),
        "ok": ok,
        "transfers": transfers,
        "workers": workers,
        "lanes_per_worker": lanes,
        "rows_per_transfer": rows,
        "seed": seed,
        "wall_seconds": round(wall, 3),
        "completed": counts.get("done", 0),
        "failed": counts.get("failed", 0),
        "shed": counts.get("shed", 0),
        "lost": lost,
        "row_mismatches": bad_rows,
        "double_admissions": len(sched.double_admissions),
        "jain_fairness": round(fairness, 4),
        "dispatch_p50_ms": round(percentile(lats_ms, 0.50), 3),
        "dispatch_p99_ms": round(percentile(lats_ms, 0.99), 3),
        # the mergeable-histogram view of the same tail (stats/hdr.py
        # — what the fleet obs segments export and the panes merge):
        # p999 exists only here, scalar percentiles stop at p99
        "dispatch_hdr_p50_ms": hdr_summary["p50_ms"],
        "dispatch_hdr_p99_ms": hdr_summary["p99_ms"],
        "dispatch_hdr_p999_ms": hdr_summary["p999_ms"],
        "dispatch_hdr_count": hdr_summary["count"],
        "dispatch_hdr_max_trace": hdr_summary["max_trace"],
        # end-to-end freshness tail over the same run window: sample
        # batches carry event time, the sink-side Statistician feeds
        # publish lag into the mergeable replication_lag histogram
        "replication_lag_p99_ms": lag_summary["p99_ms"],
        "replication_lag_count": lag_summary["count"],
        "pick_p50_us": round(percentile(picks_us, 0.50), 1),
        "pick_p99_us": round(percentile(picks_us, 0.99), 1),
        "desired_workers_final": sched.desired_workers(),
        "tenants": {
            name: TENANT_SKEW[name] for name in sorted(TENANT_SKEW)
        },
    }


def format_report(report: dict) -> str:
    lines = [
        f"fleet bench: {report['transfers']} transfers x "
        f"{report['rows_per_transfer']} rows over "
        f"{report['workers']}x{report['lanes_per_worker']} worker "
        f"lanes in {report['wall_seconds']}s "
        f"({report['value']} transfers/s)",
        f"  dispatch latency p50={report['dispatch_p50_ms']}ms "
        f"p99={report['dispatch_p99_ms']}ms  (pick overhead "
        f"p50={report['pick_p50_us']}us p99={report['pick_p99_us']}us)",
        f"  dispatch hdr (mergeable): "
        f"p50={report['dispatch_hdr_p50_ms']}ms "
        f"p99={report['dispatch_hdr_p99_ms']}ms "
        f"p999={report['dispatch_hdr_p999_ms']}ms "
        f"n={report['dispatch_hdr_count']}",
        f"  replication lag (mergeable): "
        f"p99={report['replication_lag_p99_ms']}ms "
        f"n={report['replication_lag_count']}",
        f"  jain fairness (contention window, skew 10:1): "
        f"{report['jain_fairness']}",
        f"  completed={report['completed']} failed={report['failed']} "
        f"shed={report['shed']} double_admitted="
        f"{report['double_admissions']}",
    ]
    if report["lost"]:
        lines.append(f"  LOST: {report['lost']}")
    if report["row_mismatches"]:
        lines.append(f"  ROW MISMATCHES: {report['row_mismatches']}")
    lines.append("fleet bench verdict: "
                 + ("PASS" if report["ok"] else "FAIL"))
    return "\n".join(lines)
