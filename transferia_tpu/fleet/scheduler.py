"""Fleet scheduler: admission control + weighted fair-share dispatch.

The plane ABOVE operations.  PR 5's leases/epoch fencing recover a
single operation's parts when a worker dies; nothing decided which of
N tenants x M transfers get to run at all.  This module is that
decision: transfers are submitted as `FleetTransfer` tickets, pass an
admission gate (per-tenant queue quotas + data-plane backpressure,
fleet/backpressure.py), queue per tenant, and dispatch onto a bounded
pool of worker slots by deficit round-robin — each dispatched ticket
then runs the EXISTING engine (SnapshotLoader / run_replication),
whose part claims go through the coordinator's lease machinery
unchanged.  The scheduler never touches parts; it decides who runs.

Fair share: every tenant owns a deficit counter.  A visit adds
`quantum * weight` to the tenant's deficit; the head ticket dispatches
when the deficit covers its charged cost (`cost * qos factor` —
INTERACTIVE tickets charge 1x, BATCH 2x, SCAVENGER 4x, so latency-
sensitive work drains proportionally faster without starving anyone:
deficits grow every round, so any queued ticket's dispatch is at most
a bounded number of rounds away regardless of the competing load).

Determinism: every dispatch decision happens under the scheduler
lock, tenants are visited in a fixed rotation, ties break by
submission sequence — so for a fixed submission set the k-th dispatch
is a pure function of k, no matter which worker thread asks or how
the OS schedules them.  `dispatch_log` records the order; the chaos
`scheduler_kill` mode replays it under a seed.  The `fleet.dispatch`
failpoint fires inside that same critical section, which is what
makes kill/rebalance trials replay exactly.

Worker model: `workers` slots x `max_inflight_per_worker` lanes, one
thread per lane.  A `WorkerKilledError` (from the dispatch failpoint
or raised out of a running transfer) kills the SLOT: the raising
lane's in-flight ticket is rebalanced — requeued at the head of its
tenant's queue with the attempt counted — and the slot's lanes exit
at their next dispatch (in-process threads cannot be preempted, so a
sibling lane mid-transfer finishes that transfer before exiting —
work already done is never thrown away).  Surviving slots absorb the
queue; if every slot is dead while work remains, one replacement slot
spawns (the floor the autoscaling hint builds on).

Backpressure wiring: pass `backpressure=True` to gate admission on a
`BackpressureController` over THIS scheduler's metrics registry —
which therefore must be the registry the data plane folds its gauges
into (share one `Metrics` across the pipeline and the scheduler), or
the readahead/sink/ratio signals will read 0.0 and only the
scheduler's own `fleet_queue_depth` can trip.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from transferia_tpu.abstract.errors import is_worker_kill
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.fleet.backpressure import BackpressureController
from transferia_tpu.runtime import lockwatch
from transferia_tpu.stats import hdr, trace
from transferia_tpu.stats.ledger import LEDGER
from transferia_tpu.stats.registry import FleetStats, Metrics

logger = logging.getLogger(__name__)

DEFAULT_TICKET_ATTEMPTS = 3  # dispatch attempts per ticket (faults +
#                              rebalances both count; the part-level
#                              retry machinery runs INSIDE each attempt)


class QosClass(str, enum.Enum):
    INTERACTIVE = "interactive"
    BATCH = "batch"
    SCAVENGER = "scavenger"


# deficit charge multipliers: a SCAVENGER ticket spends 4x the deficit
# an INTERACTIVE one does, so interactive work drains ~4x faster under
# contention while scavengers still advance every round
QOS_COST_FACTOR = {
    QosClass.INTERACTIVE: 1,
    QosClass.BATCH: 2,
    QosClass.SCAVENGER: 4,
}

_QOS_ORDER = (QosClass.INTERACTIVE, QosClass.BATCH, QosClass.SCAVENGER)


@dataclass
class FleetTransfer:
    """One schedulable transfer: identity + the engine entry point."""

    transfer_id: str
    tenant: str
    run: Callable[[], Any]
    qos: QosClass = QosClass.BATCH
    cost: int = 1                  # deficit units (~parts / eta weight)
    # -- scheduler bookkeeping (owned by FleetScheduler) ------------------
    state: str = "new"             # queued|running|done|failed|shed
    seq: int = -1
    attempts: int = 0
    worker: Optional[int] = None
    shed_reason: str = ""
    error: Optional[BaseException] = None
    submitted_at: float = 0.0
    queued_at: float = 0.0         # last time the ticket entered a queue
    dispatched_at: float = 0.0
    finished_at: float = 0.0
    # causal anchor: the admission span's context — every later
    # lifecycle span/instant of this ticket (queue wait, dispatch,
    # run, rebalance, kill) links to it, so the whole lifecycle is ONE
    # trace no matter which lane thread touches the ticket
    trace_ctx: Optional["trace.SpanContext"] = None

    @property
    def charged_cost(self) -> int:
        return max(1, self.cost) * QOS_COST_FACTOR[self.qos]

    @property
    def dispatch_latency(self) -> float:
        """Queue wait of the CURRENT attempt: last enqueue -> dispatch
        decision (seconds).  queued_at resets on every requeue
        (rebalance, transient fault, retry) so a rerun never bills the
        prior attempt's wait or run time as queue wait."""
        start = self.queued_at or self.submitted_at
        if self.dispatched_at and start:
            return self.dispatched_at - start
        return 0.0


class _Tenant:
    __slots__ = ("name", "weight", "deficit", "queues", "queued",
                 "charged_queued", "running", "done", "failed", "shed",
                 "service")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = weight
        self.deficit = 0.0
        self.queues: dict[QosClass, deque] = {q: deque()
                                              for q in _QOS_ORDER}
        self.queued = 0
        self.charged_queued = 0  # incremental: gauge reads stay O(1)
        self.running = 0
        self.done = 0
        self.failed = 0
        self.shed = 0
        self.service = 0  # charged cost dispatched (fairness numerator)

    def head(self) -> Optional[FleetTransfer]:
        for q in _QOS_ORDER:
            if self.queues[q]:
                return self.queues[q][0]
        return None

    def pop_head(self) -> FleetTransfer:
        for q in _QOS_ORDER:
            if self.queues[q]:
                t = self.queues[q].popleft()
                self.queued -= 1
                self.charged_queued -= t.charged_cost
                return t
        raise IndexError("pop from empty tenant queue")

    def push(self, ticket: FleetTransfer, front: bool = False) -> None:
        dq = self.queues[ticket.qos]
        (dq.appendleft if front else dq.append)(ticket)
        self.queued += 1
        self.charged_queued += ticket.charged_cost

    def debt(self) -> float:
        """Weighted backlog: charged cost queued, normalized by weight
        — the autoscaling hint's per-tenant term."""
        return self.charged_queued / max(self.weight, 1e-9)


class _WorkerDied(Exception):
    """Internal lane signal: this slot was killed at dispatch time."""


def percentile(values: list[float], p: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(p * (len(vs) - 1)))))
    return vs[idx]


class FleetScheduler:
    """Admission control + DRR dispatch over a bounded worker pool."""

    def __init__(self, workers: int = 4,
                 max_inflight_per_worker: int = 2,
                 tenant_queue_quota: int = 1024,
                 quantum: float = 1.0,
                 tenant_weights: Optional[dict[str, float]] = None,
                 backpressure: "Optional[BackpressureController | bool]"
                 = None,
                 metrics: Optional[Metrics] = None,
                 max_attempts: int = DEFAULT_TICKET_ATTEMPTS,
                 ticket_history_limit: int = 65536,
                 name: str = "fleet"):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if max_inflight_per_worker < 1:
            raise ValueError("max_inflight_per_worker must be >= 1")
        self.name = name
        self.metrics = metrics or Metrics()
        self.stats = FleetStats(self.metrics)
        # backpressure=True builds the controller over THIS registry so
        # the fleet_queue_depth signal (and any data-plane gauges folded
        # into the same registry) are actually read — a controller over
        # a disconnected registry would see 0.0 forever
        if backpressure is True:
            backpressure = BackpressureController(self.metrics)
        self.backpressure = backpressure or None
        if self.backpressure is not None:
            # gauge freshness: the controller's evaluation is the
            # "backpressure tick" — refresh queue_depth/desired_workers
            # right before it reads them, so a drained-then-idle fleet
            # never advertises its last busy desired_workers value
            self.backpressure.add_tick_listener(self.refresh_gauges)
        self.quantum = quantum
        self.tenant_queue_quota = tenant_queue_quota
        self.max_attempts = max_attempts
        self._n_workers = workers
        self._lanes_per_worker = max_inflight_per_worker
        self._tenant_weights = dict(tenant_weights or {})
        self._lock = lockwatch.named_lock("fleet.scheduler")
        self._cond = threading.Condition(self._lock)
        self._tenants: dict[str, _Tenant] = {}
        self._active: deque[str] = deque()   # tenants with queued work
        self._tickets: dict[str, FleetTransfer] = {}
        # terminal tickets are evicted FIFO past this bound so a
        # long-lived scheduler does not hold every transfer it ever ran
        # (its run closure captures the whole Transfer)
        self._history_limit = max(1, ticket_history_limit)
        self._terminal_order: deque[str] = deque()
        self._pending = 0   # admitted minus terminal — drain() waits on 0
        self._seq = 0
        self._running = 0
        self._stopped = False
        self._started = False
        self._threads: list[threading.Thread] = []
        self._dead_workers: set[int] = set()
        self._next_worker = workers          # respawn slot indices
        # audit surfaces (bounded like the latency deques): dispatch
        # order, kill/rebalance log, and any double-admission (a ticket
        # picked while not queued — must stay empty; the chaos auditor
        # asserts on it)
        self.dispatch_log: deque = deque(maxlen=65536)
        self.rebalance_log: deque = deque(maxlen=65536)
        self.kill_log: deque = deque(maxlen=65536)
        self.double_admissions: list[str] = []
        self.dispatch_latencies: deque = deque(maxlen=65536)
        self.pick_seconds: deque = deque(maxlen=65536)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetScheduler":
        with self._lock:
            if self._started:
                return self
            self._started = True
            for w in range(self._n_workers):
                self._spawn_worker_locked(w)
        from transferia_tpu import fleet as fleet_mod

        fleet_mod.register_scheduler(self)
        return self

    def _spawn_worker_locked(self, widx: int) -> None:
        for lane in range(self._lanes_per_worker):
            t = threading.Thread(
                target=self._lane_loop, args=(widx,),
                name=f"{self.name}-w{widx}.{lane}", daemon=True)
            self._threads.append(t)
            t.start()

    def shutdown(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=30.0)
        if self.backpressure is not None:
            # a shared long-lived controller must not keep this dead
            # scheduler alive (or let it overwrite live gauges)
            self.backpressure.remove_tick_listener(self.refresh_gauges)
        from transferia_tpu import fleet as fleet_mod

        fleet_mod.unregister_scheduler(self)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted ticket reaches a terminal state
        (done/failed/shed).  Returns False on timeout."""
        deadline = (time.monotonic() + timeout) if timeout else None
        with self._cond:
            while True:
                if self._pending <= 0:
                    return True
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        return False
                self._cond.wait(wait if wait is not None else 1.0)

    # -- admission -----------------------------------------------------------
    def submit(self, ticket: FleetTransfer) -> str:
        """Admission gate.  Returns "admitted" or a shed reason
        ("shed-tenant-quota" / "shed-backpressure"); raises when the
        admission RPC itself fails (the `fleet.admit` chaos site) —
        callers retry, exactly as they would a coordinator call."""
        failpoint("fleet.admit")
        # the ticket's trace root: queue-wait, dispatch, run and any
        # rebalance/kill events all link back to this admission span
        adm_sp = trace.span("fleet_admit",
                            transfer_id=ticket.transfer_id,
                            tenant=ticket.tenant, qos=ticket.qos.value)
        with adm_sp:
            if adm_sp:
                ticket.trace_ctx = adm_sp.context()
            # read the data-plane gauges OUTSIDE the scheduler lock: the
            # controller takes its own lock and reads N metrics
            hot = (self.backpressure.overloaded()
                   if self.backpressure else False)
            with self._cond:
                tn = self._tenant_locked(ticket.tenant)
                if tn.queued >= self.tenant_queue_quota:
                    ticket.state = "shed"
                    ticket.shed_reason = "shed-tenant-quota"
                elif hot:
                    ticket.state = "shed"
                    ticket.shed_reason = "shed-backpressure"
                else:
                    ticket.seq = self._seq
                    self._seq += 1
                    ticket.state = "queued"
                    ticket.submitted_at = time.perf_counter()
                    ticket.queued_at = ticket.submitted_at
                    self._tickets[ticket.transfer_id] = ticket
                    self._pending += 1
                    tn.push(ticket)
                    if ticket.tenant not in self._active:
                        self._active.append(ticket.tenant)
                    self.stats.admitted.inc()
                    self._update_gauges_locked()
                    self._cond.notify()
                    if adm_sp:
                        adm_sp.add(decision="admitted")
                    return "admitted"
                tn.shed += 1
                self.stats.shed.inc()
                # sheds must refresh the gauges too: a shedding fleet
                # is exactly when the autoscaler reads desired_workers
                self._update_gauges_locked()
                if adm_sp:
                    adm_sp.add(decision=ticket.shed_reason)
                return ticket.shed_reason

    def _tenant_locked(self, name: str) -> _Tenant:
        tn = self._tenants.get(name)
        if tn is None:
            tn = self._tenants[name] = _Tenant(
                name, float(self._tenant_weights.get(name, 1.0)))
        return tn

    def tenant_weight(self, name: str) -> float:
        """Current WDRR weight for a tenant (configured default when it
        has not queued work yet)."""
        with self._lock:
            tn = self._tenants.get(name)
            if tn is not None:
                return tn.weight
            return float(self._tenant_weights.get(name, 1.0))

    def set_tenant_weight(self, name: str, weight: float) -> float:
        """Retune a tenant's WDRR weight live (the SLO alert hook's
        escalation lever: a tenant burning latency budget gets a larger
        deficit refill, so its queue drains faster).  Returns the prior
        weight so the caller can restore it on recovery."""
        weight = max(0.01, float(weight))
        with self._lock:
            tn = self._tenants.get(name)
            prior = tn.weight if tn is not None \
                else float(self._tenant_weights.get(name, 1.0))
            self._tenant_weights[name] = weight
            if tn is not None:
                tn.weight = weight
            self._cond.notify_all()
        return prior

    # -- dispatch (DRR) ------------------------------------------------------
    def _pick_locked(self) -> Optional[FleetTransfer]:
        """One deficit-round-robin decision.  Caller holds the lock."""
        guard = 0
        while self._active:
            guard += 1
            if guard > 100_000:  # pathological quantum/cost ratio
                logger.error("fleet DRR guard tripped; dispatching "
                             "front tenant head")
                tn = self._tenants[self._active[0]]
                taken = self._take_locked(tn, tn.pop_head())
                if taken is None:
                    continue
                return taken
            tname = self._active[0]
            tn = self._tenants[tname]
            head = tn.head()
            if head is None:
                self._active.popleft()
                tn.deficit = 0.0
                continue
            if tn.deficit < head.charged_cost:
                tn.deficit += self.quantum * tn.weight
                self._active.rotate(-1)
                continue
            tn.pop_head()
            if not tn.head():
                self._active.popleft()
                tn.deficit = 0.0
            else:
                tn.deficit -= head.charged_cost
            taken = self._take_locked(tn, head)
            if taken is None:
                continue  # double-admission guard dropped the ticket
            return taken
        return None

    def _take_locked(self, tn: _Tenant,
                     ticket: FleetTransfer) -> Optional[FleetTransfer]:
        if ticket.state != "queued":
            # double admission: a ticket reached the dispatch point
            # while not queued — record and DROP it (running it again
            # is exactly the duplicate delivery the guard exists to
            # prevent); the auditor asserts this list stays empty
            self.double_admissions.append(ticket.transfer_id)
            logger.error("fleet: ticket %s picked in state %r; dropped",
                         ticket.transfer_id, ticket.state)
            return None
        ticket.state = "running"
        ticket.attempts += 1
        ticket.dispatched_at = time.perf_counter()
        tn.running += 1
        tn.service += ticket.charged_cost
        self._running += 1
        self.dispatch_log.append(ticket.transfer_id)
        lat = ticket.dispatch_latency
        self.dispatch_latencies.append(lat)
        self.stats.dispatch_time.observe(lat)
        # mergeable log-bucket histogram (stats/hdr.py): the fleet obs
        # segments export this, so N processes' dispatch tails merge
        # into one exact p50/p99/p999; the exemplar on the max bucket
        # is the worst dispatch's trace id
        hdr.observe("fleet_dispatch", lat,
                    trace_id=ticket.trace_ctx.trace_id
                    if ticket.trace_ctx else 0)
        # the queue wait becomes a real span on the ticket trace,
        # recorded retroactively now that it ended (admission →
        # dispatch decision, regardless of which lane thread picked it)
        trace.complete("fleet_queue_wait",
                       t0=ticket.queued_at or ticket.submitted_at,
                       dur=lat, parent=ticket.trace_ctx,
                       transfer_id=ticket.transfer_id,
                       tenant=ticket.tenant, attempt=ticket.attempts)
        return ticket

    def _next_dispatch(self, widx: int) -> Optional[FleetTransfer]:
        """Block until a ticket is available for this worker slot, the
        scheduler stops, or the slot is found dead.  The dispatch
        failpoint fires inside the same critical section as the pick,
        so a kill's position in the dispatch order is seed-exact."""
        with self._cond:
            while True:
                if self._stopped:
                    return None
                if widx in self._dead_workers:
                    raise _WorkerDied()
                t0 = time.perf_counter()
                ticket = self._pick_locked()
                self.pick_seconds.append(time.perf_counter() - t0)
                if ticket is None:
                    self._update_gauges_locked()
                    self._cond.wait(0.5)
                    continue
                ticket.worker = widx
                try:
                    failpoint("fleet.dispatch")
                except BaseException as e:
                    if is_worker_kill(e):
                        self._kill_worker_locked(widx, ticket)
                        raise _WorkerDied() from e
                    # transient dispatch fault: the slot survives, the
                    # ticket goes back and redispatches (attempt spent)
                    self._rebalance_locked(ticket, widx)
                    self._update_gauges_locked()
                    continue
                self._update_gauges_locked()
                # dispatch decision as a point event on the ticket
                # trace: together with the fleet_queue_wait span this
                # marks where the scheduler handed the ticket to a lane
                trace.instant("fleet_dispatch", ctx=ticket.trace_ctx,
                              worker=widx,
                              transfer_id=ticket.transfer_id)
                return ticket

    # -- worker death & rebalance -------------------------------------------
    def _kill_worker_locked(self, widx: int, ticket: FleetTransfer
                            ) -> None:
        if widx not in self._dead_workers:
            # a sibling lane of an already-dead slot raising its own
            # kill must not double-count the slot's death — but its
            # ticket still needs the rebalance below
            self._dead_workers.add(widx)
            self.kill_log.append((widx, ticket.transfer_id))
            self.stats.worker_deaths.inc()
            trace.instant("fleet_worker_kill", ctx=ticket.trace_ctx,
                          worker=widx, transfer_id=ticket.transfer_id)
        logger.warning("fleet worker %d killed holding %s; rebalancing",
                       widx, ticket.transfer_id)
        self._rebalance_locked(ticket, widx)
        live = set(range(self._next_worker)) - self._dead_workers
        if not live and not self._stopped:
            # every slot is dead: spawn the floor replacement
            # UNCONDITIONALLY — a worker-less scheduler is permanently
            # wedged (later submits would be "admitted" with nobody to
            # ever pick them and drain() would block forever), whether
            # or not work is pending right now.  The autoscaling hint
            # asks for anything beyond this floor.
            widx_new = self._next_worker
            self._next_worker += 1
            logger.warning("fleet: all workers dead; spawning "
                           "replacement slot %d", widx_new)
            self._spawn_worker_locked(widx_new)
        self._cond.notify_all()

    def _rebalance_locked(self, ticket: FleetTransfer,
                          dead_worker: int) -> None:
        """Requeue an assigned ticket after its slot died (or its
        dispatch faulted).  The `fleet.rebalance` fault is ABSORBED —
        a failed rebalance RPC must never lose the transfer, so the
        requeue proceeds regardless and the fault only counts."""
        try:
            failpoint("fleet.rebalance")
        except Exception as e:
            logger.warning("fleet rebalance fault for %s (absorbed): %s",
                           ticket.transfer_id, e)
        tn = self._tenant_locked(ticket.tenant)
        tn.running -= 1
        self._running -= 1
        ticket.worker = None
        self.stats.rebalanced.inc()
        trace.instant("fleet_rebalance", ctx=ticket.trace_ctx,
                      transfer_id=ticket.transfer_id,
                      dead_worker=dead_worker,
                      attempt=ticket.attempts)
        LEDGER.add_for(ticket.transfer_id, tenant=ticket.tenant,
                       retries=1)
        self.rebalance_log.append(
            (ticket.transfer_id, dead_worker, ticket.attempts))
        if ticket.attempts >= self.max_attempts:
            ticket.state = "failed"
            ticket.finished_at = time.perf_counter()
            tn.failed += 1
            self.stats.failed.inc()
            self._terminal_locked(ticket)
            logger.error("fleet: %s failed after %d dispatch attempts",
                         ticket.transfer_id, ticket.attempts)
            self._cond.notify_all()
            return
        ticket.state = "queued"
        ticket.dispatched_at = 0.0
        ticket.queued_at = time.perf_counter()
        tn.push(ticket, front=True)
        if ticket.tenant not in self._active:
            self._active.appendleft(ticket.tenant)
        self._cond.notify_all()

    def _terminal_locked(self, ticket: FleetTransfer) -> None:
        """A ticket reached done/failed: count it out of the pending
        set and evict the oldest terminal tickets past the history
        bound (their run closures hold whole Transfer objects)."""
        self._pending -= 1
        self._terminal_order.append(ticket.transfer_id)
        while len(self._tickets) > self._history_limit \
                and self._terminal_order:
            old = self._terminal_order.popleft()
            t = self._tickets.get(old)
            if t is not None and t.state in ("done", "failed"):
                del self._tickets[old]

    # -- lanes ---------------------------------------------------------------
    def _lane_loop(self, widx: int) -> None:
        while True:
            try:
                ticket = self._next_dispatch(widx)
            except _WorkerDied:
                return
            if ticket is None:
                return
            try:
                self._run_ticket(widx, ticket)
            except BaseException as e:
                if is_worker_kill(e):
                    # the transfer died WITH its worker (OOM-kill, pod
                    # eviction mid-run): slot dies, ticket rebalances —
                    # the engine's at-least-once contract absorbs the
                    # partial delivery on the rerun
                    with self._cond:
                        self._kill_worker_locked(widx, ticket)
                        self._update_gauges_locked()
                    return
                self._finish(ticket, error=e)
            else:
                self._finish(ticket)

    def _run_ticket(self, widx: int, ticket: FleetTransfer) -> None:
        """Run one dispatched ticket under its trace + ledger scope:
        the engine's own spans (snapshot_op → part → batch → device)
        flow onto the ticket trace, and every resource the run burns
        bills (transfer_id, tenant) — queue wait included, so `trtpu
        top` shows where a slow transfer's seconds actually went."""
        with trace.adopted(ticket.trace_ctx), \
                LEDGER.context(transfer_id=ticket.transfer_id,
                               tenant=ticket.tenant):
            LEDGER.add(queue_wait_seconds=ticket.dispatch_latency)
            sp = trace.span("fleet_run",
                            transfer_id=ticket.transfer_id,
                            tenant=ticket.tenant, worker=widx,
                            attempt=ticket.attempts)
            with sp:
                ticket.run()

    def _finish(self, ticket: FleetTransfer,
                error: Optional[BaseException] = None) -> None:
        with self._cond:
            tn = self._tenant_locked(ticket.tenant)
            if error is not None and \
                    ticket.attempts < self.max_attempts:
                logger.warning("fleet: %s attempt %d failed (%s); "
                               "requeueing", ticket.transfer_id,
                               ticket.attempts, error)
                tn.running -= 1
                self._running -= 1
                ticket.error = error
                ticket.state = "queued"
                ticket.worker = None
                ticket.dispatched_at = 0.0
                ticket.queued_at = time.perf_counter()
                tn.push(ticket, front=True)
                if ticket.tenant not in self._active:
                    self._active.appendleft(ticket.tenant)
                self._update_gauges_locked()
                self._cond.notify_all()
                return
            tn.running -= 1
            self._running -= 1
            ticket.finished_at = time.perf_counter()
            ticket.worker = None
            if error is None:
                ticket.state = "done"
                tn.done += 1
                self.stats.completed.inc()
            else:
                ticket.state = "failed"
                ticket.error = error
                tn.failed += 1
                self.stats.failed.inc()
                logger.error("fleet: %s failed: %s",
                             ticket.transfer_id, error)
            self._terminal_locked(ticket)
            self._update_gauges_locked()
            self._cond.notify_all()

    # -- introspection / autoscaling ----------------------------------------
    def _update_gauges_locked(self) -> None:
        queued = sum(tn.queued for tn in self._tenants.values())
        self.stats.queue_depth.set(queued)
        self.stats.inflight.set(self._running)
        self.stats.desired_workers.set(self._desired_workers_locked())
        debts = [tn.debt() for tn in self._tenants.values()]
        self.stats.tenant_debt_max.set(max(debts) if debts else 0.0)

    def _desired_workers_locked(self) -> int:
        pending = self._running + sum(
            tn.queued for tn in self._tenants.values())
        per = self._lanes_per_worker
        return max(1, -(-pending // per))

    def refresh_gauges(self) -> None:
        """Recompute queue_depth/inflight/desired_workers NOW.  Called
        from the backpressure tick (and free for any poller): the
        gauges otherwise refresh on scheduler events (submit, dispatch,
        completion), and a fleet that went idle between events would
        keep advertising its last busy desired_workers value."""
        with self._lock:
            self._update_gauges_locked()

    def desired_workers(self) -> int:
        with self._lock:
            return self._desired_workers_locked()

    def live_workers(self) -> int:
        with self._lock:
            return len(set(range(self._next_worker))
                       - self._dead_workers)

    def counts(self) -> dict[str, int]:
        with self._lock:
            out = {"queued": 0, "running": self._running, "done": 0,
                   "failed": 0, "shed": 0}
            for tn in self._tenants.values():
                out["queued"] += tn.queued
                out["done"] += tn.done
                out["failed"] += tn.failed
                out["shed"] += tn.shed
            return out

    def snapshot(self) -> dict:
        """The /debug/fleet payload: admission state, per-tenant debt,
        autoscaling hints, dispatch latency percentiles."""
        with self._lock:
            lats = [v * 1000.0 for v in self.dispatch_latencies]
            tenants = {
                tn.name: {
                    "weight": tn.weight,
                    "queued": tn.queued,
                    "running": tn.running,
                    "done": tn.done,
                    "failed": tn.failed,
                    "shed": tn.shed,
                    "service": tn.service,
                    "debt": round(tn.debt(), 3),
                }
                for tn in sorted(self._tenants.values(),
                                 key=lambda t: t.name)
            }
            snap = {
                "name": self.name,
                "workers": {
                    "configured": self._n_workers,
                    "lanes_per_worker": self._lanes_per_worker,
                    "dead": sorted(self._dead_workers),
                    "live": (self._next_worker
                             - len(self._dead_workers)),
                },
                "queued": sum(tn.queued
                              for tn in self._tenants.values()),
                "running": self._running,
                "dispatched": len(self.dispatch_log),
                "rebalanced": len(self.rebalance_log),
                "double_admissions": len(self.double_admissions),
                "desired_workers": self._desired_workers_locked(),
                "tenants": tenants,
                "dispatch_latency_ms": {
                    "p50": round(percentile(lats, 0.50), 3),
                    "p99": round(percentile(lats, 0.99), 3),
                    "count": len(lats),
                },
            }
        if self.backpressure is not None:
            snap["backpressure"] = self.backpressure.snapshot()
        return snap
