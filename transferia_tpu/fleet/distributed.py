"""Distributed fleet scheduler: a durable, preemptible admission plane.

PR 8's `FleetScheduler` runs worker SLOTS inside one Python process —
one crash loses every queued transfer, and N scheduler replicas can't
share a queue at all.  This module moves the queue into the
COORDINATOR (memory / filestore flock / s3 conditional writes;
`Coordinator.enqueue_ticket`/`claim_ticket`/`complete_ticket`), so:

- a scheduler restart resumes the queue exactly where it left off
  (tickets are durable; `submit` is idempotent by ticket id, which is
  also the no-double-admission guarantee across N replicas);
- workers are real PROCESSES (`trtpu worker`, fleet/worker.py) that
  claim tickets with the same lease + epoch-fencing rules as snapshot
  parts — a kill -9'd worker's ticket is reclaimed by a survivor after
  lease expiry, and the zombie's late completion is fenced;
- QoS priorities mean something: an INTERACTIVE arrival with no free
  lane REVOKES the lease of the lowest-priority in-flight ticket
  (`preempt_if_needed`).  The revoke bumps the claim epoch, the running
  worker notices at its next part boundary (its heartbeat renewal
  returns 0) and yields; part checkpointing + exactly-once sinks mean
  the preempted transfer later resumes from its committed parts with
  nothing lost or duplicated.

The WDRR pick (which claimable ticket a worker runs next) reuses the
in-process scheduler's fair-share semantics — same quantum/weight
deficits, same QoS cost factors (fleet/scheduler.py) — but runs over
the durable queue snapshot, worker-side (`WdrrPicker`), so workers
keep draining even while every scheduler replica is down.

Replay surfaces: `admission_log` (enqueue order), and the
coordinator-level claim/preempt logs the chaos `fleet_distributed`
mode records (chaos/invariants.AuditingCoordinator) — for a fixed seed
the three logs replay byte-identically.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from transferia_tpu.abstract.ticket import (
    FleetTicket,
    ticket_lease_expired,
)
from transferia_tpu.chaos.failpoints import failpoint
from transferia_tpu.coordinator.interface import Coordinator
from transferia_tpu.fleet.backpressure import BackpressureController
from transferia_tpu.fleet.scheduler import QOS_COST_FACTOR, QosClass
from transferia_tpu.stats import trace
from transferia_tpu.stats.registry import DistributedFleetStats, Metrics

logger = logging.getLogger(__name__)

DEFAULT_QUEUE = "fleet"

# Payload key carrying the admitting scheduler's span context across
# the process boundary (trace.wire_format form).  Payloads are opaque
# JSON the runner registry resolves; the dunder prefix keeps it out of
# any runner's parameter namespace.  FleetWorker._run_ticket adopts it,
# so a ticket's admission (scheduler process) and run (worker process)
# land on ONE trace in the merged fleet timeline (stats/fleetobs.py).
TICKET_TRACE_KEY = "__trace"


def charged_cost(ticket: FleetTicket) -> int:
    """Deficit units one ticket charges: cost x QoS factor — identical
    to FleetTransfer.charged_cost (fleet/scheduler.py), so fair share
    means the same thing in both fleets."""
    try:
        factor = QOS_COST_FACTOR[QosClass(ticket.qos)]
    except ValueError:
        factor = QOS_COST_FACTOR[QosClass.BATCH]
    return max(1, ticket.cost) * factor


class WdrrPicker:
    """Weighted deficit-round-robin pick over a durable queue snapshot.

    Mirrors the in-process scheduler's dispatch loop (same quantum /
    weight / charged-cost semantics), but stateless with respect to the
    queue itself: the caller passes the current claimable tickets and
    the picker only persists per-tenant deficits.  Tenants are visited
    in sorted-name order from a persistent cursor, ties break by
    durable seq — for a fixed queue snapshot the pick is a pure
    function of the picker state, which is what seed-exact chaos
    replay of the claim log relies on.

    `pick` never charges: the caller claims the candidate (CAS against
    the coordinator) and calls `charge` only on a WON claim — a lost
    race must not bill the tenant for a ticket someone else runs.
    """

    def __init__(self, tenant_weights: Optional[dict[str, float]] = None,
                 quantum: float = 1.0):
        self.quantum = quantum
        self._weights = dict(tenant_weights or {})
        self._deficits: dict[str, float] = {}
        self._cursor = 0

    def _weight(self, tenant: str) -> float:
        return float(self._weights.get(tenant, 1.0))

    def pick(self, claimable: list[FleetTicket]
             ) -> Optional[FleetTicket]:
        by_tenant: dict[str, list[FleetTicket]] = {}
        for t in claimable:
            by_tenant.setdefault(t.tenant, []).append(t)
        if not by_tenant:
            return None
        for heads in by_tenant.values():
            heads.sort(key=lambda t: (t.qos_rank, t.seq))
        names = sorted(by_tenant)
        start = self._cursor % len(names)
        guard = 0
        while True:
            guard += 1
            if guard > 100_000:  # pathological quantum/cost ratio
                logger.error("distributed DRR guard tripped; picking "
                             "cursor tenant head")
                return by_tenant[names[start]][0]
            idx = start % len(names)
            tenant = names[idx]
            head = by_tenant[tenant][0]
            deficit = self._deficits.get(tenant, 0.0)
            if deficit >= charged_cost(head):
                self._cursor = idx
                return head
            self._deficits[tenant] = deficit + \
                self.quantum * self._weight(tenant)
            start += 1

    def charge(self, ticket: FleetTicket) -> None:
        """Bill the tenant for a WON claim."""
        self._deficits[ticket.tenant] = \
            self._deficits.get(ticket.tenant, 0.0) - charged_cost(ticket)
        self._cursor += 1

    def reset_tenant(self, tenant: str) -> None:
        self._deficits.pop(tenant, None)


class DistributedFleetScheduler:
    """Admission + preemption over a coordinator-backed ticket queue.

    Holds NO queue state of its own: every decision reads the durable
    queue, so any number of replicas can run `submit`/`tick` against
    the same coordinator and a fresh replica picks up exactly where a
    dead one stopped (`resume()` is deliberately a read-only probe).
    """

    def __init__(self, coordinator: Coordinator,
                 queue: str = DEFAULT_QUEUE,
                 metrics: Optional[Metrics] = None,
                 tenant_queue_quota: int = 1024,
                 lanes_per_worker: int = 1,
                 backpressure: "Optional[BackpressureController | bool]"
                 = None,
                 capacity: Optional[Callable[[], int]] = None,
                 name: str = "fleet-dist"):
        if not coordinator.supports_ticket_queue():
            raise ValueError(
                f"coordinator {type(coordinator).__name__} has no "
                f"durable ticket queue; the distributed fleet needs "
                f"memory/filestore/s3")
        self.cp = coordinator
        self.queue = queue
        self.name = name
        self.metrics = metrics or Metrics()
        self.stats = DistributedFleetStats(self.metrics)
        self.tenant_queue_quota = tenant_queue_quota
        self.lanes_per_worker = max(1, lanes_per_worker)
        if backpressure is True:
            backpressure = BackpressureController(self.metrics)
        self.backpressure = backpressure or None
        # free-lane probe for the preemption decision: the supervisor's
        # live worker count x lanes.  None = the free-lane check is
        # SKIPPED and a queued high-priority arrival always preempts —
        # acceptable for single-worker tests, but a real deployment
        # should wire it (FleetAutoscaler does automatically) or idle
        # capacity won't save running work from needless revocation.
        self._capacity = capacity
        self._lock = threading.Lock()
        # replay surfaces (mirrors FleetScheduler.dispatch_log et al)
        self.admission_log: list[str] = []
        self.preempt_log: list[tuple] = []
        self.shed_log: list[tuple] = []
        # terminal-ticket retention GC cadence (tick may run at 1s;
        # pruning is a day-scale policy and on s3 each prune is real
        # DELETE traffic)
        self.gc_interval = 60.0
        self._last_gc = 0.0

    # -- admission -----------------------------------------------------------
    def submit(self, ticket: FleetTicket) -> str:
        """Admission gate + durable enqueue.  Returns "admitted" or a
        shed reason; raises when the enqueue RPC itself fails (the
        `fleet.enqueue` chaos site) — callers retry, and the idempotent
        enqueue makes the retry double-admission-proof."""
        adm_sp = trace.span("fleet_dist_admit",
                            ticket_id=ticket.ticket_id,
                            tenant=ticket.tenant, qos=ticket.qos)
        with adm_sp:
            hot = (self.backpressure.overloaded()
                   if self.backpressure else False)
            if hot:
                self.stats.shed.inc()
                with self._lock:
                    self.shed_log.append(
                        (ticket.ticket_id, "shed-backpressure"))
                if adm_sp:
                    adm_sp.add(decision="shed-backpressure")
                return "shed-backpressure"
            # one queue scan serves the quota check AND the gauge
            # refresh (each list is LIST + N GETs on the s3 backend)
            tickets = self.cp.list_tickets(self.queue)
            queued = sum(1 for t in tickets
                         if t.tenant == ticket.tenant
                         and t.state == "queued")
            if queued >= self.tenant_queue_quota:
                self.stats.shed.inc()
                with self._lock:
                    self.shed_log.append(
                        (ticket.ticket_id, "shed-tenant-quota"))
                if adm_sp:
                    adm_sp.add(decision="shed-tenant-quota")
                return "shed-tenant-quota"
            # stamp the admission trace onto the wire BEFORE the
            # enqueue: whichever worker process claims the ticket
            # adopts this context and joins the trace
            wire = trace.wire_format(trace.current_context())
            if wire and TICKET_TRACE_KEY not in ticket.payload:
                ticket.payload[TICKET_TRACE_KEY] = wire
            failpoint("fleet.enqueue")
            stored = self.cp.enqueue_ticket(self.queue, ticket)
            with self._lock:
                if stored.ticket_id not in self.admission_log:
                    self.admission_log.append(stored.ticket_id)
            if all(t.ticket_id != stored.ticket_id for t in tickets):
                # count only NEW admissions: an idempotent re-submit
                # (RPC-fault retry, replica failover) returns the
                # stored ticket and must not inflate the counter
                self.stats.enqueued.inc()
                tickets = tickets + [stored]
            self._refresh_gauges(tickets)
            if adm_sp:
                adm_sp.add(decision="admitted", seq=stored.seq)
            return "admitted"

    def resume(self) -> dict:
        """Failover probe: what a fresh replica inherits from the
        durable queue (counts only — nothing to rebuild, the queue IS
        the state)."""
        counts = self.counts()
        logger.info("scheduler %s resumed queue %r: %s", self.name,
                    self.queue, counts)
        return counts

    # -- preemption ----------------------------------------------------------
    def preempt_if_needed(self, tickets: Optional[list] = None
                          ) -> Optional[str]:
        """One preemption decision: when a queued ticket outranks some
        in-flight ticket and no lane is free, revoke the LOWEST-
        priority in-flight ticket's lease (ties: latest admitted).  The
        running worker yields at its next part boundary; the revoked
        ticket resumes later from its committed parts.  Returns the
        revoked ticket id, or None (nothing to do / revoke lost a
        race / revoke RPC faulted — dropped for this tick).  `tickets`
        lets a caller reuse a queue snapshot it already fetched (the
        revoke itself re-checks atomically at the coordinator)."""
        if tickets is None:
            tickets = self.cp.list_tickets(self.queue)
        now = time.time()
        queued = [t for t in tickets if t.state == "queued"]
        # only LIVE claims hold lanes: an expired-lease claim is a dead
        # worker's — it occupies nothing (its lane died with it) and
        # revoking it would "preempt" nobody while the actually-running
        # lowest-priority ticket kept its lane; the crash-reclaim path
        # owns expired claims
        claimed = [t for t in tickets
                   if t.state == "claimed"
                   and not ticket_lease_expired(t.to_json(), now)]
        if not queued or not claimed:
            return None
        want = min(t.qos_rank for t in queued)
        victim = max(claimed, key=lambda t: (t.qos_rank, t.seq))
        if victim.qos_rank <= want:
            return None  # nothing in flight outranked by the arrival
        if self._capacity is not None:
            free = self._capacity() * self.lanes_per_worker \
                - len(claimed)
            if free > 0:
                return None  # a lane is free: no need to preempt
        sp = trace.span("fleet_preempt", ticket_id=victim.ticket_id,
                        victim_qos=victim.qos,
                        holder=victim.claimed_by)
        with sp:
            try:
                failpoint("fleet.preempt")
                revoked = self.cp.revoke_ticket(self.queue,
                                                victim.ticket_id)
            except Exception as e:
                # a faulted revoke is dropped whole — never
                # half-applied; the arrival waits one lane-drain longer
                logger.warning("preempt of %s dropped (revoke fault: "
                               "%s)", victim.ticket_id, e)
                if sp:
                    sp.add(outcome="dropped")
                return None
            if revoked is None:
                if sp:
                    sp.add(outcome="lost-race")
                return None  # victim completed/yielded concurrently
            self.stats.preemptions.inc()
            with self._lock:
                self.preempt_log.append(
                    (revoked.ticket_id, revoked.preempted_from,
                     revoked.claim_epoch))
            if sp:
                sp.add(outcome="revoked", epoch=revoked.claim_epoch)
            logger.info(
                "preempted %s (qos=%s) from worker %s for a rank-%d "
                "arrival", revoked.ticket_id, revoked.qos,
                revoked.preempted_from, want)
            return revoked.ticket_id

    # -- introspection / autoscaling ----------------------------------------
    def tick(self) -> None:
        """Periodic maintenance (the autoscaler loop drives this):
        one queue snapshot feeds both the preemption decision and the
        gauge refresh — each list is LIST + N GETs on the s3 backend,
        so a 1s tick must not scan the queue four times.  A revoke
        flips one ticket claimed→queued after the snapshot; pending
        (and so desired_workers) is unchanged by that.  Terminal-ticket
        retention GC rides the same loop at its own (60s) cadence so
        multi-day fleets keep the queue — and every s3 poll — O(active)."""
        tickets = self.cp.list_tickets(self.queue)
        self.preempt_if_needed(tickets)
        self._refresh_gauges(tickets)
        now = time.time()
        if now - self._last_gc >= self.gc_interval:
            self._last_gc = now
            try:
                pruned = self.cp.gc_tickets(self.queue)
            except Exception as e:
                logger.warning("ticket GC failed (retrying next "
                               "cycle): %s", e)
                return
            if pruned:
                self.stats.gc_pruned.inc(pruned)
                logger.info("ticket GC pruned %d terminal ticket(s) "
                            "from %r", pruned, self.queue)
            # observability-segment retention rides the same cadence
            # (stats/fleetobs.py): the SCHEDULER prunes fleet-wide so
            # a crashed worker's final segments still age out even
            # though no exporter of that process will ever GC again
            try:
                from transferia_tpu.stats import fleetobs

                self.cp.gc_obs_segments(fleetobs.default_scope())
            except Exception as e:  # advisory, like all obs work
                logger.debug("obs segment GC failed (retrying next "
                             "cycle): %s", e)

    def counts(self, tickets: Optional[list] = None) -> dict[str, int]:
        out = {"queued": 0, "claimed": 0, "done": 0, "failed": 0}
        if tickets is None:
            tickets = self.cp.list_tickets(self.queue)
        for t in tickets:
            out[t.state] = out.get(t.state, 0) + 1
        return out

    def _desired(self, pending: int) -> int:
        """THE scaling-hint formula (ceil-divide with a floor of one):
        single definition so the gauge, the autoscaler input, and
        /debug/fleet can never silently diverge."""
        return max(1, -(-pending // self.lanes_per_worker))

    def desired_workers(self) -> int:
        """The autoscaling hint: lanes needed for the current pending
        set (queued + claimed), floor 1.  Recomputed from the durable
        queue on EVERY read — completion and the backpressure tick see
        a fresh value, never a stale last-busy one."""
        c = self.counts()
        return self._desired(c["queued"] + c["claimed"])

    def refresh_gauges(self) -> None:
        self._refresh_gauges()

    def _refresh_gauges(self, tickets: Optional[list] = None) -> None:
        c = self.counts(tickets)
        self.stats.queued.set(c["queued"])
        self.stats.inflight.set(c["claimed"])
        self.stats.desired_workers.set(
            self._desired(c["queued"] + c["claimed"]))

    def drain(self, timeout: Optional[float] = None,
              poll: float = 0.05) -> bool:
        """Block until every ticket is terminal.  False on timeout."""
        deadline = (time.monotonic() + timeout) if timeout else None
        while True:
            tickets = self.cp.list_tickets(self.queue)
            # an EMPTY queue is drained (nothing was ever admitted, or
            # everything was shed) — polling it to timeout would report
            # a false failure
            if all(t.terminal for t in tickets):
                self._refresh_gauges(tickets)
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(poll)

    def snapshot(self) -> dict:
        """The /debug/fleet payload for the distributed plane."""
        tickets = self.cp.list_tickets(self.queue)
        tenants: dict[str, dict] = {}
        counts = {"queued": 0, "claimed": 0, "done": 0, "failed": 0}
        for t in tickets:
            counts[t.state] = counts.get(t.state, 0) + 1
            tn = tenants.setdefault(t.tenant, {
                "queued": 0, "claimed": 0, "done": 0, "failed": 0,
                "preemptions": 0, "attempts": 0})
            tn[t.state] = tn.get(t.state, 0) + 1
            tn["preemptions"] += t.preemptions
            tn["attempts"] += t.attempts
        with self._lock:
            adm, pre, shed = (len(self.admission_log),
                              len(self.preempt_log),
                              len(self.shed_log))
        pending = counts["queued"] + counts["claimed"]
        snap = {
            "name": self.name,
            "kind": "distributed",
            "queue": self.queue,
            "tickets": counts,
            "tenants": dict(sorted(tenants.items())),
            "admitted": adm,
            "preemptions": pre,
            "shed": shed,
            "desired_workers": self._desired(pending),
        }
        if self.backpressure is not None:
            snap["backpressure"] = self.backpressure.snapshot()
        return snap

    # the fleet registry (fleet/__init__.py) serves /debug/fleet from
    # live schedulers; distributed ones register the same way
    def register(self) -> "DistributedFleetScheduler":
        from transferia_tpu import fleet as fleet_mod

        fleet_mod.register_scheduler(self)
        return self

    def unregister(self) -> None:
        from transferia_tpu import fleet as fleet_mod

        fleet_mod.unregister_scheduler(self)
