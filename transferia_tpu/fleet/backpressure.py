"""Backpressure: load signals for the fleet scheduler's admission gate.

The data plane already exports the gauges that say when a worker is
hot (stats/registry.py): `decode_readahead_inflight_bytes` piles up
when host decode outruns the downstream, `sinker_inflight_rows` when
the sink write path lags, `dispatch_compression_ratio` collapses
toward 1.0 when the device wire is shipping flat buffers (the link is
then the bottleneck, not compute), and the scheduler's own
`fleet_queue_depth` grows when admission outruns dispatch.  This
module folds those into one shed/resume decision with hysteresis, so
a hot worker sheds NEW admissions instead of thrashing the work it
already holds — and resumes only after the signal has genuinely
drained, not on the first dip below the high watermark.

Each signal carries its own (high, low) watermark pair and latches
independently: pressure starts at `value >= high` and clears only at
`value <= low`.  The controller is overloaded while any signal is
latched.  `inverted` signals (compression ratio) latch when the value
FALLS to the high-pressure mark and clear when it recovers.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from transferia_tpu.runtime import lockwatch
from transferia_tpu.stats.registry import Metrics

logger = logging.getLogger(__name__)


@dataclass
class SignalSpec:
    """One load signal: a metric name plus its hysteresis band.

    `inverted=True` means LOW values signal pressure (the compression
    ratio: a healthy encoded wire sits well above 1.0); the band is
    then (high=enter-pressure-at-or-below, low=leave-at-or-above).
    `min_activity` gates inverted signals on a companion counter — a
    ratio gauge that never moved (no dispatches yet) is idle, not hot.
    """

    name: str
    metric: str
    high: float
    low: float
    inverted: bool = False
    activity_metric: str = ""
    min_activity: float = 0.0


# The default signal table (ISSUE: DeviceStats/readahead/interchange
# gauges -> admission decisions).  Watermarks are deliberately lax —
# operators tune them via FleetTuning/env; the defaults only catch
# order-of-magnitude blowups.
DEFAULT_SIGNALS = (
    SignalSpec("readahead_inflight", "decode_readahead_inflight_bytes",
               high=1 << 30, low=1 << 28),
    SignalSpec("readahead_depth", "decode_readahead_depth",
               high=64, low=16),
    SignalSpec("sink_inflight_rows", "sinker_inflight_rows",
               high=2_000_000, low=500_000),
    SignalSpec("fleet_queue_depth", "fleet_queue_depth",
               high=4096, low=1024),
    # link honesty: a compressed wire showing ~1.0 is shipping flat
    # buffers — the link is saturating for nothing.  Gated on actual
    # dispatch traffic so an idle pipeline never reads as hot.
    SignalSpec("dispatch_ratio", "dispatch_compression_ratio",
               high=1.05, low=1.5, inverted=True,
               activity_metric="h2d_encoded_bytes",
               min_activity=float(64 << 20)),
)


@dataclass
class SignalState:
    spec: SignalSpec
    value: float = 0.0
    latched: bool = False
    transitions: int = 0


class BackpressureController:
    """Hysteresis gate over load gauges in a Metrics registry.

    `overloaded()` re-reads every signal and returns whether any is
    latched.  A `probe` callable (tests, remote workers) overrides the
    metrics read: it receives the metric name and returns the value.
    """

    def __init__(self, metrics: Optional[Metrics] = None,
                 signals: tuple[SignalSpec, ...] = DEFAULT_SIGNALS,
                 probe: Optional[Callable[[str], float]] = None):
        self.metrics = metrics or Metrics()
        self._probe = probe
        self._lock = lockwatch.named_lock("fleet.backpressure")
        self._states = [SignalState(s) for s in signals]
        # tick listeners: called (outside the signal lock) on every
        # overloaded() evaluation — the scheduler hangs its gauge
        # refresh here so fleet_queue_depth/desired_workers are fresh
        # at the exact moment this controller reads them, not stale
        # from the last dispatch
        self._tick_listeners: list[Callable[[], None]] = []
        # external latches (the SLO alert hook): named pressure sources
        # outside the gauge table, held until explicitly cleared
        self._external: dict[str, str] = {}

    def add_tick_listener(self, cb: Callable[[], None]) -> None:
        with self._lock:
            if cb not in self._tick_listeners:
                self._tick_listeners.append(cb)

    def remove_tick_listener(self, cb: Callable[[], None]) -> None:
        """Unhook on scheduler shutdown: a long-lived shared controller
        must not keep dead schedulers alive (and writing stale gauges)
        through their listener references."""
        with self._lock:
            if cb in self._tick_listeners:
                self._tick_listeners.remove(cb)

    def _read(self, metric: str) -> float:
        if self._probe is not None:
            return float(self._probe(metric))
        return float(self.metrics.value(metric))

    def overloaded(self) -> bool:
        """Re-evaluate every signal; True while any is latched."""
        with self._lock:
            listeners = list(self._tick_listeners)
        for cb in listeners:
            try:
                cb()
            except Exception as e:
                # a broken listener must not block admission decisions
                logger.debug("backpressure tick listener failed: %s", e)
        with self._lock:
            hot = False
            for st in self._states:
                s = st.spec
                st.value = self._read(s.metric)
                if s.inverted:
                    if s.activity_metric and \
                            self._read(s.activity_metric) < s.min_activity:
                        # no traffic yet: an untouched ratio gauge
                        # (0.0) must not read as a collapsed wire
                        if st.latched:
                            st.latched = False
                            st.transitions += 1
                        continue
                    enter = st.value <= s.high
                    leave = st.value >= s.low
                else:
                    enter = st.value >= s.high
                    leave = st.value <= s.low
                if not st.latched and enter:
                    st.latched = True
                    st.transitions += 1
                elif st.latched and leave:
                    st.latched = False
                    st.transitions += 1
                hot = hot or st.latched
            return hot or bool(self._external)

    def latch_external(self, name: str, reason: str = "") -> None:
        """Latch a named external pressure source (an SLO objective
        burning budget sheds new admissions until it recovers)."""
        with self._lock:
            if name not in self._external:
                logger.info("backpressure: external latch %s (%s)",
                            name, reason or "-")
            self._external[name] = reason

    def clear_external(self, name: str) -> None:
        with self._lock:
            if self._external.pop(name, None) is not None:
                logger.info("backpressure: external latch %s cleared",
                            name)

    def snapshot(self) -> dict:
        """Per-signal values/latch states for /debug/fleet."""
        with self._lock:
            out = {
                st.spec.name: {
                    "value": st.value,
                    "latched": st.latched,
                    "high": st.spec.high,
                    "low": st.spec.low,
                    "inverted": st.spec.inverted,
                    "transitions": st.transitions,
                }
                for st in self._states
            }
            for name, reason in sorted(self._external.items()):
                out[f"external:{name}"] = {
                    "value": 1.0, "latched": True, "high": 1.0,
                    "low": 0.0, "inverted": False, "transitions": 0,
                    "reason": reason,
                }
            return out

    def latched_signals(self) -> list[str]:
        with self._lock:
            return [st.spec.name for st in self._states if st.latched] \
                + [f"external:{n}" for n in sorted(self._external)]
