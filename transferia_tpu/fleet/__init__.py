"""Fleet control plane: schedule N tenants x M transfers over a
bounded worker pool (ROADMAP item 3), in-process or distributed.

- `scheduler.py` — in-process plane: admission control (tenant queue
  quotas + backpressure shed), weighted deficit-round-robin fair share
  with per-transfer QoS classes, bounded in-flight dispatch onto worker
  slots, kill/rebalance recovery, autoscaling hints.
- `distributed.py` — the durable plane: the admission queue lives in
  the COORDINATOR (memory/filestore/s3 tickets with lease + epoch
  fencing), schedulers fail over and never double-admit, and QoS
  priorities preempt via lease revocation.
- `worker.py` — `trtpu worker`: a supervised worker process claiming
  tickets (WDRR), heartbeating its lease, draining on SIGTERM; plus
  the `WorkerSupervisor` (thread/process modes).
- `autoscaler.py` — the elastic loop consuming `desired_workers` with
  hysteresis: sustained-demand scale-up, idle-drain scale-down.
- `backpressure.py` — hysteresis gate over the data-plane load gauges
  (readahead bytes/depth, sink in-flight rows, dispatch compression
  ratio, fleet queue depth).
- `bench.py` — `trtpu fleet bench` / `bench.py --fleet`: 100+
  concurrent sample->memory transfers; p50/p99 dispatch latency and
  the Jain fairness index are tracked bench metrics.

Live schedulers (and autoscalers) register here so the health port can
serve `/debug/fleet` without the CLI holding a reference.
"""

from __future__ import annotations

import threading

from transferia_tpu.fleet.backpressure import (  # noqa: F401
    BackpressureController,
    SignalSpec,
)
from transferia_tpu.fleet.scheduler import (  # noqa: F401
    FleetScheduler,
    FleetTransfer,
    QosClass,
)

_registry_lock = threading.Lock()
_SCHEDULERS: list = []
_AUTOSCALERS: list = []


def register_scheduler(sched) -> None:
    with _registry_lock:
        if sched not in _SCHEDULERS:
            _SCHEDULERS.append(sched)


def unregister_scheduler(sched) -> None:
    with _registry_lock:
        if sched in _SCHEDULERS:
            _SCHEDULERS.remove(sched)


def register_autoscaler(scaler) -> None:
    with _registry_lock:
        if scaler not in _AUTOSCALERS:
            _AUTOSCALERS.append(scaler)


def unregister_autoscaler(scaler) -> None:
    with _registry_lock:
        if scaler in _AUTOSCALERS:
            _AUTOSCALERS.remove(scaler)


def _commit_rollup() -> dict:
    """The staged-commit ledger totals the fleet operator watches next
    to the queue state: granted publishes, fenced zombie publishes, and
    rows the dedup window dropped pre-publish (stats/ledger.py; full
    per-transfer detail stays on /debug/ledger)."""
    from transferia_tpu.stats.ledger import LEDGER

    totals = LEDGER.snapshot()["totals"]
    return {
        "commit_parts": totals["commits"],
        "commit_fences": totals["commit_fences"],
        "dedup_rows_dropped": totals["dedup_rows_dropped"],
    }


def debug_snapshot() -> dict:
    """The `/debug/fleet` payload: every live scheduler's and
    autoscaler's snapshot, the commit-ledger rollup, and — when an obs
    runtime is registered (`trtpu worker`, tests) — per-worker
    heartbeat liveness ages from `get_operation_health`, so an
    operator sees a stale worker long before its lease expires."""
    from transferia_tpu.stats import fleetobs

    with _registry_lock:
        scheds = list(_SCHEDULERS)
        scalers = list(_AUTOSCALERS)
    out = {
        "schedulers": [s.snapshot() for s in scheds],
        "autoscalers": [a.snapshot() for a in scalers],
        "commits": _commit_rollup(),
    }
    liveness = fleetobs.worker_liveness()
    if liveness is not None:
        out["workers"] = liveness
    return out
