"""Fleet control plane: schedule N tenants x M transfers over a
bounded worker pool (ROADMAP item 3).

- `scheduler.py` — admission control (tenant queue quotas +
  backpressure shed), weighted deficit-round-robin fair share with
  per-transfer QoS classes, bounded in-flight dispatch onto worker
  slots, kill/rebalance recovery, autoscaling hints.
- `backpressure.py` — hysteresis gate over the data-plane load gauges
  (readahead bytes/depth, sink in-flight rows, dispatch compression
  ratio, fleet queue depth).
- `bench.py` — `trtpu fleet bench` / `bench.py --fleet`: 100+
  concurrent sample->memory transfers; p50/p99 dispatch latency and
  the Jain fairness index are tracked bench metrics.

Live schedulers register here so the health port can serve
`/debug/fleet` without the CLI holding a reference.
"""

from __future__ import annotations

import threading

from transferia_tpu.fleet.backpressure import (  # noqa: F401
    BackpressureController,
    SignalSpec,
)
from transferia_tpu.fleet.scheduler import (  # noqa: F401
    FleetScheduler,
    FleetTransfer,
    QosClass,
)

_registry_lock = threading.Lock()
_SCHEDULERS: list = []


def register_scheduler(sched) -> None:
    with _registry_lock:
        if sched not in _SCHEDULERS:
            _SCHEDULERS.append(sched)


def unregister_scheduler(sched) -> None:
    with _registry_lock:
        if sched in _SCHEDULERS:
            _SCHEDULERS.remove(sched)


def debug_snapshot() -> dict:
    """The `/debug/fleet` payload: every live scheduler's snapshot."""
    with _registry_lock:
        scheds = list(_SCHEDULERS)
    return {
        "schedulers": [s.snapshot() for s in scheds],
    }
