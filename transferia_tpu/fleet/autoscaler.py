"""Elastic autoscaler: turn the fleet's `desired_workers` hint into
actual worker processes, with hysteresis.

PR 8 computed `desired_workers` as a gauge and stopped there — nothing
consumed it.  This loop closes the circuit: each step it reads the
durable queue's pending depth through the scheduler
(fleet/distributed.py `desired_workers()`, recomputed from the queue on
every read), clamps to [min_workers, max_workers], and drives the
`WorkerSupervisor` (fleet/worker.py) toward the target —

- **scale-up** only after the desire has been SUSTAINED for
  `scale_up_after` consecutive steps (a one-tick burst of admissions
  must not fork a worker that will be idle before it finishes
  importing), and then all the way to the sustained target;
- **scale-down** only after `scale_down_after` consecutive low steps,
  and then by ONE worker per step, by draining an IDLE worker
  (SIGTERM → part-boundary yield → release → exit) — a busy fleet is
  never shrunk by killing work, and the gradual drain keeps a noisy
  queue depth from sawtoothing the pool;
- crashed workers are reaped each step and re-spawned by the same
  scale_to call that enforces the floor, so `min_workers` is also the
  crash-replacement guarantee.

Each step also runs the scheduler's `tick()` (gauge refresh + one
preemption decision), so the INTERACTIVE-preempts-SCAVENGER rule fires
on the same cadence as scaling.  `step()` is synchronous and
side-effect-complete — the unit tests drive it directly; `start()`
wraps it in the background loop the CLI uses.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from transferia_tpu.fleet.distributed import DistributedFleetScheduler
from transferia_tpu.fleet.worker import WorkerSupervisor
from transferia_tpu.stats import trace

logger = logging.getLogger(__name__)


class FleetAutoscaler:
    def __init__(self, scheduler: DistributedFleetScheduler,
                 supervisor: WorkerSupervisor,
                 min_workers: int = 1, max_workers: int = 8,
                 scale_up_after: int = 2, scale_down_after: int = 5,
                 interval: float = 1.0, name: str = "fleet-scaler",
                 leader: "Optional[object]" = None):
        if min_workers < 0 or max_workers < max(1, min_workers):
            raise ValueError("need 0 <= min_workers <= max_workers "
                             "and max_workers >= 1")
        self.scheduler = scheduler
        self.supervisor = supervisor
        # scheduler-replica leader election (fleet/leader.py): with a
        # LeaderLease only ONE replica runs the preemption/autoscale
        # tick per period; standby replicas keep reaping their own
        # local workers (a crashed subprocess is this replica's to
        # collect regardless of who leads) and take over automatically
        # when the leader's lease expires
        self.leader = leader
        # close the preemption loop: without a capacity probe the
        # scheduler skips its free-lane check and would revoke running
        # work even while an idle worker could absorb the arrival
        # within one claim poll — the supervisor IS the probe
        if getattr(scheduler, "_capacity", None) is None and \
                hasattr(supervisor, "live_workers"):
            scheduler._capacity = supervisor.live_workers
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.scale_up_after = max(1, scale_up_after)
        self.scale_down_after = max(1, scale_down_after)
        self.interval = interval
        self.name = name
        self._up_streak = 0
        self._down_streak = 0
        self._steps = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._last_action = "none"
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def target(self) -> int:
        """The clamped desire, recomputed from the durable queue."""
        return min(self.max_workers,
                   max(self.min_workers,
                       self.scheduler.desired_workers()))

    def step(self) -> dict:
        """One synchronous control step (reap → tick → hysteresis →
        scale).  Returns the decision record `snapshot()` also shows.
        With a leader lease configured, a standby replica only reaps —
        it neither ticks the scheduler nor scales."""
        self._steps += 1
        self.supervisor.reap()
        if self.leader is not None and not self.leader.ensure():
            self._up_streak = self._down_streak = 0
            self._last_action = "standby"
            self.scheduler.stats.live_workers.set(
                self.supervisor.live_workers())
            return {"desired": None,
                    "live": self.supervisor.live_workers(),
                    "action": "standby"}
        self.scheduler.tick()
        desired = self.target()
        live = self.supervisor.live_workers()
        action = "hold"
        if live < self.min_workers:
            # floor enforcement / crash replacement bypasses hysteresis:
            # a fleet below its floor is not a trend, it is an outage
            self.supervisor.scale_to(self.min_workers)
            self._up_streak = self._down_streak = 0
            self._scale_ups += 1
            self.scheduler.stats.autoscale_ups.inc()
            action = f"floor:{self.min_workers}"
        elif desired > live:
            self._down_streak = 0
            self._up_streak += 1
            if self._up_streak >= self.scale_up_after:
                self.supervisor.scale_to(desired)
                self._up_streak = 0
                self._scale_ups += 1
                self.scheduler.stats.autoscale_ups.inc()
                action = f"up:{desired}"
        elif desired < live:
            self._up_streak = 0
            self._down_streak += 1
            if self._down_streak >= self.scale_down_after:
                retired = self.supervisor.retire_one()
                self._down_streak = 0
                if retired is not None:
                    self._scale_downs += 1
                    self.scheduler.stats.autoscale_downs.inc()
                    action = f"down:w{retired}"
        else:
            self._up_streak = self._down_streak = 0
        self._last_action = action
        if action != "hold":
            trace.instant("fleet_autoscale", action=action,
                          desired=desired, live=live)
            logger.info("%s step %d: desired=%d live=%d -> %s",
                        self.name, self._steps, desired, live, action)
        self.scheduler.stats.live_workers.set(
            self.supervisor.live_workers())
        return {"desired": desired, "live": live, "action": action}

    # -- background loop -----------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        from transferia_tpu import fleet as fleet_mod

        fleet_mod.register_autoscaler(self)
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.step()
                except Exception:
                    logger.exception("%s step failed", self.name)

        self._thread = threading.Thread(target=loop, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self.leader is not None:
            # graceful step-down: a standby replica takes over on its
            # next tick instead of waiting out the lease TTL
            self.leader.release()
        from transferia_tpu import fleet as fleet_mod

        fleet_mod.unregister_autoscaler(self)

    def snapshot(self) -> dict:
        """/debug/fleet payload: the scaling policy's live state."""
        if self.leader is not None:
            leader = {"is_leader": self.leader.is_leader(),
                      "replica_id": self.leader.replica_id}
        else:
            leader = None
        return {
            "name": self.name,
            "leader": leader,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "desired": self.target(),
            "live": self.supervisor.live_workers(),
            "draining": self.supervisor.draining_workers(),
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "scale_up_after": self.scale_up_after,
            "scale_down_after": self.scale_down_after,
            "steps": self._steps,
            "scale_ups": self._scale_ups,
            "scale_downs": self._scale_downs,
            "last_action": self._last_action,
        }
