"""Batch-serializer machinery: buffer pool, concurrency threshold,
ordered parallel chunking.

Reference parity: pkg/serializer/batch.go:28 (batchSerializer with
Concurrency/Threshold and a buffer pool; DefaultBatchSerializerThreshold
= 25000), pkg/serializer/buffer/pool.go:8 (bounded reusable buffers),
pkg/serializer/queue/debezium_multithreading.go (Split/MergeBack ordered
parallel serialization).

Python note: chunked thread concurrency pays off for encoders that leave
the GIL (arrow/parquet, zlib, bytes joins) and for the debezium emitter's
per-row packing; pure-Python json loops gain little but keep the same
ordered-merge semantics, so behavior matches the reference either way.
"""

from __future__ import annotations

import io
import logging
import os
import queue as _queue
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Sequence

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.interfaces import Batch
from transferia_tpu.serializers.formats import (
    BatchSerializer,
    QueueSerializer,
    _rows_of,
)
from transferia_tpu.stats import trace

logger = logging.getLogger(__name__)

DEFAULT_THRESHOLD = 25_000   # batch.go:19 DefaultBatchSerializerThreshold


class BufferPool:
    """Bounded pool of reusable byte buffers (buffer/pool.go:8)."""

    def __init__(self, size: int = 1):
        size = max(1, size)
        self._pool: _queue.Queue[io.BytesIO] = _queue.Queue(maxsize=size)
        for _ in range(size):
            self._pool.put(io.BytesIO())

    def get(self) -> io.BytesIO:
        buf = self._pool.get()
        buf.seek(0)
        buf.truncate(0)
        return buf

    def put(self, buf: io.BytesIO) -> None:
        self._pool.put(buf)


def split_rows(rows: Sequence[ChangeItem], chunk: int
               ) -> list[Sequence[ChangeItem]]:
    """Order-preserving chunking (debezium_multithreading.go Split)."""
    if chunk <= 0:
        return [rows]
    return [rows[i:i + chunk] for i in range(0, len(rows), chunk)]


class ConcurrentBatchSerializer(BatchSerializer):
    """Wraps a row-shaped serializer with threshold-gated parallel
    chunking and ordered reassembly (batch.go:28 batchSerializer).

    Only valid for formats whose outputs concatenate (json lines, csv
    without header, raw) — whole-file formats like parquet must not be
    wrapped."""

    def __init__(self, inner: BatchSerializer,
                 concurrency: int = 0,
                 threshold: int = DEFAULT_THRESHOLD,
                 separator: bytes = b""):
        self.inner = inner
        self.concurrency = concurrency or (os.cpu_count() or 1)
        self.threshold = threshold
        self.separator = separator
        self._buffers = BufferPool(self.concurrency)

    def serialize(self, batch: Batch) -> bytes:
        rows = _rows_of(batch)
        sp = trace.span("serialize")
        if sp:
            sp.add(rows=len(rows))
        with sp:
            return self._serialize_rows(rows)

    def _serialize_rows(self, rows) -> bytes:
        if self.concurrency < 2 or len(rows) <= self.threshold:
            return self.inner.serialize(rows)
        chunk = (len(rows) + self.concurrency - 1) // self.concurrency
        parts = split_rows(rows, chunk)
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            encoded = list(pool.map(self.inner.serialize, parts))
        buf = self._buffers.get()
        try:
            first = True
            for piece in encoded:
                if not piece:
                    continue
                if not first and self.separator:
                    buf.write(self.separator)
                buf.write(piece)
                first = False
            return buf.getvalue()
        finally:
            self._buffers.put(buf)


class ConcurrentQueueSerializer(QueueSerializer):
    """Ordered parallel (key, value) serialization for brokers
    (debezium_multithreading.go: Split -> worker pool -> MergeBack).

    `make_inner` builds one single-thread serializer per worker so inner
    state (schema-registry sessions, packers) is never shared across
    threads."""

    def __init__(self, make_inner: Callable[[], QueueSerializer],
                 concurrency: int = 0,
                 threshold: int = DEFAULT_THRESHOLD):
        self.make_inner = make_inner
        self.concurrency = concurrency or (os.cpu_count() or 1)
        self.threshold = threshold
        # persistent per-worker serializers: emitter state (SR schema-id
        # caches, packers) survives across pushes, and worker i is the
        # only user of _inners[i] within a call
        self._inners: list[QueueSerializer] = []

    def _inner(self, i: int) -> QueueSerializer:
        while len(self._inners) <= i:
            self._inners.append(self.make_inner())
        return self._inners[i]

    def serialize_messages(self, batch: Batch):
        rows = _rows_of(batch)
        sp = trace.span("serialize")
        if sp:
            sp.add(rows=len(rows))
        with sp:
            return self._serialize_rows(rows)

    def _serialize_rows(self, rows):
        if self.concurrency < 2 or len(rows) <= self.threshold:
            return self._inner(0).serialize_messages(rows)
        chunk = (len(rows) + self.concurrency - 1) // self.concurrency
        parts = split_rows(rows, chunk)
        for i in range(len(parts)):
            self._inner(i)  # build outside the pool: no lazy-append race

        def work(args):
            i, part = args
            return self._inners[i].serialize_messages(part)

        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            merged = []
            for out in pool.map(work, enumerate(parts)):  # ordered merge
                merged.extend(out)
            return merged


class RawColumnQueueSerializer(QueueSerializer):
    """One message per row: the named column's raw bytes, no key
    (queue/raw_column_serializer.go)."""

    def __init__(self, column: str):
        self.column = column

    def serialize_messages(self, batch: Batch):
        out = []
        skipped = 0
        last_error: Optional[str] = None
        for it in _rows_of(batch):
            if self.column not in it.column_names:
                skipped += 1
                last_error = f"column {self.column!r} not found"
                continue
            v = it.value(self.column)
            if v is None:
                out.append((None, b""))
                continue
            if isinstance(v, str):
                v = v.encode()
            elif not isinstance(v, (bytes, bytearray)):
                v = str(v).encode()
            out.append((None, bytes(v)))
        if skipped:
            if not out:
                # every row lacked the column: almost certainly a
                # misconfigured column name — fail loudly instead of
                # silently acking dropped data
                raise KeyError(
                    f"raw_column: no row carried column "
                    f"{self.column!r} ({skipped} rows dropped)")
            logger.warning("raw_column: %d rows skipped (last error: %s)",
                           skipped, last_error)
        return out
