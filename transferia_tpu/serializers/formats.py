"""Serializer implementations (pkg/serializer/{json,csv,parquet,raw}.go and
serializer/queue/{debezium,json,native,mirror}*.go)."""

from __future__ import annotations

import abc
import csv
import io
import json
from typing import Any, Optional, Sequence

from transferia_tpu.abstract.change_item import ChangeItem
from transferia_tpu.abstract.interfaces import Batch, is_columnar
from transferia_tpu.columnar.batch import ColumnBatch


def _rows_of(batch: Batch) -> list[ChangeItem]:
    if is_columnar(batch):
        return batch.to_rows()
    return [it for it in batch if it.is_row_event()]


class BatchSerializer(abc.ABC):
    """Whole-batch byte encoder (serializer/interface.go:17)."""

    @abc.abstractmethod
    def serialize(self, batch: Batch) -> bytes:
        ...


class JsonSerializer(BatchSerializer):
    """JSON lines of row value maps."""

    def __init__(self, add_meta: bool = False):
        self.add_meta = add_meta

    def serialize(self, batch: Batch) -> bytes:
        buf = io.BytesIO()
        for it in _rows_of(batch):
            row: dict[str, Any] = it.as_dict()
            if self.add_meta:
                row = {"__kind": it.kind.value,
                       "__table": str(it.table_id), **row}
            buf.write(json.dumps(row, separators=(",", ":"),
                                 default=_json_default).encode())
            buf.write(b"\n")
        return buf.getvalue()


def _json_default(v):
    if isinstance(v, bytes):
        return v.decode("utf-8", errors="replace")
    return str(v)


class CsvSerializer(BatchSerializer):
    """RFC-4180 CSV (pkg/csv splitter counterpart on the write side)."""

    def __init__(self, header: bool = False, delimiter: str = ","):
        self.header = header
        self.delimiter = delimiter

    def serialize(self, batch: Batch) -> bytes:
        out = io.StringIO()
        w = csv.writer(out, delimiter=self.delimiter, lineterminator="\n")
        rows = _rows_of(batch)
        if not rows:
            return b""
        if self.header:
            w.writerow(rows[0].column_names)
        for it in rows:
            w.writerow([
                v.decode("utf-8", "replace") if isinstance(v, bytes)
                else ("" if v is None else v)
                for v in it.column_values
            ])
        return out.getvalue().encode()


class ParquetSerializer(BatchSerializer):
    """Arrow-native parquet encoding — columnar batches never re-row."""

    def __init__(self, compression: str = "snappy"):
        self.compression = compression

    def serialize(self, batch: Batch) -> bytes:
        import pyarrow as pa
        import pyarrow.parquet as pq

        if not is_columnar(batch):
            rows = _rows_of(batch)
            if not rows:
                return b""
            batch = ColumnBatch.from_rows(rows)
        rb = batch.to_arrow()
        sink = io.BytesIO()
        pq.write_table(pa.Table.from_batches([rb]), sink,
                       compression=self.compression)
        return sink.getvalue()


class RawSerializer(BatchSerializer):
    """First column's raw bytes, newline-joined (serializer/raw.go)."""

    def __init__(self, column: str = "data"):
        self.column = column

    def serialize(self, batch: Batch) -> bytes:
        out = io.BytesIO()
        for it in _rows_of(batch):
            v = it.value(self.column)
            if v is None and it.column_values:
                v = it.column_values[0]
            if isinstance(v, str):
                v = v.encode()
            out.write(v or b"")
            out.write(b"\n")
        return out.getvalue()


class QueueSerializer(abc.ABC):
    """Per-row (key, value) pairs for message brokers."""

    @abc.abstractmethod
    def serialize_messages(self, batch: Batch
                           ) -> list[tuple[bytes, Optional[bytes]]]:
        ...


class JsonQueueSerializer(QueueSerializer):
    def serialize_messages(self, batch):
        out = []
        for it in _rows_of(batch):
            key = json.dumps(
                {c.name: it.value(c.name)
                 for c in (it.table_schema.key_columns()
                           if it.table_schema else [])},
                separators=(",", ":"), default=_json_default,
            ).encode()
            value = json.dumps(it.as_dict(), separators=(",", ":"),
                               default=_json_default).encode()
            out.append((key, value))
        return out


class NativeQueueSerializer(QueueSerializer):
    def serialize_messages(self, batch):
        return [
            (str(it.table_id).encode(),
             json.dumps(it.to_json(), separators=(",", ":"),
                        default=_json_default).encode())
            for it in _rows_of(batch)
        ]


class DebeziumQueueSerializer(QueueSerializer):
    """config: emitter params + snapshot: bool (emits op 'r' instead of 'c'
    for initial-load rows, Debezium's snapshot-read marker)."""

    def __init__(self, snapshot: bool = False, **cfg):
        from transferia_tpu.debezium import DebeziumEmitter

        self.emitter = DebeziumEmitter(**cfg)
        self.snapshot = snapshot

    def serialize_messages(self, batch):
        return self.emitter.emit_batch(batch, snapshot=self.snapshot)


class MirrorQueueSerializer(QueueSerializer):
    """Raw pass-through for queue mirroring (queue/mirror: key/data cols
    from the blank parser's RAW_SCHEMA)."""

    def serialize_messages(self, batch):
        out = []
        for it in _rows_of(batch):
            key = it.value("key") or b""
            data = it.value("data") or b""
            if isinstance(key, str):
                key = key.encode()
            if isinstance(data, str):
                data = data.encode()
            out.append((key, data))
        return out


_SERIALIZERS = {
    "json": JsonSerializer,
    "csv": CsvSerializer,
    "parquet": ParquetSerializer,
    "raw": RawSerializer,
}

def _raw_column_queue_serializer(**cfg):
    from transferia_tpu.serializers.batch import RawColumnQueueSerializer

    return RawColumnQueueSerializer(**cfg)


_QUEUE_SERIALIZERS = {
    "json": JsonQueueSerializer,
    "native": NativeQueueSerializer,
    "debezium": DebeziumQueueSerializer,
    "mirror": MirrorQueueSerializer,
    "raw_column": _raw_column_queue_serializer,
}


def make_serializer(fmt: str, concurrency: int = 1,
                    threshold: int = 0, **cfg) -> BatchSerializer:
    """Build a serializer; concurrency > 1 wraps row-shaped formats in the
    threshold-gated parallel chunker (batch.go:28).  Parquet is a
    whole-file format and is never wrapped."""
    if fmt not in _SERIALIZERS:
        raise KeyError(
            f"unknown serializer {fmt!r}; known: {sorted(_SERIALIZERS)}"
        )
    inner = _SERIALIZERS[fmt](**cfg)
    # whole-file formats and headered csv must not be chunk-concatenated
    # (every chunk would re-emit the header mid-file)
    unwrappable = fmt == "parquet" or (fmt == "csv" and cfg.get("header"))
    if concurrency > 1 and not unwrappable:
        from transferia_tpu.serializers.batch import (
            DEFAULT_THRESHOLD,
            ConcurrentBatchSerializer,
        )

        return ConcurrentBatchSerializer(
            inner, concurrency=concurrency,
            threshold=threshold or DEFAULT_THRESHOLD)
    return inner


def make_queue_serializer(fmt: str, threads: int = 1,
                          threshold: int = 0, **cfg) -> QueueSerializer:
    """Build a queue serializer; threads > 1 returns the ordered parallel
    wrapper with one inner serializer per worker
    (queue/debezium_multithreading.go)."""
    if fmt not in _QUEUE_SERIALIZERS:
        raise KeyError(
            f"unknown queue serializer {fmt!r}; known: "
            f"{sorted(_QUEUE_SERIALIZERS)}"
        )
    if threads > 1:
        from transferia_tpu.serializers.batch import (
            DEFAULT_THRESHOLD,
            ConcurrentQueueSerializer,
        )

        return ConcurrentQueueSerializer(
            lambda: _QUEUE_SERIALIZERS[fmt](**cfg),
            concurrency=threads,
            threshold=threshold or DEFAULT_THRESHOLD)
    return _QUEUE_SERIALIZERS[fmt](**cfg)
