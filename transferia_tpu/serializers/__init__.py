"""Serializers: batches -> bytes (reference: pkg/serializer/ + queue/).

Two shapes: `BatchSerializer.serialize(batch) -> bytes` for object/file
sinks (json/csv/parquet/raw), and `QueueSerializer.serialize_messages(batch)
-> [(key, value)]` for message-broker sinks (debezium/json/native/mirror),
mirroring serializer/interface.go and serializer/queue/*.
"""

from transferia_tpu.serializers.batch import (
    BufferPool,
    ConcurrentBatchSerializer,
    ConcurrentQueueSerializer,
    RawColumnQueueSerializer,
)
from transferia_tpu.serializers.formats import (
    BatchSerializer,
    CsvSerializer,
    JsonSerializer,
    ParquetSerializer,
    QueueSerializer,
    RawSerializer,
    make_serializer,
    make_queue_serializer,
)

__all__ = [
    "BatchSerializer",
    "BufferPool",
    "ConcurrentBatchSerializer",
    "ConcurrentQueueSerializer",
    "CsvSerializer",
    "JsonSerializer",
    "ParquetSerializer",
    "QueueSerializer",
    "RawColumnQueueSerializer",
    "RawSerializer",
    "make_serializer",
    "make_queue_serializer",
]
