"""Predicate AST nodes (pkg/predicate/ast.go)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence, Union

Literal = Union[int, float, str, bool, None]


class Node:
    """Base predicate node."""

    def columns(self) -> set[str]:
        raise NotImplementedError


@dataclass(frozen=True)
class Cmp(Node):
    """column <op> literal; op in = != < <= > >= ~ (LIKE)."""

    column: str
    op: str
    value: Literal

    def columns(self) -> set[str]:
        return {self.column}


@dataclass(frozen=True)
class InList(Node):
    column: str
    values: tuple[Literal, ...]
    negate: bool = False

    def columns(self) -> set[str]:
        return {self.column}


@dataclass(frozen=True)
class IsNull(Node):
    column: str
    negate: bool = False  # True => IS NOT NULL

    def columns(self) -> set[str]:
        return {self.column}


@dataclass(frozen=True)
class Between(Node):
    column: str
    low: Literal
    high: Literal

    def columns(self) -> set[str]:
        return {self.column}


@dataclass(frozen=True)
class And(Node):
    parts: tuple[Node, ...]

    def columns(self) -> set[str]:
        return set().union(*(p.columns() for p in self.parts))


@dataclass(frozen=True)
class Or(Node):
    parts: tuple[Node, ...]

    def columns(self) -> set[str]:
        return set().union(*(p.columns() for p in self.parts))


@dataclass(frozen=True)
class Not(Node):
    inner: Node

    def columns(self) -> set[str]:
        return self.inner.columns()


@dataclass(frozen=True)
class TrueNode(Node):
    def columns(self) -> set[str]:
        return set()
